"""ANN candidate-generation indexes: the retrieval subsystem.

Until this subsystem, "recommend" meant SCORING — every serve path
(``ops.topk``, the templates' predict methods) ranks candidates the
caller already has. Candidate GENERATION at catalog scale ("user ->
top-k of millions of items", "item -> top-k similar items") is what the
reference's MLlib ancestry never had (ALS serving ends at
``predict(user, item)``) and what this package adds:

  :class:`AnnIndex`     the one retrieval interface every backend
                        implements: ``build`` / ``search`` / ``upsert``
                        / ``stats``.
  ``index/exact.py``    exact on-device retrieval: a fused Pallas
                        dot+top-k kernel (``ops/pallas/topk_dot.py`` —
                        item table streamed through VMEM in tiles,
                        never a [B, I] logits matrix in HBM) with the
                        XLA brute-force scorer (``ops.topk``) as the
                        reference and fallback.
  ``index/ivf.py``      approximate CPU fallback: k-means coarse
                        quantizer + ``nprobe`` inverted-list search,
                        optional int8 per-dim quantization — gated at
                        build time by measured recall@k against brute
                        force (``PIO_INDEX_RECALL_FLOOR``, default
                        0.95).
  ``index/recall.py``   recall@k measurement vs brute force — the
                        equivalence currency of the whole subsystem
                        (bench gates, IVF build gate, the streaming
                        drift probe in workflow/stream.py).

Models expose ``retrieval_index()`` (ALS / two-tower / similarproduct
share the factor-table container); the engine server builds and warms
the index at model load, and the streaming ``POST /model/patch`` lane
lands fold-in rows in the index via ``upsert`` — freshness reaches
retrieval, not just scoring.

Backend selection: ``make_index(vectors, backend=...)`` with
``PIO_INDEX_BACKEND`` (``auto`` | ``exact`` | ``ivf``) overriding the
argument for bench A/B. ``auto`` = exact: on an accelerator the fused
kernel IS the fast path, and on CPU the exact fallback is still the
correct default — IVF is the explicit opt-in for host-only serving of
catalogs where brute force can't hold latency.
"""

from __future__ import annotations

import abc
import os
from typing import Dict, Optional, Tuple

import numpy as np

from predictionio_tpu.obs import metrics

BUILD_SECONDS = metrics.gauge(
    "pio_index_build_seconds",
    "Wall seconds of the last ANN index build, per backend",
    ("backend",),
)
SIZE_ITEMS = metrics.gauge(
    "pio_index_size_items",
    "Items currently held by the ANN index, per backend",
    ("backend",),
)
QUERIES_TOTAL = metrics.counter(
    "pio_index_queries_total",
    "ANN index search calls, per backend",
    ("backend",),
)
MEASURED_RECALL = metrics.gauge(
    "pio_index_recall",
    "Last measured recall@k of the index against brute force, per "
    "backend (exact backends pin 1.0; IVF measures at build)",
    ("backend",),
)

BACKENDS = ("exact", "ivf")


class AnnIndex(abc.ABC):
    """One retrieval index over a ``[I, D]`` float32 vector table.

    Contract shared by every backend:

      - ``search`` scores by DOT PRODUCT (cosine when the caller's
        table is row-normalized — two-tower towers are, ALS factors are
        not) and returns ``(scores [B, k], idx [B, k])`` with masked /
        unfillable slots at ``score <= NEG_INF`` — identical to the
        ``ops.topk`` scorer's contract, because that scorer IS the
        equivalence reference;
      - ``exclude`` entries are row indices (-1 padded, per the
        ``ops.topk`` wire format) or None;
      - ``upsert`` lands streaming fold-in rows (overwrite existing
        rows, append brand-new ones) without a rebuild — the
        ``POST /model/patch`` freshness lane ends here;
      - ``stats()`` is the operator surface (engine-server status page,
        bench detail).
    """

    backend: str = "abstract"

    #: device-memory ledger attribution (obs/memacct.py): the owning
    #: model sets this to ITS label before build, so the index's bytes
    #: land under pio_model_device_bytes{model=<owner>,component=index}
    mem_model: Optional[str] = None

    @abc.abstractmethod
    def build(self, item_vectors: np.ndarray) -> None:
        """(Re)build over the full table; records build metrics."""

    @abc.abstractmethod
    def search(self, query_vecs: np.ndarray, k: int,
               exclude: Optional[np.ndarray] = None,
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-``k`` rows by dot product -> (scores [B,k], idx [B,k])."""

    @abc.abstractmethod
    def upsert(self, rows: np.ndarray, vectors: np.ndarray) -> None:
        """Overwrite (or append, when ``rows == len(index)``) the given
        row indices with new vectors — the streaming patch lane."""

    @abc.abstractmethod
    def __len__(self) -> int:
        ...

    def stats(self) -> Dict[str, object]:
        return {"backend": self.backend, "size": len(self)}

    # -- shared bookkeeping ---------------------------------------------------
    def _note_build(self, seconds: float) -> None:
        BUILD_SECONDS.labels(self.backend).set(seconds)
        SIZE_ITEMS.labels(self.backend).set(float(len(self)))

    def _register_mem(self, nbytes: int) -> None:
        """Price this index's resident tables in the device-memory
        ledger (obs/memacct.py) — build/upsert/device-copy seams call
        it with their current total, re-pricing under the same owner."""
        from predictionio_tpu.obs import memacct

        memacct.LEDGER.register(
            self, self.mem_model or f"index:{self.backend}", "index",
            int(nbytes))

    def _note_query(self) -> None:
        QUERIES_TOTAL.labels(self.backend).inc()


def resolve_backend(backend: Optional[str] = None) -> str:
    """``PIO_INDEX_BACKEND`` beats the argument (bench A/B without code
    changes, same stance as the kernel flags); ``auto`` -> exact."""
    value = os.environ.get("PIO_INDEX_BACKEND") or backend or "auto"
    value = str(value).strip().lower()
    if value in ("auto", ""):
        return "exact"
    if value not in BACKENDS:
        raise ValueError(
            f"unknown index backend {value!r} — one of auto/exact/ivf")
    return value


def make_index(item_vectors: Optional[np.ndarray] = None,
               backend: Optional[str] = None,
               kernel: str = "auto",
               **kwargs) -> AnnIndex:
    """Build an index over ``item_vectors`` (or an empty one to fill
    later). ``kernel`` is the exact backend's Pallas flag
    (``index_kernel`` on the model params: on/off/auto, env
    ``PIO_INDEX_KERNEL`` overrides — exactly like ``flash_ce_kernel``)."""
    name = resolve_backend(backend)
    if name == "exact":
        from predictionio_tpu.index.exact import ExactIndex

        index: AnnIndex = ExactIndex(kernel=kernel, **kwargs)
    else:
        from predictionio_tpu.index.ivf import IVFIndex

        index = IVFIndex(**kwargs)
    if item_vectors is not None:
        index.build(np.asarray(item_vectors, np.float32))
    return index


__all__ = [
    "AnnIndex",
    "BACKENDS",
    "make_index",
    "resolve_backend",
    "BUILD_SECONDS",
    "SIZE_ITEMS",
    "QUERIES_TOTAL",
    "MEASURED_RECALL",
]
