"""IVF: approximate CPU retrieval — k-means coarse quantizer + nprobe.

The host-only fallback for catalogs where brute force can't hold
serving latency without an accelerator: items are partitioned into
``nlist`` inverted lists by a k-means coarse quantizer; a query scores
the ``nprobe`` nearest lists' members only (classic IVF-Flat), with
optional per-dimension int8 quantization of the stored vectors
(IVF-SQ8: 4x less memory traffic on the scan, plus a full-precision
re-rank of the top ~4k shortlist so quantization error can't cost
recall at the k-th boundary).

Approximation is GATED, not assumed: ``build`` measures recall@k
against brute force on a sample of self-queries and raises ``nprobe``
until the measured recall clears ``PIO_INDEX_RECALL_FLOOR`` (default
0.95) or every list is probed (== brute force). The measured value is
exported on the ``pio_index_recall{backend="ivf"}`` gauge and in
``stats()`` — an operator never has to take the approximation on
faith, and the bench's ``retrieval_qps_recall95`` key only counts
configurations that cleared the floor.

Everything here is numpy partial-sorts (``np.argpartition``) — the
graftlint JT14 rule exists precisely because a stray ``argsort(...)[:k]``
on this path would silently pay O(n log n) per query.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.index import AnnIndex, MEASURED_RECALL
from predictionio_tpu.obs import metrics
from predictionio_tpu.ops.topk import NEG_INF

log = logging.getLogger(__name__)

#: recall@k floor the build-time autotune must clear (vs brute force)
RECALL_FLOOR_ENV = "PIO_INDEX_RECALL_FLOOR"
DEFAULT_RECALL_FLOOR = 0.95


def _kmeans(vectors: np.ndarray, nlist: int, iters: int, seed: int
            ) -> np.ndarray:
    """Lloyd's k-means on (a sample of) the vectors -> [nlist, D]
    centroids. Assignment by the expanded-L2 trick (argmax of
    v.c - |c|^2/2) so each iteration is one matmul + argmax."""
    rng = np.random.default_rng(seed)
    n = vectors.shape[0]
    sample = vectors
    if n > 20_000:
        sample = vectors[rng.choice(n, 20_000, replace=False)]
    pick = rng.choice(sample.shape[0], nlist, replace=False)
    centroids = sample[pick].copy()
    for _ in range(iters):
        assign = _assign(sample, centroids)
        for c in range(nlist):
            members = sample[assign == c]
            if len(members):
                centroids[c] = members.mean(axis=0)
            else:
                # dead list: reseed on a random vector so capacity
                # isn't silently wasted
                centroids[c] = sample[rng.integers(sample.shape[0])]
    return centroids


def _assign(vectors: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Nearest centroid per vector under L2 -> [n] int32."""
    # argmin ||v - c||^2 == argmax (v.c - |c|^2 / 2); one GEMM
    logits = vectors @ centroids.T - 0.5 * (centroids ** 2).sum(axis=1)
    return np.argmax(logits, axis=1).astype(np.int32)


def _partial_topk(scores: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """(top-k scores desc, their positions) over a 1-D score vector —
    the reference scorer's partial-sort idiom (argpartition +
    canonicalize + stable rank), one row at a time."""
    from predictionio_tpu.ops.topk import TopKScorer

    k = min(k, scores.shape[0])
    if k <= 0:
        return np.zeros(0, np.float32), np.zeros(0, np.int64)
    s, i = TopKScorer._host_topk(scores[None, :], k)
    return s[0], i[0]


class IVFIndex(AnnIndex):
    """IVF-Flat / IVF-SQ8 over a host vector table."""

    backend = "ivf"

    def __init__(self, nlist: Optional[int] = None,
                 nprobe: Optional[int] = None,
                 quantize: Optional[str] = None,
                 kmeans_iters: int = 8, seed: int = 17,
                 recall_floor: Optional[float] = None,
                 recall_sample: int = 64, recall_k: int = 10):
        self.nlist = nlist if nlist is None else int(nlist)
        self.nprobe = nprobe if nprobe is None else int(nprobe)
        import os

        if quantize is None:
            quantize = os.environ.get("PIO_INDEX_QUANT", "off")
        self.quantize = str(quantize).strip().lower() in ("int8", "1",
                                                          "on", "true")
        self.kmeans_iters = int(kmeans_iters)
        self.seed = int(seed)
        self.recall_floor = (
            recall_floor if recall_floor is not None
            else metrics.env_float(RECALL_FLOOR_ENV, DEFAULT_RECALL_FLOOR))
        self.recall_sample = int(recall_sample)
        self.recall_k = int(recall_k)
        self._lock = threading.Lock()
        self._vectors = np.zeros((0, 1), np.float32)
        self._centroids: Optional[np.ndarray] = None
        self._lists: List[np.ndarray] = []
        self._codes: Optional[np.ndarray] = None   # int8 [I, D]
        self._scale: Optional[np.ndarray] = None   # f32 [D]
        self.measured_recall: Optional[float] = None
        self.build_seconds = 0.0
        self.searches = 0

    # -- build ----------------------------------------------------------------
    def build(self, item_vectors: np.ndarray) -> None:
        t0 = time.perf_counter()
        vectors = np.ascontiguousarray(item_vectors, dtype=np.float32)
        n = vectors.shape[0]
        with self._lock:
            self._vectors = vectors
            if n == 0:
                self._centroids, self._lists = None, []
                self._codes = self._scale = None
                self.measured_recall = 1.0
            else:
                nlist = self.nlist or max(1, min(
                    int(round(np.sqrt(n))), n, 4096))
                nlist = min(nlist, n)
                self._centroids = _kmeans(vectors, nlist,
                                          self.kmeans_iters, self.seed)
                assign = _assign(vectors, self._centroids)
                self._lists = [
                    np.flatnonzero(assign == c).astype(np.int64)
                    for c in range(nlist)]
                self._requantize()
        if n:
            self._autotune_nprobe()
        self.build_seconds = time.perf_counter() - t0
        self._note_build(self.build_seconds)
        self._register_mem(self._mem_nbytes())
        if self.measured_recall is not None:
            MEASURED_RECALL.labels(self.backend).set(self.measured_recall)

    def _requantize(self) -> None:
        if not self.quantize:
            self._codes = self._scale = None
            return
        v = self._vectors
        self._scale = np.maximum(np.abs(v).max(axis=0), 1e-12) / 127.0
        self._codes = np.clip(np.round(v / self._scale), -127, 127
                              ).astype(np.int8)

    def _autotune_nprobe(self) -> None:
        """Raise nprobe until sampled recall@k vs brute force clears
        the floor (or every list is probed — exact). An explicitly
        configured nprobe is still MEASURED (the gauge must tell the
        truth) but never overridden."""
        from predictionio_tpu.index.recall import recall_at_k

        rng = np.random.default_rng(self.seed + 1)
        n = self._vectors.shape[0]
        sample = self._vectors[
            rng.choice(n, min(self.recall_sample, n), replace=False)]
        k = min(self.recall_k, n)
        if self.nprobe is not None:
            self.measured_recall = recall_at_k(
                self, sample, k, vectors=self._vectors)
            return
        nprobe = 1
        nlist = len(self._lists)
        while True:
            self.nprobe = nprobe
            self.measured_recall = recall_at_k(
                self, sample, k, vectors=self._vectors)
            if self.measured_recall >= self.recall_floor or nprobe >= nlist:
                break
            nprobe = min(nprobe * 2, nlist)
        if self.measured_recall < self.recall_floor:
            log.warning(
                "ivf index recall@%d %.3f below floor %.2f even at "
                "nprobe=nlist=%d — vectors may be degenerate",
                k, self.measured_recall, self.recall_floor, nlist)

    # -- upsert ---------------------------------------------------------------
    def upsert(self, rows: np.ndarray, vectors: np.ndarray) -> None:
        rows = np.asarray(rows, np.int64).ravel()
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        if len(rows) == 0:
            return
        if self._centroids is None:
            # first rows into an empty index: a real build (and its
            # recall gate) is the only honest path
            table = np.zeros((int(rows.max()) + 1, vectors.shape[1]),
                             np.float32)
            table[rows] = vectors
            self.build(table)
            return
        with self._lock:
            table = self._vectors
            n, d = table.shape
            grow = int(rows.max()) + 1 - n
            if grow > 0:
                table = np.vstack([table, np.zeros((grow, d), np.float32)])
            else:
                table = table.copy()
            table[rows] = vectors
            self._vectors = table
            # re-list the touched rows under the FIXED quantizer (the
            # standard IVF upsert: centroids move only on rebuild)
            new_assign = _assign(vectors, self._centroids)
            self._lists = [
                lst[~np.isin(lst, rows)] for lst in self._lists]
            for r, c in zip(rows, new_assign):
                self._lists[int(c)] = np.append(self._lists[int(c)], r)
            if self.quantize:
                # per-dim scales track the global max — recompute from
                # the updated table so a hot new row can't clip
                self._requantize()
            self._note_build(self.build_seconds)
        self._register_mem(self._mem_nbytes())

    def __len__(self) -> int:
        return int(self._vectors.shape[0])

    def _mem_nbytes(self) -> int:
        """Resident bytes: full-precision table + coarse quantizer +
        (when int8 is on) the code table."""
        total = int(self._vectors.nbytes)
        for arr in (self._centroids, self._codes, self._scale):
            if arr is not None:
                total += int(arr.nbytes)
        return total

    @property
    def vectors(self) -> np.ndarray:
        return self._vectors

    # -- search ---------------------------------------------------------------
    def _row_scores(self, q: np.ndarray, cand: np.ndarray) -> np.ndarray:
        if self.quantize:
            return (self._codes[cand].astype(np.float32)
                    * self._scale) @ q
        return self._vectors[cand] @ q

    def search(self, query_vecs: np.ndarray, k: int,
               exclude: Optional[np.ndarray] = None,
               ) -> Tuple[np.ndarray, np.ndarray]:
        self._note_query()
        self.searches += 1
        q = np.atleast_2d(np.asarray(query_vecs, np.float32))
        B = q.shape[0]
        n = len(self)
        if n == 0:
            return (np.zeros((B, 0), np.float32),
                    np.zeros((B, 0), np.int32))
        k = min(int(k), n)
        with self._lock:
            centroids, lists = self._centroids, self._lists
        nprobe = min(self.nprobe or 1, len(lists))
        excl = None
        if exclude is not None:
            excl = np.atleast_2d(np.asarray(exclude, np.int64))
            if excl.shape[0] == 1 and B > 1:
                excl = np.broadcast_to(excl, (B, excl.shape[1]))
        out_s = np.full((B, k), float(NEG_INF), np.float32)
        out_i = np.full((B, k), -1, np.int32)
        cent_scores = q @ centroids.T          # [B, nlist]
        for b in range(B):
            _, probe_lists = _partial_topk(cent_scores[b], nprobe)
            cand = np.concatenate([lists[int(c)] for c in probe_lists]) \
                if len(probe_lists) else np.zeros(0, np.int64)
            if cand.size == 0:
                continue
            scores = self._row_scores(q[b], cand)
            drop = np.zeros(0, np.int64)
            if excl is not None:
                drop = excl[b]
                drop = drop[(drop >= 0) & (drop < n)]
                if drop.size:
                    scores = np.where(np.isin(cand, drop),
                                      float(NEG_INF), scores)
            if self.quantize:
                # SQ8-with-refine: the int8 scan picks a shortlist, a
                # full-precision re-rank of the top ~4k fixes the
                # orderings quantization flipped at the k-th boundary
                # (without it measured recall stalls ~0.93 on the
                # tier-1 fixture)
                m = min(scores.shape[0], max(4 * k, 32))
                _, pos = _partial_topk(scores, m)
                shortlist = cand[pos]
                rescored = self._vectors[shortlist] @ q[b]
                if drop.size:
                    rescored = np.where(np.isin(shortlist, drop),
                                        float(NEG_INF), rescored)
                s, pos2 = _partial_topk(rescored, k)
                out_s[b, :len(s)] = s
                out_i[b, :len(s)] = shortlist[pos2]
            else:
                s, pos = _partial_topk(scores, k)
                out_s[b, :len(s)] = s
                out_i[b, :len(s)] = cand[pos]
        return out_s, out_i

    def stats(self) -> Dict[str, object]:
        out = super().stats()
        out.update({
            "nlist": len(self._lists),
            "nprobe": self.nprobe,
            "quantize": "int8" if self.quantize else "off",
            "measured_recall": (None if self.measured_recall is None
                                else round(self.measured_recall, 4)),
            "recall_floor": self.recall_floor,
            "build_seconds": round(self.build_seconds, 4),
            "searches": self.searches,
        })
        return out
