"""Recall@k against brute force — the subsystem's equivalence currency.

Every approximate (or patched) index in this package is judged by one
number: of the true top-k items under exact dot-product scoring, what
fraction did the index return? The IVF build gate, the bench's
``retrieval_qps_recall95`` key and the streaming drift probe
(``pio_stream_index_recall``) all call :func:`recall_at_k` so they can
never disagree about what "recall" means.

Ties are handled the only honest way: a retrieved item counts if its
TRUE score is >= the k-th true score (minus a float epsilon), so an
index returning a different-but-equal-scoring item is not punished for
the arbitrary half of a tie.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def brute_force_topk(vectors: np.ndarray, queries: np.ndarray, k: int):
    """(scores [B, k], idx [B, k]) by exact dot product — ONE matmul
    into the reference scorer's partial-sort (``TopKScorer._host_topk``
    owns the argpartition + canonicalize + stable-rank idiom; a copy
    here could drift from the thing recall is measured against)."""
    from predictionio_tpu.ops.topk import TopKScorer

    queries = np.atleast_2d(np.asarray(queries, np.float32))
    scores = queries @ np.asarray(vectors, np.float32).T    # [B, I]
    k = min(int(k), scores.shape[1])
    if k <= 0:
        return (np.zeros((queries.shape[0], 0), np.float32),
                np.zeros((queries.shape[0], 0), np.int64))
    return TopKScorer._host_topk(scores, k)


def recall_at_k(index, queries: np.ndarray, k: int,
                vectors: Optional[np.ndarray] = None,
                eps: float = 1e-6) -> float:
    """Mean recall@k of ``index.search`` vs brute force over
    ``vectors`` (default: the index's own table — pass the
    authoritative factor table when probing a PATCHED index for
    drift)."""
    queries = np.atleast_2d(np.asarray(queries, np.float32))
    if vectors is None:
        vectors = index.vectors
    vectors = np.asarray(vectors, np.float32)
    n = vectors.shape[0]
    k = min(int(k), n)
    if k == 0 or queries.shape[0] == 0:
        return 1.0
    true_s, _ = brute_force_topk(vectors, queries, k)
    _, got_i = index.search(queries, k)
    hits = 0
    total = queries.shape[0] * k
    for b in range(queries.shape[0]):
        kth = true_s[b, -1]
        got = got_i[b]
        got = got[(got >= 0) & (got < n)]
        if got.size == 0:
            continue
        got_true_scores = vectors[got] @ queries[b]
        hits += int(np.sum(got_true_scores >= kth - eps))
    return hits / total
