"""Exact on-device retrieval: fused Pallas dot+top-k, XLA fallback.

The hot path is ``ops/pallas/topk_dot.py`` — the item table streamed
through VMEM in tiles, MXU partial dots, a running [B, k] top-k merged
per tile; the full [B, I] logits matrix never exists in HBM. The XLA
brute-force scorer (``ops.topk.TopKScorer``) remains the numerical
reference and the fallback everywhere the kernel is ineligible or its
Mosaic probe fails — the ``ops/pallas`` design contract, applied to
serving instead of training.

Kernel selection mirrors ``flash_ce_kernel`` exactly: a per-index
``kernel`` flag ("auto"/"on"/"off", wired from the model params'
``index_kernel``), the ``PIO_INDEX_KERNEL`` env override, ``auto``
engaging only on a real TPU backend, probe-guarded with per-shape
smoke compiles, and interpret mode for CPU tier-1 equivalence tests.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from predictionio_tpu.index import AnnIndex, MEASURED_RECALL
from predictionio_tpu.ops import pallas as plk

log = logging.getLogger(__name__)


class ExactIndex(AnnIndex):
    """Exact top-k by dot product over the full table.

    Results are pinned to ``ops.topk.TopKScorer.score`` (identical
    scores; identical indices modulo exact score ties when the Pallas
    kernel is engaged — tests/test_index.py).
    """

    backend = "exact"

    def __init__(self, kernel: str = "auto", max_exclude: int = 64,
                 block_items: Optional[int] = None,
                 placement: Optional[str] = None):
        from predictionio_tpu.ops.pallas import topk_dot as tkd

        self.kernel_flag = kernel
        self.max_exclude = int(max_exclude)
        self.block_items = int(block_items or tkd.BLOCK_ITEMS)
        self._placement = placement
        self._scorer = None          # lazy TopKScorer fallback
        self._vectors = np.zeros((0, 1), np.float32)
        self._device_padded = None   # device copy padded to the tile
        self._fns: Dict[Tuple[int, int, int], object] = {}
        self._lock = threading.Lock()
        self.kernel_plan: Dict[str, object] = {"engaged": False,
                                               "reason": "no build yet"}
        self.build_seconds = 0.0
        self.searches = 0

    # -- build / upsert -------------------------------------------------------
    def build(self, item_vectors: np.ndarray) -> None:
        t0 = time.perf_counter()
        with self._lock:
            self._vectors = np.ascontiguousarray(item_vectors,
                                                 dtype=np.float32)
            self._scorer = None
            self._device_padded = None
            self._fns.clear()
            self._plan_kernel()
        self.build_seconds = time.perf_counter() - t0
        self._note_build(self.build_seconds)
        self._register_mem(self._mem_nbytes())
        MEASURED_RECALL.labels(self.backend).set(1.0)  # exact by design

    def upsert(self, rows: np.ndarray, vectors: np.ndarray) -> None:
        """Overwrite/append rows copy-on-write: readers of
        ``self._vectors`` see old-or-new tables, never torn rows — the
        same publication discipline as ``ALSModel.upsert_rows``, which
        is this method's only production caller."""
        rows = np.asarray(rows, np.int64).ravel()
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        if len(rows) == 0:
            return
        with self._lock:
            table = self._vectors
            n, d = table.shape if table.size else (0, vectors.shape[1])
            grow = int(rows.max()) + 1 - n if rows.size else 0
            if grow > 0:
                table = np.vstack(
                    [table.reshape(n, d),
                     np.zeros((grow, d), np.float32)])
            else:
                table = table.copy()
            table[rows] = vectors
            self._vectors = table
            # the kernel/fallback paths hold device copies of the OLD
            # table; drop them — a same-shape re-put hits the compile
            # cache, only appends change shapes
            self._scorer = None
            self._device_padded = None
            if grow > 0:
                self._fns.clear()   # n_items is a static kernel arg
            self._note_build(self.build_seconds)
        self._register_mem(self._mem_nbytes())

    def __len__(self) -> int:
        return int(self._vectors.shape[0])

    def _mem_nbytes(self) -> int:
        """Resident bytes this index owns: the host table plus, once
        materialized, the tile-padded device copy the kernel streams."""
        padded = self._device_padded
        return int(self._vectors.nbytes
                   + (padded.nbytes if padded is not None else 0))

    @property
    def vectors(self) -> np.ndarray:
        return self._vectors

    # -- kernel selection -----------------------------------------------------
    def _plan_kernel(self) -> None:
        import jax

        interpret = plk.interpret_mode()
        n = self._vectors.shape[0]
        eligible = n > 0
        reason = "empty table" if not eligible else ""
        engaged, why = plk.decide(
            self.kernel_flag, "PIO_INDEX_KERNEL",
            eligible=eligible, ineligible_reason=reason,
            auto_default=jax.default_backend() == "tpu",
        )
        self.kernel_plan = {"engaged": engaged, "reason": why,
                            "interpret": interpret}

    def _kernel_eligible(self, B: int, E: int, k: int) -> bool:
        from predictionio_tpu.ops.pallas import topk_dot as tkd

        return (bool(self.kernel_plan.get("engaged"))
                and B <= tkd.MAX_BATCH and E <= tkd.MAX_EXCLUDE
                and k <= tkd.MAX_K and k <= len(self))

    def _fn(self, B: int, E: int, k: int):
        from predictionio_tpu.ops.pallas import topk_dot as tkd

        key = (B, E, k)
        fn = self._fns.get(key)
        if fn is None:
            n, d = self._vectors.shape
            interpret = bool(self.kernel_plan.get("interpret"))
            if not interpret and not plk.probe(
                    f"topk_dot:{n}x{d}:B{B}E{E}k{k}",
                    lambda: tkd.smoke_at(n, d, B, k, E,
                                         block_items=self.block_items)):
                self._fns[key] = False   # this shape degraded to XLA
                return False
            fn = tkd.make_topk_dot(n, d, B, k, E,
                                   block_items=self.block_items,
                                   interpret=interpret)
            self._fns[key] = fn
        return fn

    def _device_items(self):
        from predictionio_tpu.ops.pallas import topk_dot as tkd
        import jax.numpy as jnp

        # read-once: a concurrent upsert nulls the cache mid-call (the
        # patch lane runs while queries are in flight); the local ref
        # keeps this search on a consistent (old-or-new) table
        padded = self._device_padded
        if padded is None:
            padded = tkd.pad_items(jnp.asarray(self._vectors),
                                   self.block_items)
            self._device_padded = padded  # graftlint: disable=JT18 — lock-free lazy init by design: the store is atomic, racing fills compute identical tables and the last write wins; readers above took one local ref
            # a NEW long-lived device allocation: re-price the ledger
            # footprint with the padded copy included (JT16 contract)
            self._register_mem(self._mem_nbytes())
        return padded

    def _fallback(self):
        from predictionio_tpu.ops.topk import TopKScorer

        scorer = self._scorer
        if scorer is None:
            scorer = TopKScorer(self._vectors,
                                max_exclude=self.max_exclude,
                                placement=self._placement)
            self._scorer = scorer  # graftlint: disable=JT18 — lock-free lazy init by design: racing fills build equivalent scorers over the same read-only vectors; last write wins, readers hold their local ref
        return scorer

    # -- search ---------------------------------------------------------------
    def search(self, query_vecs: np.ndarray, k: int,
               exclude: Optional[np.ndarray] = None,
               ) -> Tuple[np.ndarray, np.ndarray]:
        self._note_query()
        self.searches += 1
        if len(self) == 0:
            B = np.atleast_2d(np.asarray(query_vecs)).shape[0]
            return (np.zeros((B, 0), np.float32),
                    np.zeros((B, 0), np.int32))
        from predictionio_tpu.ops.topk import _prepare_score_inputs

        q2, excl, k_eff, k_bucket, B = _prepare_score_inputs(
            query_vecs, k, exclude, len(self), self.max_exclude)
        if not self._kernel_eligible(q2.shape[0], excl.shape[1], k_bucket):
            return self._fallback().score(query_vecs, k, exclude)
        fn = self._fn(q2.shape[0], excl.shape[1], k_bucket)
        if fn is False:   # probe failed for this shape — XLA fallback
            return self._fallback().score(query_vecs, k, exclude)
        scores, idx = fn(q2, self._device_items(), excl)
        return (np.asarray(scores)[:B, :k_eff],
                np.asarray(idx)[:B, :k_eff])

    def stats(self) -> Dict[str, object]:
        out = super().stats()
        out.update({
            "kernel": dict(self.kernel_plan),
            "build_seconds": round(self.build_seconds, 4),
            "searches": self.searches,
            "max_exclude": self.max_exclude,
        })
        return out
