"""FastEval: prefix-memoized evaluation across EngineParams candidates.

Behavior contract from the reference's FastEvalEngine
(controller/FastEvalEngine.scala:38-330): during tuning, consecutive
EngineParams often share a prefix of the DASE pipeline (same DataSource
params, same Preparator params, ...). FastEval caches each pipeline
stage's result keyed by the params prefix so shared work runs once:

  read_eval      keyed by (data_source_params)
  prepare        keyed by (data_source_params, preparator_params)
  trained models keyed by (+ one algorithm's params)          [per algo]
  batch predict  keyed by the same                            [per algo]
  serving        computed per full params (cheap, not cached)

The reference structures this as workflow objects with pluggable
caches; here it is one wrapper with dict caches, keyed by
params-JSON strings.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, List, Tuple

from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.params import EngineParams, params_to_dict
from predictionio_tpu.parallel.mesh import MeshContext

log = logging.getLogger(__name__)


def _key(*parts) -> str:
    return json.dumps(parts, sort_keys=True, default=str)


def _slot_key(slot) -> Any:
    name, params = slot
    return [name, params_to_dict(params)]


class FastEvalEngineWorkflow:
    """ref: FastEvalEngineWorkflow (FastEvalEngine.scala:38,273)."""

    def __init__(self, engine: Engine, ctx: MeshContext):
        self.engine = engine
        self.ctx = ctx
        self.eval_data_cache: Dict[str, Any] = {}
        self.prepared_cache: Dict[str, Any] = {}
        self.model_cache: Dict[str, Any] = {}
        self.predict_cache: Dict[str, Any] = {}
        # instrumentation for tests + cache-hit logging
        self.counts = {"read": 0, "prepare": 0, "train": 0, "predict": 0,
                       "grid_dispatches": 0}

    # -- stages -------------------------------------------------------------
    def _eval_data(self, ep: EngineParams):
        key = _key(_slot_key(ep.data_source_params))
        if key not in self.eval_data_cache:
            self.counts["read"] += 1
            ds = self.engine.make_data_source(ep)
            self.eval_data_cache[key] = ds.read_eval(self.ctx)
        return self.eval_data_cache[key]

    def _prepared(self, ep: EngineParams):
        key = _key(_slot_key(ep.data_source_params), _slot_key(ep.preparator_params))
        if key not in self.prepared_cache:
            self.counts["prepare"] += 1
            preparator = self.engine.make_preparator(ep)
            folds = self._eval_data(ep)
            self.prepared_cache[key] = [
                (preparator.prepare(self.ctx, td), ei, qa) for td, ei, qa in folds
            ]
        return self.prepared_cache[key]

    def _models(self, ep: EngineParams, algo_slot) -> List[Any]:
        """One model per fold for one algorithm params slot."""
        key = _key(
            _slot_key(ep.data_source_params),
            _slot_key(ep.preparator_params),
            _slot_key(algo_slot),
        )
        if key not in self.model_cache:
            self.counts["train"] += 1
            name, params = algo_slot
            algo = self.engine.algorithm_classes[name].create(params)
            self.model_cache[key] = [
                algo.train(self.ctx, pd) for pd, _ei, _qa in self._prepared(ep)
            ]
        return self.model_cache[key]

    def _predictions(self, ep: EngineParams, algo_slot) -> List[Dict[int, Any]]:
        """Per fold: {query_idx: prediction} for one algorithm."""
        key = _key(
            _slot_key(ep.data_source_params),
            _slot_key(ep.preparator_params),
            _slot_key(algo_slot),
            "predict",
        )
        if key not in self.predict_cache:
            self.counts["predict"] += 1
            name, params = algo_slot
            algo = self.engine.algorithm_classes[name].create(params)
            models = self._models(ep, algo_slot)
            folds = self._prepared(ep)
            per_fold = []
            for model, (_pd, _ei, qa) in zip(models, folds):
                indexed = [(i, q) for i, (q, _a) in enumerate(qa)]
                per_fold.append(dict(algo.batch_predict(model, indexed)))
            self.predict_cache[key] = per_fold
        return self.predict_cache[key]

    def prefetch_grid(self, engine_params_list) -> int:
        """Vmapped grid tuning (ref role: MetricEvaluator over
        engineParamsList, MetricEvaluator.scala:177): when every
        candidate shares the DASE prefix and differs only inside ONE
        algorithm slot whose class offers ``grid_train`` (e.g. ALS reg
        sweeps), all candidates' models are trained in a single
        compiled dispatch per fold and seeded into the model cache —
        the per-candidate eval path then scores them without ever
        calling train. Returns the number of candidates grid-trained
        (0 = shape did not apply; the sequential path runs as before).
        Leaderboard, ranking and best.json are unchanged either way."""
        eps = list(engine_params_list)
        if len(eps) < 2:
            return 0
        base = eps[0]
        prefix = _key(_slot_key(base.data_source_params),
                      _slot_key(base.preparator_params),
                      _slot_key(base.serving_params))
        for ep in eps:
            if (_key(_slot_key(ep.data_source_params),
                     _slot_key(ep.preparator_params),
                     _slot_key(ep.serving_params)) != prefix
                    or len(ep.algorithm_params_list) != 1
                    or ep.algorithm_params_list[0][0]
                    != base.algorithm_params_list[0][0]):
                return 0
        name = base.algorithm_params_list[0][0]
        hook = getattr(self.engine.algorithm_classes[name], "grid_train", None)
        if hook is None:
            return 0
        params_list = [ep.algorithm_params_list[0][1] for ep in eps]
        folds = self._prepared(base)
        per_fold_models = []
        for pd, _ei, _qa in folds:
            models = hook(self.ctx, pd, params_list)
            if models is None:
                return 0  # shape inapplicable (params differ beyond the
                # grid scalar, or a sharded mesh): sequential path
            self.counts["grid_dispatches"] += 1
            per_fold_models.append(models)
        for ci, ep in enumerate(eps):
            key = _key(
                _slot_key(ep.data_source_params),
                _slot_key(ep.preparator_params),
                _slot_key(ep.algorithm_params_list[0]),
            )
            self.model_cache[key] = [fold[ci] for fold in per_fold_models]
        log.info(
            "grid tuning: %d candidates trained in %d dispatch(es) "
            "(one vmapped compile instead of %d sequential trains)",
            len(eps), self.counts["grid_dispatches"],
            len(eps) * len(folds))
        return len(eps)

    # -- public -------------------------------------------------------------
    def eval(self, ep: EngineParams):
        """Same result shape as Engine.eval, with memoized prefixes."""
        serving = self.engine.make_serving(ep)
        folds = self._prepared(ep)
        per_algo = [self._predictions(ep, slot) for slot in ep.algorithm_params_list]
        results = []
        for fold_idx, (_pd, ei, qa) in enumerate(folds):
            qpa = []
            for i, (q, a) in enumerate(qa):
                preds = [algo_preds[fold_idx][i] for algo_preds in per_algo]
                qpa.append((q, serving.serve(q, preds), a))
            results.append((ei, qpa))
        return results


class FastEvalEngine(Engine):
    """Engine whose eval path memoizes across candidates
    (ref: FastEvalEngine.scala:297). Create once, call ``eval`` with
    each candidate EngineParams."""

    def __init__(self, data_source_classes, preparator_classes, algorithm_classes,
                 serving_classes):
        super().__init__(
            data_source_classes, preparator_classes, algorithm_classes, serving_classes
        )
        self._workflow: FastEvalEngineWorkflow = None

    def eval(self, ctx: MeshContext, engine_params: EngineParams, workflow_params=None):
        if self._workflow is None or self._workflow.ctx is not ctx:
            self._workflow = FastEvalEngineWorkflow(self, ctx)
        return self._workflow.eval(engine_params)
