"""k-fold cross-validation splitting (ref: e2/.../evaluation/CrossValidation.scala:20).

Behavior contract: ``split_data`` divides a dataset into ``eval_k``
folds where fold *i*'s test set is the points whose index satisfies
``idx % eval_k == i`` and its training set is everything else
(CommonHelperFunctions.splitData :33-62). Each fold yields
``(training_data, evaluator_info, [(query, actual), ...])`` — the
shape DataSource.read_eval returns to the evaluation harness.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(
    eval_k: int,
    dataset: Sequence[D],
    evaluator_info: EI,
    training_data_creator: Callable[[List[D]], TD],
    query_creator: Callable[[D], Q],
    actual_creator: Callable[[D], A],
) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
    if eval_k < 1:
        raise ValueError("eval_k must be >= 1")
    folds = []
    for fold_idx in range(eval_k):
        training = [d for i, d in enumerate(dataset) if i % eval_k != fold_idx]
        testing = [d for i, d in enumerate(dataset) if i % eval_k == fold_idx]
        folds.append((
            training_data_creator(training),
            evaluator_info,
            [(query_creator(d), actual_creator(d)) for d in testing],
        ))
    return folds
