"""The Engine: named DASE component maps + train/eval orchestration.

Behavior contract from the reference (controller/Engine.scala):

  - an Engine holds *maps* of named component classes per DASE slot
    (Engine.scala:78); an EngineParams picks one name per slot (plus a
    list for algorithms) — together they define a trainable/deployable
    pipeline
  - `train` (object Engine.train:583): read -> sanity-check ->
    [stop-after-read] -> prepare -> sanity-check -> [stop-after-prepare]
    -> train each algorithm -> sanity-check models
  - `eval` (object Engine.eval:688): per fold from readEval, prepare +
    train all algorithms, batch-predict each algorithm over indexed
    queries, regroup per query, serve -> (query, prediction, actual)
  - engine.json variant JSON -> EngineParams
    (Engine.scala jValueToEngineParams:328)
"""

from __future__ import annotations

import abc
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type, Union

from predictionio_tpu.core.controller import (
    Algorithm,
    DataSource,
    Preparator,
    SanityCheck,
    Serving,
)
from predictionio_tpu.core.params import (
    EmptyParams,
    EngineParams,
    Params,
    params_from_dict,
)
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.workflow.config import WorkflowParams

log = logging.getLogger(__name__)

ClassMap = Union[type, Dict[str, type]]


def _as_map(classes: ClassMap) -> Dict[str, type]:
    if isinstance(classes, dict):
        return dict(classes)
    return {"": classes}


def _declared_params_class(cls: type) -> Optional[Type[Params]]:
    """The params dataclass a component declares.

    Resolution order: explicit ``params_class`` attribute, then the type
    annotation of the ctor's ``params`` argument (the analogue of the
    reference reflecting ctor signatures, AbstractDoer.scala:24).
    """
    pc = getattr(cls, "params_class", None)
    if pc is not None:
        return pc
    import typing

    try:
        hints = typing.get_type_hints(cls.__init__)
    except Exception as e:
        # get_type_hints eval()s forward refs, so a user component's
        # annotations can raise anything; fall back to "no declared
        # params class", but say so — a silent None here surfaces later
        # as unvalidated params
        log.warning("cannot resolve type hints on %s.__init__ (%s: %s); "
                    "params dataclass not auto-detected",
                    cls.__name__, type(e).__name__, e)
        return None
    ann = hints.get("params")
    return ann if isinstance(ann, type) else None


def _sanity(obj: Any, wp: WorkflowParams, stage: str) -> None:
    """ref: Engine.scala:610-666 — check TD/PD/models implementing SanityCheck."""
    if wp.skip_sanity_check:
        return
    if isinstance(obj, SanityCheck):
        log.info("sanity check %s", stage)
        obj.sanity_check()


@dataclass
class TrainResult:
    """Outcome of Engine.train — models plus debug-interruption state."""

    models: Optional[List[Any]] = None
    stopped_after: Optional[str] = None  # None | "read" | "prepare"
    training_data: Any = None
    prepared_data: Any = None


class Engine:
    """ref: controller/Engine.scala:78."""

    def __init__(
        self,
        data_source_classes: ClassMap,
        preparator_classes: ClassMap,
        algorithm_classes: ClassMap,
        serving_classes: ClassMap,
    ):
        self.data_source_classes = _as_map(data_source_classes)
        self.preparator_classes = _as_map(preparator_classes)
        self.algorithm_classes = _as_map(algorithm_classes)
        self.serving_classes = _as_map(serving_classes)

    # -- component instantiation (ref: Doer(…) calls in Engine.scala:140-150) --
    def _make(self, classes: Dict[str, type], slot: Tuple[str, Params], role: str):
        name, params = slot
        if name not in classes:
            raise KeyError(
                f"{role} {name!r} not found (available: {sorted(classes)})"
            )
        return classes[name].create(params)

    def make_data_source(self, ep: EngineParams) -> DataSource:
        return self._make(self.data_source_classes, ep.data_source_params, "DataSource")

    def make_preparator(self, ep: EngineParams) -> Preparator:
        return self._make(self.preparator_classes, ep.preparator_params, "Preparator")

    def make_algorithms(self, ep: EngineParams) -> List[Algorithm]:
        if not ep.algorithm_params_list:
            raise ValueError("EngineParams.algorithm_params_list must not be empty")
        return [
            self._make(self.algorithm_classes, slot, "Algorithm")
            for slot in ep.algorithm_params_list
        ]

    def make_serving(self, ep: EngineParams) -> Serving:
        return self._make(self.serving_classes, ep.serving_params, "Serving")

    # -- training (ref: object Engine.train:583) ----------------------------
    def train(
        self,
        ctx: MeshContext,
        engine_params: EngineParams,
        workflow_params: Optional[WorkflowParams] = None,
    ) -> TrainResult:
        import time as _time

        from predictionio_tpu.obs import perfacct

        wp = workflow_params or WorkflowParams()
        data_source = self.make_data_source(engine_params)
        # freshness horizon at read START: an event landing while the
        # scan is in flight may miss the snapshot, so the model is only
        # guaranteed to cover ingests up to this instant — capturing at
        # read end would mark mid-read arrivals as servable when they
        # are not (conservative staleness, never false freshness)
        perfacct.LEDGER.note_train_read()
        t0 = _time.perf_counter()
        td = data_source.read_training(ctx)
        perfacct.LEDGER.note_stage("read", _time.perf_counter() - t0)
        _sanity(td, wp, "training data")
        if wp.stop_after_read:
            return TrainResult(stopped_after="read", training_data=td)

        preparator = self.make_preparator(engine_params)
        t0 = _time.perf_counter()
        pd = preparator.prepare(ctx, td)
        perfacct.LEDGER.note_stage("prepare", _time.perf_counter() - t0)
        _sanity(pd, wp, "prepared data")
        if wp.stop_after_prepare:
            return TrainResult(stopped_after="prepare", training_data=td, prepared_data=pd)

        algorithms = self.make_algorithms(engine_params)
        models = []
        t0 = _time.perf_counter()
        for i, algo in enumerate(algorithms):
            model = algo.train(ctx, pd)  # HOT LOOP (ref: Engine.scala:650)
            _sanity(model, wp, f"model {i}")
            models.append(model)
        perfacct.LEDGER.note_stage("fit", _time.perf_counter() - t0)
        return TrainResult(models=models, training_data=td, prepared_data=pd)

    # -- evaluation (ref: object Engine.eval:688) ---------------------------
    def eval(
        self,
        ctx: MeshContext,
        engine_params: EngineParams,
        workflow_params: Optional[WorkflowParams] = None,
    ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        """Returns per fold: (eval info, [(query, prediction, actual)])."""
        wp = workflow_params or WorkflowParams()
        data_source = self.make_data_source(engine_params)
        preparator = self.make_preparator(engine_params)
        algorithms = self.make_algorithms(engine_params)
        serving = self.make_serving(engine_params)

        eval_data = data_source.read_eval(ctx)
        results = []
        for td, ei, qa_pairs in eval_data:
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algorithms]
            indexed_queries = [(i, q) for i, (q, _a) in enumerate(qa_pairs)]
            # per-algo batch predict, regrouped per query index
            # (ref: Engine.scala:737-750 union + groupByKey)
            per_query: Dict[int, List[Any]] = {i: [] for i, _ in indexed_queries}
            for algo, model in zip(algorithms, models):
                for i, p in algo.batch_predict(model, indexed_queries):
                    per_query[i].append(p)
            qpa = [
                (q, serving.serve(q, per_query[i]), a)
                for i, (q, a) in enumerate(qa_pairs)
            ]
            results.append((ei, qpa))
        return results

    # -- variant JSON -> EngineParams (ref: Engine.jValueToEngineParams:328) --
    def engine_params_from_variant(self, variant: Dict[str, Any]) -> EngineParams:
        def slot(key: str, classes: Dict[str, type]) -> Tuple[str, Params]:
            block = variant.get(key)
            if block is None:
                name = "" if "" in classes else next(iter(sorted(classes)))
                return (name, _materialize(classes, name, {}))
            name = block.get("name", "")
            return (name, _materialize(classes, name, block.get("params")))

        algo_blocks = variant.get("algorithms")
        if algo_blocks is None:
            name = "" if "" in self.algorithm_classes else next(iter(sorted(self.algorithm_classes)))
            algo_list = [(name, _materialize(self.algorithm_classes, name, {}))]
        else:
            algo_list = [
                (
                    b.get("name", ""),
                    _materialize(self.algorithm_classes, b.get("name", ""), b.get("params")),
                )
                for b in algo_blocks
            ]
        return EngineParams(
            data_source_params=slot("datasource", self.data_source_classes),
            preparator_params=slot("preparator", self.preparator_classes),
            algorithm_params_list=algo_list,
            serving_params=slot("serving", self.serving_classes),
        )


def _materialize(classes: Dict[str, type], name: str, params_dict: Optional[dict]) -> Params:
    if name not in classes:
        raise KeyError(f"component {name!r} not found (available: {sorted(classes)})")
    return params_from_dict(_declared_params_class(classes[name]), params_dict)


class SimpleEngine(Engine):
    """1-of-each sugar (ref: EngineParams.scala:98 SimpleEngine)."""

    def __init__(self, data_source: type, preparator: type, algorithm: type, serving: type):
        super().__init__(data_source, preparator, algorithm, serving)


class EngineFactory(abc.ABC):
    """User entry point (ref: EngineFactory.scala:28) —
    ``class MyEngine(EngineFactory)`` with ``apply()`` returning an Engine."""

    @abc.abstractmethod
    def apply(self) -> Engine:
        ...


def factory_from_object(obj: Any, name: str) -> Callable[[], Engine]:
    """Resolved attribute -> zero-arg engine factory (the acceptance
    rules of WorkflowUtils.getEngine:60: an EngineFactory subclass, an
    instance, an Engine, or a plain callable)."""
    if isinstance(obj, type) and issubclass(obj, EngineFactory):
        return obj().apply
    if isinstance(obj, EngineFactory):
        return obj.apply
    if isinstance(obj, Engine):
        return lambda: obj
    if callable(obj):
        return obj
    raise TypeError(f"{name} is not an EngineFactory / Engine / callable")


def resolve_engine_factory(dotted: str) -> Callable[[], Engine]:
    """'pkg.module.ObjName' -> zero-arg engine factory.

    ref: WorkflowUtils.getEngine:60 — accepts an EngineFactory subclass,
    an instance, a plain function, or an Engine-returning attribute.
    """
    import importlib

    module_name, _, attr = dotted.rpartition(".")
    if not module_name:
        raise ValueError(f"engine factory {dotted!r} must be a dotted path")
    obj = getattr(importlib.import_module(module_name), attr)
    return factory_from_object(obj, dotted)
