"""DASE controller framework (ref: core/src/main/scala/io/prediction/{core,controller}/).

The reference splits every DASE role into P* (Spark RDD) and L* (local)
class families (PDataSource/LDataSource, PAlgorithm/P2LAlgorithm/
LAlgorithm, ...). Without Spark that split disappears: one class per
role, and "parallel vs local" becomes a property of the *data* — a
TrainingData that is a pytree of (possibly mesh-sharded) arrays runs on
the mesh; one that is plain Python runs on the host (SURVEY.md §7.3).
"""

from predictionio_tpu.core.params import Params, EmptyParams, EngineParams
from predictionio_tpu.core.controller import (
    Algorithm,
    AverageServing,
    DataSource,
    FirstServing,
    IdentityPreparator,
    Preparator,
    SanityCheck,
    Serving,
)
from predictionio_tpu.core.engine import Engine, EngineFactory, SimpleEngine, TrainResult

__all__ = [
    "Params",
    "EmptyParams",
    "EngineParams",
    "DataSource",
    "Preparator",
    "IdentityPreparator",
    "Algorithm",
    "Serving",
    "FirstServing",
    "AverageServing",
    "SanityCheck",
    "Engine",
    "EngineFactory",
    "SimpleEngine",
    "TrainResult",
]
