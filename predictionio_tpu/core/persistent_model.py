"""Opt-in custom model persistence.

Behavior contract from the reference (controller/PersistentModel.scala:47,72
and PersistentModelManifest.scala:18): an algorithm whose model
implements PersistentModel saves itself under the engine-instance id and
is reloaded (not unpickled) at deploy; the Models repo then stores only
a manifest naming the loader class. LocalFileSystemPersistentModel
(ref: LocalFileSystemPersistentModel.scala:26) is the ready-made file
based implementation.

The reference's third path — a `Unit` model sentinel forcing a full
retrain at deploy (Engine.scala:186-204) — is intentionally dropped:
array models are cheap to persist (SURVEY.md §7 hard-part (c)).
"""

from __future__ import annotations

import abc
import os
import pickle
from dataclasses import dataclass
from typing import Any, Optional

from predictionio_tpu.core.params import Params
from predictionio_tpu.parallel.mesh import MeshContext


@dataclass(frozen=True)
class PersistentModelManifest:
    """Stored in the Models repo instead of the model bytes
    (ref: PersistentModelManifest.scala:18)."""

    class_name: str
    module_name: str


class PersistentModel(abc.ABC):
    """Models that manage their own persistence (ref: PersistentModel.scala:47)."""

    @abc.abstractmethod
    def save(self, instance_id: str, params: Params, ctx: MeshContext) -> bool:
        """Persist under the engine-instance id; return True if saved."""

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Params, ctx: MeshContext) -> "PersistentModel":
        """ref: PersistentModelLoader.apply."""


def model_base_dir() -> str:
    base = os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store"))
    path = os.path.join(base, "persistent_models")
    os.makedirs(path, exist_ok=True)
    return path


class LocalFileSystemPersistentModel(PersistentModel):
    """File-per-instance pickle persistence
    (ref: LocalFileSystemPersistentModel.scala:26)."""

    def save(self, instance_id: str, params: Params, ctx: MeshContext) -> bool:
        with open(os.path.join(model_base_dir(), instance_id), "wb") as f:
            pickle.dump(self, f)
        return True

    @classmethod
    def load(cls, instance_id: str, params: Params, ctx: MeshContext):
        with open(os.path.join(model_base_dir(), instance_id), "rb") as f:
            return pickle.load(f)


def manifest_for(model: PersistentModel) -> PersistentModelManifest:
    return PersistentModelManifest(
        class_name=type(model).__qualname__, module_name=type(model).__module__
    )


def load_from_manifest(
    manifest: PersistentModelManifest,
    instance_id: str,
    params: Params,
    ctx: MeshContext,
) -> Any:
    """ref: SparkWorkflowUtils.getPersistentModel (WorkflowUtils.scala:356)."""
    import importlib

    module = importlib.import_module(manifest.module_name)
    cls = module
    for part in manifest.class_name.split("."):
        cls = getattr(cls, part)
    return cls.load(instance_id, params, ctx)
