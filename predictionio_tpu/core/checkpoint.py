"""Mid-training checkpoint/resume for iterative trainers.

Beyond the reference, which persists models ONLY after training
completes (SURVEY.md §5.4: "model-level only, no mid-training
checkpoints" — a failed Spark job just fails the instance): the neural
trainers (two-tower, sessionrec) can write an atomic checkpoint every
``every`` epochs and resume exactly — optimizer state, epoch counter
and RNG streams included, so an interrupted-and-resumed run produces
the SAME parameters as an uninterrupted one.

Safety properties owned here (NOT by the trainers):
  - a ``fingerprint`` of (config, data dims, data sample) travels with
    every checkpoint; restore ignores checkpoints whose fingerprint
    differs, so a later run on NEW data or a changed config starts
    fresh instead of silently adopting stale parameters or wrong-shape
    embedding tables. (A rerun with an IDENTICAL fingerprint resuming
    to completion is correct by construction: deterministic seeds mean
    the checkpointed parameters ARE that run's result.)
  - multi-host: only process 0 writes (no torn concurrent writes to a
    shared filesystem); cross-process-sharded arrays are allgathered
    to host before pickling. ``directory`` MUST be a filesystem shared
    by all processes — every process restores from it at trainer
    construction. restore() enforces this: process 0's restored epoch
    is broadcast and any process that disagrees (the symptom of
    host-local directories) raises instead of silently desynchronizing
    the jitted collective training steps.
  - atomicity: write to ``.tmp`` then ``os.replace``; a crash mid-write
    never corrupts the latest good checkpoint; a torn newest file falls
    back to the previous one. The two most recent checkpoints are kept.

Format: one pickle per checkpoint (pytrees with numpy leaves — device
arrays are materialized on save and re-placed by the trainer on
restore).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import re
from typing import Any, Optional, Tuple

log = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.pkl$")


def train_fingerprint(*parts: Any) -> str:
    """Stable digest of a training run's identity: pass the config
    dataclass, dimension ints, and cheap data samples (numpy arrays are
    hashed by content)."""
    import numpy as np

    h = hashlib.md5()
    for part in parts:
        if isinstance(part, np.ndarray):
            h.update(str(part.dtype).encode())
            h.update(str(part.shape).encode())
            h.update(np.ascontiguousarray(part).tobytes())
        else:
            h.update(repr(part).encode())
        h.update(b"|")
    return h.hexdigest()


def _to_host(x: Any) -> Any:
    """Device array -> numpy, allgathering cross-process shards (see
    parallel.multihost.to_host); non-arrays pass through."""
    import jax

    if not isinstance(x, jax.Array):
        return x
    from predictionio_tpu.parallel.multihost import to_host

    return to_host(x)


class TrainCheckpointer:
    """Epoch-granular checkpoint writer/reader over one directory."""

    def __init__(self, directory: str, every: int = 1, keep: int = 2,
                 fingerprint: Optional[str] = None):
        self.directory = directory
        self.every = max(1, int(every))
        self.keep = max(1, int(keep))
        self.fingerprint = fingerprint
        os.makedirs(directory, exist_ok=True)

    def _path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"ckpt_{epoch}.pkl")

    def _epochs_on_disk(self):
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def maybe_save(self, epoch: int, state: Any) -> bool:
        """Save after ``epoch`` completed epochs when due; returns
        whether a checkpoint was written. ``state`` is any picklable
        pytree — device arrays are pulled to host first. Multi-host:
        process 0 is the single writer."""
        if epoch % self.every:
            return False
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            return False
        host_state = jax.tree_util.tree_map(_to_host, state)
        path = self._path(epoch)
        with open(path + ".tmp", "wb") as f:
            pickle.dump(
                {"epoch": epoch, "state": host_state,
                 "fingerprint": self.fingerprint},
                f,
            )
        os.replace(path + ".tmp", path)
        for old in self._epochs_on_disk()[: -self.keep]:
            try:
                os.remove(self._path(old))
            except FileNotFoundError:
                pass
        log.info("checkpoint written: %s", path)
        return True

    def restore(self) -> Optional[Tuple[int, Any]]:
        """(completed_epochs, state) from the newest readable checkpoint
        whose fingerprint matches this run, or None. A torn newest file
        falls back to the previous one; a fingerprint mismatch (other
        data/config trained into this directory) is skipped with a
        warning.

        Multi-host: the result is validated against process 0's — all
        processes must resume from the SAME epoch (requires ``directory``
        on a shared filesystem), otherwise the jitted collective steps
        would desynchronize (hang or silent divergence). Disagreement
        fails fast here; if process 0 starts fresh, every process does.
        """
        local = self._restore_local()
        return self._reconcile_multihost(local)

    def _restore_local(self) -> Optional[Tuple[int, Any]]:
        for epoch in reversed(self._epochs_on_disk()):
            try:
                with open(self._path(epoch), "rb") as f:
                    doc = pickle.load(f)
            except Exception:  # noqa: BLE001 — fall back to older
                log.warning("unreadable checkpoint %s; trying older",
                            self._path(epoch))
                continue
            if doc.get("fingerprint") != self.fingerprint:
                log.warning(
                    "checkpoint %s belongs to a different run "
                    "(config/data changed) — starting fresh",
                    self._path(epoch),
                )
                return None
            return int(doc["epoch"]), doc["state"]
        return None

    def _reconcile_multihost(
        self, local: Optional[Tuple[int, Any]]
    ) -> Optional[Tuple[int, Any]]:
        import jax

        if jax.process_count() <= 1:
            return local
        import numpy as np
        from jax.experimental import multihost_utils

        my_epoch = local[0] if local is not None else -1
        epoch0 = int(
            multihost_utils.broadcast_one_to_all(np.int64(my_epoch))
        )
        if epoch0 == -1:
            # process 0 starts fresh -> everyone starts fresh (a local
            # checkpoint here would mean a stale/non-shared directory)
            return None
        if my_epoch != epoch0:
            raise RuntimeError(
                f"checkpoint desync: process 0 restored epoch {epoch0} but "
                f"process {jax.process_index()} found "
                f"{'epoch %d' % my_epoch if my_epoch >= 0 else 'no checkpoint'} "
                f"in {self.directory!r} — checkpoint_dir must be a filesystem "
                "shared by ALL processes (process 0 is the single writer; "
                "every process restores from it)"
            )
        return local
