"""DASE component base classes.

Behavior contracts from the reference controller layer:

  - DataSource  (ref: controller/PDataSource.scala:34, LDataSource.scala:35)
  - Preparator  (ref: controller/PPreparator.scala:30, IdentityPreparator.scala:31)
  - Algorithm   (ref: controller/PAlgorithm.scala:45, P2LAlgorithm.scala:42,
                 LAlgorithm.scala:41 — collapsed into one class; see
                 predictionio_tpu.core.__doc__ for why)
  - Serving     (ref: controller/LServing.scala:26 + LFirstServing/LAverageServing)
  - SanityCheck (ref: controller/SanityCheck.scala:24)

Generic type roles (kept as documentation; Python stays duck-typed):
TD training data, EI evaluation info, PD prepared data, Q query,
P predicted result, A actual result, M model.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Generic, List, Optional, Sequence, Tuple, TypeVar

from predictionio_tpu.core.params import EmptyParams, Params
from predictionio_tpu.parallel.mesh import MeshContext

TD = TypeVar("TD")
EI = TypeVar("EI")
PD = TypeVar("PD")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")
M = TypeVar("M")


class Doer:
    """Base for components instantiated with their Params.

    ref: core/AbstractDoer.scala:24 — the reference reflects on a
    constructor taking (Params) or zero args; here components store
    their params on construction via `create`.
    """

    params: Params

    def __init__(self, params: Optional[Params] = None):
        self.params = params if params is not None else EmptyParams()

    @classmethod
    def create(cls, params: Optional[Params] = None) -> "Doer":
        """Instantiate with params if the ctor accepts them, else bare.

        Mirrors Doer.apply's two-ctor protocol so user classes may
        define `__init__(self)` without params.
        """
        import inspect

        sig = inspect.signature(cls.__init__)
        if len(sig.parameters) > 1:  # beyond self
            return cls(params)
        inst = cls()
        if params is not None and not isinstance(params, EmptyParams):
            inst.params = params
        return inst


class SanityCheck(abc.ABC):
    """Opt-in hook: TrainingData / PreparedData / models implementing
    this get checked after each pipeline stage (ref: SanityCheck.scala:24,
    called from Engine.scala:610-666)."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise on inconsistent data."""


class DataSource(Doer, Generic[TD, EI, Q, A]):
    """Reads training and evaluation data from the event store."""

    @abc.abstractmethod
    def read_training(self, ctx: MeshContext) -> TD:
        """ref: PDataSource.readTraining"""

    def read_eval(self, ctx: MeshContext) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
        """k folds of (training data, eval info, (query, actual) pairs).

        ref: PDataSource.readEval — default: no eval data.
        """
        return []


class Preparator(Doer, Generic[TD, PD]):
    @abc.abstractmethod
    def prepare(self, ctx: MeshContext, training_data: TD) -> PD:
        """ref: PPreparator.prepare"""


class IdentityPreparator(Preparator):
    """Pass-through (ref: IdentityPreparator.scala:31)."""

    def prepare(self, ctx: MeshContext, training_data):
        return training_data


class Algorithm(Doer, Generic[PD, M, Q, P]):
    """One trainable + servable algorithm.

    Collapses the reference's PAlgorithm / P2LAlgorithm / LAlgorithm
    split: `train` computes on the mesh when its data is sharded,
    `predict` answers one query at serve time, `batch_predict`
    vector-scores query batches for evaluation (override it with a
    jitted scorer — the default is the per-query loop the reference
    uses in P2LAlgorithm.scala:63).
    """

    @abc.abstractmethod
    def train(self, ctx: MeshContext, prepared_data: PD) -> M:
        ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P:
        ...

    def batch_predict(self, model: M, queries: Sequence[Tuple[int, Q]]) -> List[Tuple[int, P]]:
        """ref: P2LAlgorithm.batchPredict default — mapValues(predict)."""
        return [(i, self.predict(model, q)) for i, q in queries]

    # -- persistence (ref: PAlgorithm.makePersistentModel + CoreWorkflow Kryo path)
    def make_persistent_model(self, model: M) -> Any:
        """Convert the in-memory model to its persisted form.

        Default: the model itself (pickled into the Models repo).
        Return a `PersistentModelManifest` from
        predictionio_tpu.core.persistent_model to take over persistence
        (custom checkpoint dirs, the reference's PersistentModel path).
        """
        return model

    def load_persistent_model(self, persisted: Any, ctx: MeshContext) -> M:
        """Inverse of make_persistent_model at deploy time."""
        return persisted

    def warmup(self, model: M, ctx: MeshContext) -> None:
        """Pre-compile the serve path's standard shape buckets.

        Called by the engine server right after deploy/reload so the
        FIRST live query doesn't pay XLA compile (SURVEY.md §7.5 hard
        part #2 — the reference has no compile step to warm; a jitted
        scorer does). Default: no-op. Implementations should drive the
        same compiled functions ``predict`` uses, at the default
        (B, k, ...) buckets, and must tolerate empty models."""

    def apply_patch(self, model: M, patch: dict) -> bool:
        """Apply a streaming model patch (workflow/stream.py fold-in)
        to the LIVE model in place — the lightweight alternative to a
        full ``/reload`` when only a few rows of the model moved.

        Returns False when this algorithm does not support patching
        (the default): the engine server then answers 400 and the
        streaming path falls back to the rolling-reload lane. An
        implementation must leave concurrent ``predict`` calls
        consistent (copy-on-write swaps, never torn in-place rows)."""
        return False


class Serving(Doer, Generic[Q, P]):
    """Combines the per-algorithm predictions into one response."""

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        """ref: LServing.serve"""


class FirstServing(Serving):
    """Head of the predictions (ref: LFirstServing.scala:25)."""

    def serve(self, query, predictions):
        return predictions[0]


class AverageServing(Serving):
    """Arithmetic mean of numeric predictions (ref: LAverageServing.scala:25)."""

    def serve(self, query, predictions):
        return sum(predictions) / len(predictions)
