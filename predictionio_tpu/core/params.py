"""Component parameters + engine-level parameter bundles.

Behavior contract from the reference (controller/Params.scala:23,
controller/EngineParams.scala:31): every DASE component takes a typed
`Params` value; an `EngineParams` names which component variant fills
each DASE slot together with its params — the unit of hyperparameter
search. Params are Python dataclasses; JSON params blocks from
engine.json variants are materialized into them by field name
(the analogue of WorkflowUtils.extractParams:129 reflection).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Type


class Params:
    """Marker base for component params (ref: Params.scala:23).

    Subclasses should be @dataclass es. Params must be JSON-round-trippable.
    """


@dataclass(frozen=True)
class EmptyParams(Params):
    """ref: Params.scala:29 EmptyParams."""


def params_to_dict(p: Optional[Params]) -> dict:
    if p is None:
        return {}
    if dataclasses.is_dataclass(p):
        return dataclasses.asdict(p)
    if isinstance(p, dict):
        return dict(p)
    raise TypeError(f"params must be a dataclass or dict, got {type(p)}")


def params_from_dict(cls: Optional[Type[Params]], d: Optional[dict]) -> Params:
    """Materialize a params dataclass from a JSON dict by field name.

    ref: WorkflowUtils.extractParams:129 — unknown keys are rejected so
    typos in engine.json fail fast (the reference fails on extraction
    errors too).
    """
    d = d or {}
    if cls is None or cls is EmptyParams:
        if d:
            raise ValueError(f"component takes no params but got {sorted(d)}")
        return EmptyParams()
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"params class {cls} must be a dataclass")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(
            f"unknown params {sorted(unknown)} for {cls.__name__} "
            f"(valid: {sorted(names)})"
        )
    return cls(**d)


@dataclass
class EngineParams:
    """Named component choice + params per DASE slot (ref: EngineParams.scala:31).

    ``algorithm_params_list`` holds (name, params) per algorithm — one
    engine may train several algorithms whose predictions the Serving
    layer combines (the most distinctive DASE behavior, SURVEY.md §7).
    """

    data_source_params: Tuple[str, Params] = ("", EmptyParams())
    preparator_params: Tuple[str, Params] = ("", EmptyParams())
    algorithm_params_list: List[Tuple[str, Params]] = field(default_factory=list)
    serving_params: Tuple[str, Params] = ("", EmptyParams())

    def __post_init__(self):
        self.data_source_params = _normalize(self.data_source_params)
        self.preparator_params = _normalize(self.preparator_params)
        self.serving_params = _normalize(self.serving_params)
        self.algorithm_params_list = [_normalize(x) for x in self.algorithm_params_list]

    def to_json_dict(self) -> dict:
        return {
            "dataSourceParams": _slot_json(self.data_source_params),
            "preparatorParams": _slot_json(self.preparator_params),
            "algorithmParamsList": [
                {"name": n, "params": params_to_dict(p)}
                for n, p in self.algorithm_params_list
            ],
            "servingParams": _slot_json(self.serving_params),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), sort_keys=True)


def _normalize(slot) -> Tuple[str, Params]:
    """Accept bare Params (name defaults to "") for SimpleEngine-style use."""
    if isinstance(slot, tuple):
        name, p = slot
        return (name, p if p is not None else EmptyParams())
    return ("", slot if slot is not None else EmptyParams())


def _slot_json(slot: Tuple[str, Params]) -> dict:
    name, p = slot
    return {"name": name, "params": params_to_dict(p)}
