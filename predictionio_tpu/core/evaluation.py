"""Metrics + MetricEvaluator: offline evaluation and tuning.

Behavior contracts from the reference:

  - Metric family (controller/Metric.scala:36-218): a Metric reduces
    all folds' (query, prediction, actual) triples to one score;
    AverageMetric (mean), OptionAverageMetric (None-scores excluded),
    StdevMetric (population stddev), OptionStdevMetric, SumMetric.
    The reference computes these with RDD mean()/stdev(); here they are
    numpy reductions.
  - MetricEvaluator (controller/MetricEvaluator.scala:90-222):
    evaluates each EngineParams candidate, ranks by the primary metric,
    logs a leaderboard, writes the best params to ``best.json`` and
    yields a result with one-liner / JSON / HTML renderings.
  - Evaluation (controller/Evaluation.scala:32): binds an engine with a
    metric (+ optional secondary metrics).
  - EngineParamsGenerator (controller/EngineParamsGenerator.scala:27):
    the candidate list for grid search.
"""

from __future__ import annotations

import abc
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, List, Optional, Sequence, Tuple, TypeVar

import numpy as np

from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.params import EngineParams
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.workflow.config import WorkflowParams

log = logging.getLogger(__name__)

#: eval data shape: per fold (eval info, [(query, prediction, actual)])
EvalDataSet = List[Tuple[Any, List[Tuple[Any, Any, Any]]]]


class Metric(abc.ABC):
    """ref: Metric.scala:36 — reduces an EvalDataSet to one score.

    ``higher_is_better`` plays the role of the reference's Ordering
    (Metric.scala comparator): MetricEvaluator ranks accordingly.
    """

    higher_is_better: bool = True

    @abc.abstractmethod
    def calculate(self, ctx: MeshContext, eval_data: EvalDataSet) -> float:
        ...

    def header(self) -> str:
        return type(self).__name__


class QPAMetric(Metric):
    """Marker matching the reference's QPAMetric shape (Metric.scala:216):
    metrics computed pointwise from (Q, P, A) triples."""

    @abc.abstractmethod
    def calculate_qpa(self, q: Any, p: Any, a: Any) -> Optional[float]:
        ...

    def _scores(self, eval_data: EvalDataSet) -> np.ndarray:
        scores = [
            s
            for _ei, qpas in eval_data
            for q, p, a in qpas
            if (s := self.calculate_qpa(q, p, a)) is not None
        ]
        return np.asarray(scores, dtype=np.float64)


class AverageMetric(QPAMetric):
    """Mean of per-triple scores (ref: Metric.scala:87). Subclasses
    implement calculate_qpa returning a float for every triple."""

    def calculate(self, ctx, eval_data) -> float:
        scores = self._scores(eval_data)
        return float(scores.mean()) if scores.size else float("nan")


class OptionAverageMetric(AverageMetric):
    """Mean over triples with non-None scores (ref: Metric.scala:112)."""


class StdevMetric(QPAMetric):
    """Population stddev of scores (ref: Metric.scala:139 — RDD stdev)."""

    def calculate(self, ctx, eval_data) -> float:
        scores = self._scores(eval_data)
        return float(scores.std()) if scores.size else float("nan")


class OptionStdevMetric(StdevMetric):
    """ref: Metric.scala:167."""


class SumMetric(QPAMetric):
    """Sum of scores (ref: Metric.scala:193)."""

    def calculate(self, ctx, eval_data) -> float:
        scores = self._scores(eval_data)
        return float(scores.sum())


class MeanSquareError(AverageMetric):
    """Mean of (prediction - actual)^2 over float-valued triples
    (ref: controller/Evaluator.scala:126 — evaluateSet's
    ``mean((p - a)^2)``); lower is better."""

    higher_is_better = False

    def calculate_qpa(self, q, p, a):
        return (float(p) - float(a)) ** 2


class FunctionMetric(AverageMetric):
    """Sugar: wrap a plain (q, p, a) -> float function as an AverageMetric."""

    def __init__(self, fn: Callable[[Any, Any, Any], Optional[float]], name: str = ""):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "FunctionMetric")

    def calculate_qpa(self, q, p, a):
        return self.fn(q, p, a)

    def header(self) -> str:
        return self.name


class EngineParamsGenerator:
    """ref: EngineParamsGenerator.scala:27 — candidate params for tuning."""

    def __init__(self, engine_params_list: Sequence[EngineParams]):
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        self.engine_params_list = list(engine_params_list)


@dataclass
class Evaluation:
    """ref: Evaluation.scala:32 — engine + metric(s) binding."""

    engine: Engine
    metric: Metric
    metrics: List[Metric] = field(default_factory=list)  # secondary metrics

    @property
    def all_metrics(self) -> List[Metric]:
        return [self.metric] + list(self.metrics)


@dataclass
class MetricScores:
    engine_params: EngineParams
    score: float
    other_scores: List[float]


@dataclass
class MetricEvaluatorResult:
    """ref: MetricEvaluator.scala:144 result object."""

    best_score: float
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: List[str]
    engine_params_scores: List[MetricScores]

    def to_one_liner(self) -> str:
        return f"[{self.metric_header}: {self.best_score:.4f}] best params idx={self.best_idx}"

    def to_json(self) -> str:
        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "otherMetricHeaders": self.other_metric_headers,
                "bestScore": self.best_score,
                "bestIdx": self.best_idx,
                "bestEngineParams": self.best_engine_params.to_json_dict(),
                "engineParamsScores": [
                    {
                        "engineParams": s.engine_params.to_json_dict(),
                        "score": s.score,
                        "otherScores": s.other_scores,
                    }
                    for s in self.engine_params_scores
                ],
            },
            sort_keys=True,
        )

    def to_html(self) -> str:
        rows = "\n".join(
            f"<tr><td>{i}</td><td>{s.score:.6f}</td>"
            f"<td><pre>{json.dumps(s.engine_params.to_json_dict(), indent=1)}</pre></td></tr>"
            for i, s in enumerate(self.engine_params_scores)
        )
        return (
            f"<h2>Metric: {self.metric_header}</h2>"
            f"<p>Best score: {self.best_score:.6f} (idx {self.best_idx})</p>"
            f"<table border=1><tr><th>#</th><th>score</th><th>params</th></tr>{rows}</table>"
        )


class MetricEvaluator:
    """ref: MetricEvaluator.scala:90 — evaluate candidates, rank, persist best.

    ``best_json_path``: where the winning EngineParams land
    (ref: saveEngineJson writing best.json, MetricEvaluator.scala:152).
    """

    def __init__(self, best_json_path: Optional[str] = None):
        self.best_json_path = best_json_path

    def evaluate(
        self,
        ctx: MeshContext,
        evaluation: Evaluation,
        engine_params_list: Sequence[EngineParams],
        workflow_params: Optional[WorkflowParams] = None,
        eval_fn: Optional[Callable[[MeshContext, EngineParams], EvalDataSet]] = None,
    ) -> MetricEvaluatorResult:
        wp = workflow_params or WorkflowParams()
        engine = evaluation.engine
        run_eval = eval_fn or (lambda c, ep: engine.eval(c, ep, wp))
        results: List[MetricScores] = []
        for i, ep in enumerate(engine_params_list):
            eval_data = run_eval(ctx, ep)
            score = evaluation.metric.calculate(ctx, eval_data)
            others = [m.calculate(ctx, eval_data) for m in evaluation.metrics]
            log.info("candidate %d: %s = %s", i, evaluation.metric.header(), score)
            results.append(MetricScores(engine_params=ep, score=score, other_scores=others))

        sign = 1.0 if evaluation.metric.higher_is_better else -1.0

        def rank_key(score: float) -> float:
            # non-finite scores (no eval data) rank worst for BOTH
            # orderings: -inf must be applied after the sign flip
            return sign * score if np.isfinite(score) else -np.inf

        best_idx = int(
            max(range(len(results)), key=lambda i: rank_key(results[i].score))
        )
        best = results[best_idx]
        result = MetricEvaluatorResult(
            best_score=best.score,
            best_engine_params=best.engine_params,
            best_idx=best_idx,
            metric_header=evaluation.metric.header(),
            other_metric_headers=[m.header() for m in evaluation.metrics],
            engine_params_scores=results,
        )
        # leaderboard log (ref: MetricEvaluator printing the ranking)
        order = sorted(results, key=lambda s: rank_key(s.score), reverse=True)
        for rank, s in enumerate(order):
            log.info("leaderboard #%d: score=%s", rank + 1, s.score)
        if self.best_json_path:
            os.makedirs(os.path.dirname(self.best_json_path) or ".", exist_ok=True)
            with open(self.best_json_path, "w") as f:
                json.dump(best.engine_params.to_json_dict(), f, indent=1, sort_keys=True)
        return result
