"""The Storage Server: DAO-level REST storage service, default port 7077.

The reference delegates scale-out storage to external network services —
HBase for events (client RPC, data/.../storage/hbase/StorageClient.scala),
Elasticsearch for metadata (transport port 9300,
elasticsearch/StorageClient.scala:42), HDFS for model blobs
(hdfs/HDFSModels.scala:28). This server is the TPU build's equivalent
network tier: it exposes the *storage DAO contracts* (EventStore, the
metadata repos, ModelsRepo) over HTTP, backed by whatever local backend
the server process is configured with (eventlog/sqlite/localfs/memory).
N serving hosts + M trainer hosts point a ``rest``-type storage source
(data/backends/rest.py) at one storage server and share one logical
METADATA / EVENTDATA / MODELDATA — train on host A, deploy on host B.

Routes:
  - ``GET  /``                            {"status": "alive"}
  - ``POST /storage/events/<method>``     init/remove/insert/insert_batch/
                                          get/delete/compact — JSON body,
                                          DB-format event dicts
  - ``POST /storage/events/find``         filter body -> NDJSON stream
                                          (one DB-format event per line)
  - ``POST /storage/events/find_columnar``filter body -> {"scan_id", "bytes"}:
                                          the result npz is spooled to DISK
                                          (never a second in-memory copy) and
                                          fetched separately — see next route
  - ``GET  /storage/events/scan/<id>?offset=N`` stream the spooled npz from
                                          byte N (clients resume after a
                                          dropped connection); DELETE frees
                                          it (a TTL reaps abandoned scans)
  - ``POST /storage/meta/<repo>/<method>``whitelisted repo RPC (args array,
                                          records as dicts)
  - ``PUT/GET/DELETE /storage/models/<id>`` raw model blobs

Optional shared-secret auth: configure ``AUTH_KEY`` on the server and the
client; every request must carry it in ``X-PIO-Storage-Key`` (the
reference's storage tiers sit on a trusted network; the key guards
against accidental cross-environment writes, not adversaries).
"""

from __future__ import annotations

import collections
import datetime as _dt
import json
import logging
import os
import shutil
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from predictionio_tpu.data.event import Event
from predictionio_tpu.data import metadata as MD
from predictionio_tpu.data.metadata import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    Model,
)
from predictionio_tpu.data.storage import (
    UNSET,
    RowValidationError,
    Storage,
    StorageError,
    columns_to_npz_file,
    get_storage,
    npz_to_columns,
)
from predictionio_tpu.serving.http import (HTTPServerBase,
                                           JSONRequestHandler,
                                           install_drain_handler)

log = logging.getLogger(__name__)

DEFAULT_PORT = 7077


class _ScanRegistry:
    """Disk-spooled bulk-scan results, fetched (and resumed) by id.

    A 20M-row columnar result is written ONCE to a spool file; N fetch
    requests stream byte ranges of it, so concurrent bulk readers cost
    disk, not resident memory, and a client whose connection dropped
    mid-transfer resumes from its last received byte instead of
    re-scanning. Abandoned scans (client crashed) are reaped after
    ``ttl`` seconds, checked on every registry access."""

    def __init__(self, ttl: float = 600.0):
        self._dir = tempfile.mkdtemp(prefix="pio_scans_")
        self._scans: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._ttl = ttl

    def create(self, write_fn) -> Dict[str, Any]:
        scan_id = uuid.uuid4().hex
        path = os.path.join(self._dir, scan_id + ".npz")
        with open(path, "wb") as f:
            write_fn(f)
        size = os.path.getsize(path)
        with self._lock:
            self._reap_locked()
            self._scans[scan_id] = {"path": path, "bytes": size,
                                    "created": time.monotonic()}
        return {"scan_id": scan_id, "bytes": size}

    def path_for(self, scan_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            self._reap_locked()
            scan = self._scans.get(scan_id)
            if scan is not None:
                # sliding TTL: a transfer making progress (resumed
                # range fetches) must never expire mid-download just
                # because the WHOLE transfer outlives the ttl
                scan["created"] = time.monotonic()
            return scan

    def release(self, scan_id: str) -> bool:
        with self._lock:
            scan = self._scans.pop(scan_id, None)
        if scan:
            try:
                os.remove(scan["path"])
            except FileNotFoundError:
                pass
        return scan is not None

    def live_count(self) -> int:
        """Spools currently held on disk (reaps expired ones first) —
        the observability hook a soak test needs to PROVE the TTL
        reaper fires instead of spool files accumulating forever."""
        with self._lock:
            self._reap_locked()
            return len(self._scans)

    def _reap_locked(self) -> None:
        now = time.monotonic()
        for sid in [s for s, v in self._scans.items()
                    if now - v["created"] > self._ttl]:
            scan = self._scans.pop(sid)
            try:
                os.remove(scan["path"])
            except FileNotFoundError:
                pass

    def close(self) -> None:
        shutil.rmtree(self._dir, ignore_errors=True)

#: per-repo RPC whitelist: method -> (record-arg positions, result kind)
#: result kinds: "record" | "records" | "scalar"
_REPO_SPECS: Dict[str, Dict[str, Any]] = {
    "apps": {
        "record_cls": App,
        "methods": {
            "insert": ((), "record"),
            "put": ((0,), "scalar"),
            "get": ((), "record"),
            "get_by_name": ((), "record"),
            "get_all": ((), "records"),
            "update": ((0,), "scalar"),
            "delete": ((), "scalar"),
        },
    },
    "access_keys": {
        "record_cls": AccessKey,
        "methods": {
            "insert": ((0,), "scalar"),
            "put": ((0,), "scalar"),
            "get": ((), "record"),
            "get_all": ((), "records"),
            "get_by_app_id": ((), "records"),
            "update": ((0,), "scalar"),
            "delete": ((), "scalar"),
        },
    },
    "channels": {
        "record_cls": Channel,
        "methods": {
            "insert": ((), "record"),
            "put": ((0,), "scalar"),
            "get": ((), "record"),
            "get_by_app_id": ((), "records"),
            "delete": ((), "scalar"),
        },
    },
    "engine_manifests": {
        "record_cls": EngineManifest,
        "methods": {
            "insert": ((0,), "scalar"),
            "put": ((0,), "scalar"),
            "get": ((), "record"),
            "get_all": ((), "records"),
            "update": ((0,), "scalar"),
            "delete": ((), "scalar"),
        },
    },
    "engine_instances": {
        "record_cls": EngineInstance,
        "methods": {
            "insert": ((0,), "scalar"),
            "put": ((0,), "scalar"),
            "get": ((), "record"),
            "get_all": ((), "records"),
            "get_latest_completed": ((), "record"),
            "get_completed": ((), "records"),
            "update": ((0,), "scalar"),
            "delete": ((), "scalar"),
        },
    },
    "evaluation_instances": {
        "record_cls": EvaluationInstance,
        "methods": {
            "insert": ((0,), "scalar"),
            "put": ((0,), "scalar"),
            "get": ((), "record"),
            "get_all": ((), "records"),
            "get_completed": ((), "records"),
            "update": ((0,), "scalar"),
            "delete": ((), "scalar"),
        },
    },
}

_EVENT_METHODS = frozenset(
    {"init", "remove", "insert", "insert_batch", "get", "delete", "find",
     "find_columnar", "insert_columnar", "insert_json", "compact"}
)


def _encode_result(value: Any, kind: str) -> Any:
    if kind == "record":
        return None if value is None else MD.record_to_dict(value)
    if kind == "records":
        return [MD.record_to_dict(r) for r in value]
    return value


class StorageRequestHandler(JSONRequestHandler):
    """Dispatch /storage/* to the wrapped Storage's DAOs."""

    server_version = "PIOStorageServer/0.1"

    # -- auth ---------------------------------------------------------------
    def _authorized(self) -> bool:
        required = self.server_ref.auth_key
        if not required:
            return True
        return self.headers.get("X-PIO-Storage-Key") == required

    def _deny(self) -> None:
        self._send(401, {"message": "Invalid storage key."})

    # -- HTTP verbs ---------------------------------------------------------
    def _guarded(self, fn, *args):
        """Run a route handler, mapping storage/user errors to HTTP
        bodies (a backend failure must answer, not abort the socket —
        an aborted connection reads as a network outage client-side)."""
        try:
            return fn(*args)
        except StorageError as e:
            return self._send(400, {"message": str(e), "type": "StorageError"})
        except (KeyError, TypeError, ValueError) as e:
            return self._send(400, {"message": str(e), "type": type(e).__name__})
        except Exception as e:  # noqa: BLE001 — surfaced to the client
            log.exception("storage server error on %s", self.path)
            return self._send(500, {"message": str(e), "type": type(e).__name__})

    def do_GET(self):
        if not self._authorized():
            return self._deny()
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(self.path)
        if parsed.path == "/":
            return self._send(200, {"status": "alive"})
        if parsed.path == "/storage/stats":
            # operator/test observability: per-request log of columnar
            # scans (rows served, shard asked for) — how a 2-host
            # sharded training read is PROVEN to fetch half the rows
            # each (the Spark-UI per-executor input-size role)
            return self._send(200, self.server_ref.scan_stats())
        if parsed.path in ("/storage/models", "/storage/models/"):
            # replica-reconciliation inventory (id/bytes/sha256 per
            # blob) — the HDFS block-report role for `pio storagerepair`
            return self._guarded(
                lambda: self._send(
                    200, {"models": self.server_ref.storage.models().list()}))
        if parsed.path.startswith("/storage/models/"):
            return self._guarded(self._get_model,
                                 parsed.path[len("/storage/models/"):])
        if parsed.path.startswith("/storage/events/scan/"):
            scan_id = parsed.path[len("/storage/events/scan/"):]
            q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            return self._guarded(self._fetch_scan, scan_id,
                                 q.get("offset", "0"))
        return self._send(404, {"message": "not found"})

    def _fetch_scan(self, scan_id: str, offset_raw: str):
        offset = int(offset_raw)  # inside _guarded: bad input answers 400
        scan = self.server_ref.scans.path_for(scan_id)
        if scan is None:
            # expired/unknown (e.g. the server restarted mid-transfer):
            # the client re-prepares — a data-miss 404, not a bad route
            return self._send(404, {"message": "unknown scan",
                                    "missing": True})
        size = scan["bytes"]
        if not 0 <= offset <= size:
            return self._send(400, {"message": f"bad offset {offset}"})
        # open BEFORE the status line goes out: a concurrent release or
        # TTL reap unlinking the spool must answer a clean retryable
        # 404, never a second response corrupting the declared body
        try:
            f = open(scan["path"], "rb")
        except FileNotFoundError:
            return self._send(404, {"message": "unknown scan",
                                    "missing": True})
        # stream the spool file in bounded chunks: no full-blob buffer
        self._body_consumed = True  # GET: nothing to drain
        with f:
            f.seek(offset)
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(size - offset))
            self.end_headers()
            try:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    self.wfile.write(chunk)
            except Exception:  # noqa: BLE001 — status line already sent
                # a mid-stream failure (disk error, dead socket) must
                # NOT bubble to _guarded: its 500 would land inside the
                # declared body as corrupted scan bytes. Drop the
                # connection — the client sees a short read and resumes
                # from its received offset.
                log.exception("scan stream aborted mid-transfer")
                self.close_connection = True

    def _get_model(self, model_id: str):
        model = self.server_ref.storage.models().get(model_id)
        if model is None:
            # "missing": a data miss on a live route, NOT an unknown
            # route — the rest client maps only this 404 form to None
            return self._send(404, {"message": "model not found",
                                    "missing": True})
        return self._send(200, model.models,
                          content_type="application/octet-stream")

    def do_PUT(self):
        if not self._authorized():
            return self._deny()
        if self.path.startswith("/storage/models/"):
            return self._guarded(self._put_model,
                                 self.path[len("/storage/models/"):])
        return self._send(404, {"message": "not found"})

    def _put_model(self, model_id: str):
        if not model_id:
            return self._send(400, {"message": "missing model id"})
        blob = self._read_body()
        self.server_ref.storage.models().insert(Model(id=model_id, models=blob))
        return self._send(200, {"id": model_id, "bytes": len(blob)})

    def do_DELETE(self):
        if not self._authorized():
            return self._deny()
        if self.path.startswith("/storage/models/"):
            return self._guarded(self._delete_model,
                                 self.path[len("/storage/models/"):])
        if self.path.startswith("/storage/events/scan/"):
            scan_id = self.path[len("/storage/events/scan/"):]
            self.server_ref.scans.release(scan_id)
            return self._send(200, {"ok": True})
        return self._send(404, {"message": "not found"})

    def _delete_model(self, model_id: str):
        self.server_ref.storage.models().delete(model_id)
        return self._send(200, {"id": model_id})

    def do_POST(self):
        if not self._authorized():
            return self._deny()
        from urllib.parse import urlparse

        parts = urlparse(self.path).path.strip("/").split("/")
        if len(parts) == 3 and parts[0] == "storage" and parts[1] == "events":
            return self._guarded(self._handle_events, parts[2])
        if len(parts) == 4 and parts[0] == "storage" and parts[1] == "meta":
            return self._guarded(self._handle_meta, parts[2], parts[3])
        return self._send(404, {"message": "not found"})

    # -- events -------------------------------------------------------------
    @staticmethod
    def _find_kwargs(body: Dict[str, Any]) -> Dict[str, Any]:
        """find/find_columnar filter params from the JSON body."""
        kwargs: Dict[str, Any] = {}
        for key in ("start_time", "until_time"):
            if body.get(key) is not None:
                kwargs[key] = _dt.datetime.fromisoformat(body[key])
        for key in ("entity_type", "entity_id"):
            if body.get(key) is not None:
                kwargs[key] = body[key]
        if body.get("event_names") is not None:
            kwargs["event_names"] = list(body["event_names"])
        # target filters: tri-state (absent | null | value) via *_set flags
        if body.get("target_entity_type_set"):
            kwargs["target_entity_type"] = body.get("target_entity_type")
        if body.get("target_entity_id_set"):
            kwargs["target_entity_id"] = body.get("target_entity_id")
        if body.get("limit") is not None:
            kwargs["limit"] = int(body["limit"])
        kwargs["reversed"] = bool(body.get("reversed", False))
        return kwargs

    def _handle_events(self, method: str):
        if method not in _EVENT_METHODS:
            return self._send(404, {"message": f"unknown events method {method!r}"})
        store = self.server_ref.storage.events()
        if method == "insert_json":
            # the native live lane over the wire: the RAW API-format
            # JSON array travels untouched from the event server's
            # socket to this server's local eventlog encoder — no
            # per-row Python objects on EITHER host. Answers
            # {"unsupported": true} when the local backend has no
            # native lane (or declines the payload shape) so the
            # client falls back to the per-row wire path.
            from urllib.parse import parse_qs, urlparse

            from predictionio_tpu.data.backends.eventlog import (
                JsonRowsUnsupported,
            )

            q = {k: v[0] for k, v in
                 parse_qs(urlparse(self.path).query).items()}
            fast = getattr(store, "insert_json_batch", None)
            raw = self._read_body()
            if fast is None:
                return self._send(200, {"unsupported": True})
            try:
                ids, codes, names, etypes = fast(
                    raw, int(q["app_id"]),
                    int(q["channel_id"]) if q.get("channel_id") else None,
                    strict=q.get("strict", "1") == "1",
                )
            except JsonRowsUnsupported:
                return self._send(200, {"unsupported": True})
            except ValueError as e:
                return self._send(400, {"message": str(e),
                                        "type": "ValueError"})
            except RowValidationError as e:
                # strict=True row-validation failure: a PERMANENT
                # client-data error, not a retryable backend fault —
                # answer 400 with the row_error discriminator so the
                # rest client re-raises it under the same type; other
                # StorageErrors (lock contention, I/O) fall through to
                # _guarded WITHOUT the flag (ADVICE r4 low)
                return self._send(400, {"message": str(e),
                                        "type": "StorageError",
                                        "row_error": True})
            return self._send(201, {"ids": ids, "codes": codes,
                                    "names": names, "etypes": etypes})
        if method == "insert_columnar":
            # binary npz body; scalar params ride in the query string
            # (percent-encoded UTF-8 — headers are latin-1-only). The
            # body is spooled to disk in chunks — a multi-GB bulk
            # ingest never holds the raw blob AND the decoded arrays
            # in memory at once.
            from urllib.parse import parse_qs, urlparse

            q = {k: v[0] for k, v in parse_qs(urlparse(self.path).query).items()}
            length = int(self.headers.get("Content-Length", 0))
            self._body_consumed = True
            with tempfile.TemporaryFile() as spool:
                remaining = length
                while remaining > 0:
                    chunk = self.rfile.read(min(1 << 20, remaining))
                    if not chunk:
                        raise StorageError("truncated insert_columnar body")
                    spool.write(chunk)
                    remaining -= len(chunk)
                spool.seek(0)
                cols = npz_to_columns(spool)
            n = store.insert_columnar(
                cols,
                int(q["app_id"]),
                int(q["channel_id"]) if q.get("channel_id") else None,
                entity_type=q["entity_type"],
                target_entity_type=q.get("target_entity_type"),
                value_property=q.get("value_property"),
            )
            return self._send(201, {"count": int(n)})
        body = self._read_json()
        app_id = int(body["app_id"])
        channel_id = body.get("channel_id")
        if channel_id is not None:
            channel_id = int(channel_id)

        if method == "init":
            store.init(app_id, channel_id)
            return self._send(200, {"ok": True})
        if method == "compact":
            return self._send(200, {"stats": store.compact(app_id, channel_id)})
        if method == "remove":
            store.remove(app_id, channel_id)
            return self._send(200, {"ok": True})
        if method == "insert":
            event = Event.from_dict(body["event"])
            event_id = store.insert(event, app_id, channel_id)
            return self._send(201, {"eventId": event_id})
        if method == "insert_batch":
            events = [Event.from_dict(d) for d in body["events"]]
            ids = store.insert_batch(events, app_id, channel_id)
            return self._send(201, {"eventIds": ids})
        if method == "get":
            event = store.get(body["event_id"], app_id, channel_id)
            if event is None:
                return self._send(404, {"message": "event not found",
                                        "missing": True})
            return self._send(200, {"event": event.to_dict(api_format=False)})
        if method == "delete":
            found = store.delete(body["event_id"], app_id, channel_id)
            return self._send(200, {"found": bool(found)})
        if method == "find_columnar":
            # bulk training read: dict-encoded columns spooled to disk
            # as one npz; the response hands back a scan id the client
            # streams (and resumes) via GET /storage/events/scan/<id>.
            # shard_index/shard_count (entity-hash read shards) filter
            # SERVER-side, so a sharded reader receives ~1/N the bytes.
            shard_index = body.get("shard_index")
            shard_count = body.get("shard_count")
            cols = store.find_columnar(
                app_id, channel_id=channel_id,
                value_property=body.get("value_property"),
                time_ordered=bool(body.get("time_ordered", True)),
                shard_index=int(shard_index) if shard_index is not None else None,
                shard_count=int(shard_count) if shard_count is not None else None,
                **self._find_kwargs(body),
            )
            self.server_ref.record_scan(
                app_id=app_id, rows=len(cols),
                shard_index=shard_index, shard_count=shard_count,
            )
            scan = self.server_ref.scans.create(
                lambda f: columns_to_npz_file(cols, f))
            del cols
            return self._send(200, scan)

        # find: NDJSON stream so 20M-event training reads never build one
        # giant JSON document on either side. Optional placement filter
        # (replicated sharded clients): only rows whose entity
        # hash-routes to the requested shards travel, with any row
        # limit applied AFTER the filter
        kwargs = self._find_kwargs(body)
        pshards = body.get("placement_shards")
        pcount = body.get("placement_count")
        if pshards is not None and pcount:
            from predictionio_tpu.data.storage import stable_hash

            limit = kwargs.pop("limit", None)
            keep = {int(x) for x in pshards}
            events = [
                e for e in store.find(app_id, channel_id=channel_id, **kwargs)
                if stable_hash(e.entity_id) % int(pcount) in keep
            ]
            if limit is not None and limit >= 0:
                events = events[:limit]
        else:
            events = store.find(app_id, channel_id=channel_id, **kwargs)
        # genuinely chunked NDJSON: a 20M-event training read never
        # joins into one multi-GB buffer on the server side
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        buf: List[bytes] = []
        size = 0
        for e in events:
            line = json.dumps(
                e.to_dict(api_format=False), sort_keys=True
            ).encode() + b"\n"
            buf.append(line)
            size += len(line)
            if size >= 256 * 1024:
                self._write_chunk(b"".join(buf))
                buf, size = [], 0
        if buf:
            self._write_chunk(b"".join(buf))
        self.wfile.write(b"0\r\n\r\n")

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

    # -- metadata RPC -------------------------------------------------------
    def _handle_meta(self, repo: str, method: str):
        spec = _REPO_SPECS.get(repo)
        if spec is None or method not in spec["methods"]:
            return self._send(404, {"message": f"unknown meta RPC {repo}/{method}"})
        record_args, result_kind = spec["methods"][method]
        body = self._read_json()
        args = list(body.get("args", []))
        for pos in record_args:
            if pos < len(args) and isinstance(args[pos], dict):
                args[pos] = MD.dict_to_record(spec["record_cls"], args[pos])
        target = getattr(self.server_ref.storage, repo)()
        result = getattr(target, method)(*args)
        return self._send(200, {"result": _encode_result(result, result_kind)})


class StorageServer(HTTPServerBase):
    """DAO-level storage service over a locally-configured Storage."""

    def __init__(
        self,
        storage: Optional[Storage] = None,
        host: str = "0.0.0.0",
        port: int = DEFAULT_PORT,
        auth_key: Optional[str] = None,
        bind_retries: int = 3,
        scan_ttl: float = 600.0,
    ):
        self.storage = storage if storage is not None else get_storage()
        self.auth_key = auth_key
        self.scans = _ScanRegistry(ttl=scan_ttl)
        # bounded scan log (most recent entries) + lifetime totals: the
        # log is observability, not an audit trail — it must not grow
        # with request count on a long-running server
        self._scan_log: collections.deque = collections.deque(maxlen=1000)
        self._scan_totals = {"scans": 0, "rows": 0}
        self._scan_log_lock = threading.Lock()
        super().__init__(host, port, StorageRequestHandler, bind_retries=bind_retries)

    def record_scan(self, **entry: Any) -> None:
        with self._scan_log_lock:
            self._scan_log.append(entry)
            self._scan_totals["scans"] += 1
            self._scan_totals["rows"] += int(entry.get("rows", 0))

    def scan_stats(self) -> Dict[str, Any]:
        with self._scan_log_lock:
            scans = list(self._scan_log)
            totals = dict(self._scan_totals)
        return {
            "columnar_scans": scans,
            "columnar_scan_count": totals["scans"],
            "columnar_rows_served": totals["rows"],
            "live_scan_spools": self.scans.live_count(),
        }

    def stop(self) -> None:
        super().stop()
        self.scans.close()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="PIO-TPU storage server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument("--auth-key", default=None,
                        help="require X-PIO-Storage-Key on every request")
    args = parser.parse_args(argv)
    server = StorageServer(host=args.host, port=args.port, auth_key=args.auth_key)
    # SIGTERM closes the listening socket and drains in-flight scans
    # before exit — a kill mid-request must not drop the connection
    install_drain_handler(server)
    print(f"Storage server listening on {args.host}:{server.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
