"""The Event Server: REST event collection API, default port 7070.

Behavior contract from the reference (data/.../api/EventAPI.scala):

  - access-key auth on every data route: ``accessKey`` query param (or
    ``Authorization`` basic credentials), resolving to (appId,
    channelId); optional ``channel`` query param; failures are
    401 {"message": "Invalid accessKey."} / channel errors likewise
    (withAccessKey, EventAPI.scala:91-117)
  - ``POST /events.json`` — single event create -> 201 {"eventId": id};
    access keys may carry an allowed-event whitelist -> 403 on others
  - ``GET /events/<id>.json`` / ``DELETE /events/<id>.json`` — fetch /
    delete one event (EventAPI.scala:131)
  - ``GET /events.json`` — filtered query: startTime/untilTime (ISO),
    entityType/entityId, event (repeatable), targetEntityType/Id,
    limit (default 20, -1 = all), reversed (requires entityType+Id)
    (EventAPI.scala:209)
  - ``GET /`` — {"status": "alive"}; ``GET /stats.json`` — per-app op
    counters (EventAPI.scala:324)
  - ``POST /webhooks/<name>.json`` (JSON) and ``POST /webhooks/<name>``
    (form) via the connector registry; GET checks connector existence
    (EventAPI.scala:352-454)

The reference's spray/akka actor stack maps to a stdlib threading HTTP
server; Stats bookkeeping replaces the StatsActor.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.data.backends.eventlog import _ROW_ERRORS, JsonRowsUnsupported
from predictionio_tpu.data.event import Event, EventValidationError, validate_event, _parse_time
from predictionio_tpu.data.storage import UNSET, Storage, StorageError, get_storage
from predictionio_tpu.obs import dataobs, flight, perfacct
from predictionio_tpu.obs import logging as obs_logging
from predictionio_tpu.serving.http import (HTTPServerBase,
                                           JSONRequestHandler,
                                           install_drain_handler)
from predictionio_tpu.serving.stats import Stats
from predictionio_tpu.serving import webhooks as webhook_registry
from predictionio_tpu.serving.webhooks import ConnectorError

log = logging.getLogger(__name__)

DEFAULT_PORT = 7070  # ref: EventAPI.scala:494


class AuthError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


@dataclass
class AuthData:
    """ref: EventAPI.scala AuthData(appId, channelId, events)."""

    app_id: int
    channel_id: Optional[int]
    events: list


class EventServerCore:
    """Transport-independent request handling (also used by tests)."""

    def __init__(self, storage: Optional[Storage] = None, stats: Optional[Stats] = None):
        self.storage = storage or get_storage()
        self.stats = stats or Stats()

    # -- auth ---------------------------------------------------------------
    def authenticate(self, access_key: Optional[str], channel_name: Optional[str]) -> AuthData:
        """ref: withAccessKey (EventAPI.scala:91)."""
        if not access_key:
            raise AuthError(401, "Missing accessKey.")
        key = self.storage.access_keys().get(access_key)
        if key is None:
            raise AuthError(401, "Invalid accessKey.")
        channel_id = None
        if channel_name is not None:
            channels = self.storage.channels().get_by_app_id(key.appid)
            ch = next((c for c in channels if c.name == channel_name), None)
            if ch is None:
                raise AuthError(400, "Invalid channel.")
            channel_id = ch.id
        return AuthData(app_id=key.appid, channel_id=channel_id, events=list(key.events))

    # -- event CRUD ---------------------------------------------------------
    def create_event(self, auth: AuthData, payload: dict,
                     payload_bytes: Optional[int] = None) -> Tuple[int, dict]:
        if not isinstance(payload, dict):
            self.stats.update(auth.app_id, 400, "", "")
            return 400, {"message": "event must be a JSON object"}
        try:
            event = Event.from_dict(payload)
            validate_event(event)
        except (EventValidationError, ValueError, TypeError, AttributeError) as e:
            # bad field types / unparseable times are client errors too
            self.stats.update(auth.app_id, 400, payload.get("event", ""), payload.get("entityType", ""))
            return 400, {"message": str(e)}
        if auth.events and event.event not in auth.events:
            # per-key event whitelist (ref: AccessKeys events field)
            self.stats.update(auth.app_id, 403, event.event, event.entity_type)
            return 403, {"message": f"{event.event} events are not allowed"}
        try:
            event_id = self.storage.events().insert(event, auth.app_id, auth.channel_id)
        except StorageError as e:
            return 500, {"message": str(e)}
        self.stats.update(auth.app_id, 201, event.event, event.entity_type)
        # freshness clock (obs/perfacct.py): the single-event front-door
        # lane notes here — bulk lanes note inside their storage writers
        perfacct.note_ingest()
        # data plane (obs/dataobs.py): the 201 lane observes at full
        # fidelity — count, entities, schema, payload bytes; the
        # storage insert below the server stays observation-off
        dataobs.DATAOBS.observe_event(auth.app_id, event,
                                      payload_bytes=payload_bytes)
        return 201, {"eventId": event_id}

    def create_events_batch(self, auth: AuthData, raw_body: bytes) -> Tuple[int, Any]:
        """``POST /batch/events.json`` (ref: EventAPI.scala:252): a JSON
        array of events in, an array of per-event statuses out (201 with
        the eventId, or 400 with the validation message — one bad event
        never fails its batchmates).

        The fast lane hands the RAW request bytes to the native event
        log (EventLogEventStore.insert_json_batch): parse + validation +
        wire packing + append in one GIL-released call, no per-row
        Python objects. It engages when the store supports it and the
        access key has no event whitelist (a whitelist needs per-event
        allow/deny before insert); everything else — including payload
        shapes the native parser declines — falls back to the per-row
        Python path. Unlike the reference there is no 50-events cap
        (MaxNumberOfEventsPerBatchRequest): large batches are the point
        of the native lane."""
        store = self.storage.events()
        fast = getattr(store, "insert_json_batch", None)
        if fast is not None and not auth.events:
            try:
                ids, codes, names, etypes = fast(
                    raw_body, auth.app_id, auth.channel_id, strict=False)
            except JsonRowsUnsupported:
                pass  # the Python path below accepts more shapes
            except ValueError as e:
                return 400, {"message": str(e)}  # malformed body
            except StorageError as e:
                # an append I/O failure is a SERVER fault: a 400 would
                # make SDKs drop the events as permanently bad instead
                # of retrying (code-review regression)
                return 500, {"message": str(e)}
            else:
                results = []
                for eid, code, name, etype in zip(ids, codes, names, etypes):
                    if code == 0:
                        results.append({"status": 201, "eventId": eid})
                        self.stats.update(auth.app_id, 201, name, etype)
                    else:
                        results.append({
                            "status": 400,
                            "message": _ROW_ERRORS.get(
                                code, f"validation error {code}"),
                        })
                        self.stats.update(auth.app_id, 400, name, etype)
                return 200, results
        try:
            payload = json.loads(raw_body)
        except json.JSONDecodeError as e:
            return 400, {"message": f"invalid JSON: {e}"}
        if not isinstance(payload, list):
            return 400, {"message": "batch events must be a JSON array"}
        results = []
        for item in payload:
            status, body = self.create_event(auth, item)
            entry = {"status": status}
            entry.update(body)
            results.append(entry)
        return 200, results

    def get_event(self, auth: AuthData, event_id: str) -> Tuple[int, dict]:
        event = self.storage.events().get(event_id, auth.app_id, auth.channel_id)
        if event is None:
            return 404, {"message": "Not Found"}
        return 200, event.to_dict(api_format=False)

    def delete_event(self, auth: AuthData, event_id: str) -> Tuple[int, dict]:
        found = self.storage.events().delete(event_id, auth.app_id, auth.channel_id)
        if not found:
            return 404, {"message": "Not Found"}
        return 200, {"message": "Found"}

    def query_events(self, auth: AuthData, params: Dict[str, list]) -> Tuple[int, Any]:
        """ref: GET /events.json (EventAPI.scala:209)."""

        def one(name, default=None):
            vals = params.get(name)
            return vals[0] if vals else default

        try:
            start_time = _parse_iso(one("startTime"))
            until_time = _parse_iso(one("untilTime"))
        except ValueError as e:
            return 400, {"message": str(e)}
        entity_type = one("entityType")
        entity_id = one("entityId")
        event_names = params.get("event")
        target_entity_type = one("targetEntityType", UNSET)
        target_entity_id = one("targetEntityId", UNSET)
        try:
            limit = int(one("limit", "20"))
        except ValueError:
            return 400, {"message": "limit must be an integer."}
        if limit == 0 or limit < -1:
            return 400, {"message": "limit must be -1 (all) or positive."}
        reversed_flag = one("reversed", "false").lower() == "true"
        if reversed_flag and not (entity_type and entity_id):
            return 400, {
                "message": "the reversed parameter can only be used with both entityType and entityId specified."
            }
        events = self.storage.events().find(
            auth.app_id,
            channel_id=auth.channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=None if limit == -1 else limit,
            reversed=reversed_flag,
        )
        if not events:
            return 404, {"message": "Not Found"}
        return 200, [e.to_dict(api_format=False) for e in events]

    # -- webhooks -----------------------------------------------------------
    def webhook_json(self, auth: AuthData, name: str, payload: dict) -> Tuple[int, dict]:
        try:
            connector = webhook_registry.json_connector(name)
        except KeyError:
            return 404, {"message": f"webhook connection for {name} is not supported."}
        try:
            event_json = connector.to_event_json(payload)
        except ConnectorError as e:
            return 400, {"message": str(e)}
        return self.create_event(auth, event_json)

    def webhook_form(self, auth: AuthData, name: str, fields: Dict[str, str]) -> Tuple[int, dict]:
        try:
            connector = webhook_registry.form_connector(name)
        except KeyError:
            return 404, {"message": f"webhook connection for {name} is not supported."}
        try:
            event_json = connector.to_event_json(fields)
        except ConnectorError as e:
            return 400, {"message": str(e)}
        return self.create_event(auth, event_json)

    def webhook_exists(self, name: str, form: bool) -> Tuple[int, dict]:
        try:
            (webhook_registry.form_connector if form else webhook_registry.json_connector)(name)
            return 200, {"message": "Ok"}
        except KeyError:
            return 404, {"message": f"webhook connection for {name} is not supported."}


def _parse_iso(s: Optional[str]) -> Optional[_dt.datetime]:
    if s is None:
        return None
    try:
        return _parse_time(s)  # same parser as event bodies (data/event.py)
    except ValueError:
        raise ValueError(f"Invalid time string: {s}")


class _EventRequestHandler(JSONRequestHandler):
    server_version = "PIOEventServer/0.1"

    @property
    def core(self) -> EventServerCore:
        return self.server_ref.core

    def _auth(self, params) -> AuthData:
        access_key = (params.get("accessKey") or [None])[0]
        if not access_key:
            # Basic credentials with the key as username
            # (ref: withAccessKey also accepts HTTP credentials, EventAPI.scala:91)
            header = self.headers.get("Authorization", "")
            if header.startswith("Basic "):
                import base64

                try:
                    decoded = base64.b64decode(header[6:]).decode()
                    access_key = decoded.split(":", 1)[0]
                except (ValueError, UnicodeDecodeError) as e:
                    # binascii.Error is a ValueError subclass; a garbled
                    # header just means "no credentials" (401 follows),
                    # but leave a trace for operators debugging clients
                    log.warning("ignoring malformed Basic auth header: %s", e)
        channel = (params.get("channel") or [None])[0]
        return self.core.authenticate(access_key, channel)

    def _dispatch(self, method: str) -> None:
        url = urlparse(self.path)
        path = url.path
        params = parse_qs(url.query)
        try:
            if path == "/" and method == "GET":
                self._send(200, {"status": "alive"})
                return
            if path == "/stats.json" and method == "GET":
                auth = self._auth(params)
                self._send(200, self.core.stats.report(auth.app_id))
                return
            if path == "/events.json":
                auth = self._auth(params)
                if method == "POST":
                    body = self._read_body()
                    try:
                        payload = json.loads(body or b"{}")
                    except json.JSONDecodeError as e:
                        self._send(400, {"message": f"invalid JSON: {e}"})
                        return
                    self._send(*self.core.create_event(
                        auth, payload, payload_bytes=len(body)))
                elif method == "GET":
                    self._send(*self.core.query_events(auth, params))
                else:
                    self._send(405, {"message": "method not allowed"})
                return
            if path == "/batch/events.json":
                auth = self._auth(params)
                if method != "POST":
                    self._send(405, {"message": "method not allowed"})
                    return
                # RAW body bytes: the native lane parses them itself
                self._send(*self.core.create_events_batch(
                    auth, self._read_body()))
                return
            if path.startswith("/events/") and path.endswith(".json"):
                auth = self._auth(params)
                event_id = path[len("/events/"):-len(".json")]
                if method == "GET":
                    self._send(*self.core.get_event(auth, event_id))
                elif method == "DELETE":
                    self._send(*self.core.delete_event(auth, event_id))
                else:
                    self._send(405, {"message": "method not allowed"})
                return
            if path.startswith("/webhooks/"):
                name = path[len("/webhooks/"):]
                is_json = name.endswith(".json")
                if is_json:
                    name = name[:-len(".json")]
                auth = self._auth(params)
                if method == "GET":
                    self._send(*self.core.webhook_exists(name, form=not is_json))
                    return
                if method != "POST":
                    self._send(405, {"message": "method not allowed"})
                    return
                if is_json:
                    try:
                        payload = self._read_json()
                    except json.JSONDecodeError as e:
                        self._send(400, {"message": f"invalid JSON: {e}"})
                        return
                    self._send(*self.core.webhook_json(auth, name, payload))
                else:
                    fields = {
                        k: v[0]
                        for k, v in parse_qs(
                            self._read_body().decode(), keep_blank_values=True
                        ).items()
                    }
                    self._send(*self.core.webhook_form(auth, name, fields))
                return
            self._send(404, {"message": "Not Found"})
        except AuthError as e:
            self._send(e.status, {"message": e.message})
        except Exception as e:  # pragma: no cover - defensive 500
            log.exception("event server error")
            # name the failure in the request's flight record (the
            # answered 500 never raises through the wrapper)
            flight.note_field("error", f"{type(e).__name__}: {e}")
            self._send(500, {"message": str(e)})

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


class EventServer(HTTPServerBase):
    """ref: EventServer.createEventServer (EventAPI.scala:497)."""

    def __init__(
        self,
        storage: Optional[Storage] = None,
        host: str = "0.0.0.0",
        port: int = DEFAULT_PORT,
        stats: Optional[Stats] = None,
    ):
        self.core = EventServerCore(storage, stats)
        super().__init__(host, port, _EventRequestHandler)


def main(argv=None) -> None:
    """Standalone runner (ref: EventServer Run main, EventAPI.scala:519)."""
    import argparse

    parser = argparse.ArgumentParser(description="PredictionIO-TPU event server")
    parser.add_argument("--ip", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    args = parser.parse_args(argv)
    # structured JSON log lines with trace-id correlation (obs/logging)
    obs_logging.setup(level=logging.INFO)
    server = EventServer(host=args.ip, port=args.port)
    # SIGTERM closes the listening socket and drains in-flight events
    # before exit — a kill mid-request must not drop the connection
    install_drain_handler(server)
    server.serve_forever()


if __name__ == "__main__":
    main()
