"""Replica supervisor: N engine-server replicas as one serving fleet.

The reference deploys each trained engine as ONE process
(CreateServer / ``GET /reload``) — one crash or one mid-traffic reload
away from an outage. This module is the redundancy half of the fleet
story (serving/router.py is the routing half):

  spawn      N engine-server replicas — subprocesses on ephemeral
             ports in production (``pio deploy --replicas N``), or
             in-process threaded servers for tier-1 CPU tests (same
             HTTP surface, so the supervisor/router code path is
             identical in both modes)
  monitor    a supervision loop probes each replica's existing
             ``GET /readyz``: a failing probe EVICTS the replica from
             rotation (the router stops selecting it), a succeeding
             one re-admits it — readiness, not liveness, drives
             placement
  restart    a replica that stops answering (process exit, closed
             socket) is restarted under the resilience layer's
             full-jitter backoff (resilience/policy.py), with the
             attempt counter reset after a stable period — crash loops
             back off, one-off crashes restart fast
  hot-swap   :meth:`FleetSupervisor.rolling_reload` rolls the fleet
             onto the newest COMPLETED instance one replica at a time:
             drain from rotation, ``GET /reload`` (load + warm BEFORE
             the in-replica swap, serving/engine_server.py), rejoin —
             live traffic never waits on a compile and the fleet never
             drops below N-1 ready replicas
  canary     :meth:`FleetSupervisor.start_canary` puts the newest
             COMPLETED instance on EXACTLY ONE replica through the
             same drain→reload→rejoin machinery; the router then tags
             per-lane latency histograms and samples paired answers
             (serving/router.py), obs/quality.py renders the
             promote/rollback verdict, and the supervisor acts on it
             automatically (``PIO_CANARY_AUTO``, default on): promote
             = rolling-swap the rest of the fleet onto the candidate,
             rollback = swap the canary replica BACK onto the baseline
             instance (``GET /reload?instance=<baseline>``). With
             ``canary_mode`` (``pio deploy --canary`` /
             ``PIO_FLEET_CANARY=1``) the auto-swap watch starts a
             canary instead of a full rolling swap when a new
             COMPLETED instance lands — train-to-serving with a
             quality gate and no operator in the loop.

Observability: ``pio_fleet_replica_up{replica}``,
``pio_fleet_replica_version{replica,version}``,
``pio_fleet_restarts_total{replica}``, ``pio_fleet_ready_replicas``,
a ``fleet`` readiness probe, a ``fleet.ready`` timeline series, and
the ``GET/POST /admin/fleet`` surface (serving/http.py) on whichever
server holds the supervisor (normally the router).

Env knobs: ``PIO_REPLICAS`` (deploy default), ``PIO_FLEET_PROBE_SEC``
(supervision cadence, default 0.5), ``PIO_FLEET_PROBE_DEADLINE``
(per-probe timeout, default 2), ``PIO_FLEET_BACKOFF_BASE`` /
``PIO_FLEET_BACKOFF_CAP`` (restart backoff, default 0.5/30),
``PIO_FLEET_WATCH_SEC`` (auto rolling swap on a new COMPLETED
instance; 0 = manual, the default), ``PIO_DRAIN_TIMEOUT`` (drain
window per replica, shared with the SIGTERM handler).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from predictionio_tpu.obs import health, journal, metrics, timeline, trace
from predictionio_tpu.resilience.policy import Policy
from predictionio_tpu.serving.http import drain_timeout

log = logging.getLogger(__name__)

# replica lifecycle states
STARTING = "starting"    # launched, first ready probe pending
READY = "ready"          # in rotation
EVICTED = "evicted"      # alive but failing readiness; out of rotation
DRAINING = "draining"    # deliberately out of rotation (swap/admin)
DEAD = "dead"            # unreachable; restart scheduled under backoff
STOPPED = "stopped"      # terminated on purpose; never restarted

#: consecutive transport-level probe failures before a replica is
#: declared DEAD (a single blip only evicts)
CRASH_THRESHOLD = 2
#: seconds after launch() during which a STARTING replica whose
#: process is still alive may refuse connections without being
#: declared dead: a subprocess replica's boot includes the jax import,
#: model load and warm-up compiles — killing a slow boot respawns an
#: equally slow boot, forever (``PIO_FLEET_STARTUP_GRACE`` overrides)
DEFAULT_STARTUP_GRACE_SEC = 180.0
#: seconds of uninterrupted readiness after which the restart-backoff
#: attempt counter resets (a once-a-day crash should restart fast)
STABLE_RESET_SEC = 30.0

_REPLICA_UP = metrics.gauge(
    "pio_fleet_replica_up",
    "1 while the replica is in rotation (READY), else 0",
    ("replica",),
)
_REPLICA_VERSION = metrics.gauge(
    "pio_fleet_replica_version",
    "1 for the engine instance a replica currently serves (the rolling "
    "swap is observable as this label moving replica by replica)",
    ("replica", "version"),
)
_RESTARTS = metrics.counter(
    "pio_fleet_restarts_total",
    "Supervisor-initiated replica restarts after a crash",
    ("replica",),
)
_READY_GAUGE = metrics.gauge(
    "pio_fleet_ready_replicas",
    "Replicas currently in rotation",
)
_SWAPS = metrics.counter(
    "pio_fleet_rolling_swaps_total",
    "Rolling hot-swaps completed, by outcome",
    ("outcome",),
)

#: supervisors running in THIS process (dashboard /fleet panel; the
#: threaded tier-1 mode and `pio deploy --replicas` both land here)
ACTIVE: List["FleetSupervisor"] = []


def _free_port() -> int:
    """An ephemeral port for a subprocess replica (bind-and-release;
    the tiny reuse race is covered by the engine server's bind retry)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Replica:
    """One supervised replica: state, version, and the router's
    outstanding-request count (the power-of-two-choices load signal)."""

    def __init__(self, name: str):
        self.name = name
        self.lock = threading.Lock()
        self.state = STOPPED
        self.version: Optional[str] = None
        self.restarts = 0
        self.probe_failures = 0
        self.backoff_attempt = 0
        self.next_restart_at = 0.0    # monotonic
        self.ready_since = 0.0        # monotonic
        self.launched_at = 0.0        # monotonic; set by the supervisor
        self.last_probe: Optional[Dict[str, Any]] = None
        self._outstanding = 0
        _REPLICA_UP.labels(name).set(0.0)

    # -- mode-specific hooks -------------------------------------------------
    @property
    def port(self) -> int:
        raise NotImplementedError

    def launch(self) -> None:
        raise NotImplementedError

    def terminate(self, drain: bool = True) -> None:
        raise NotImplementedError

    def request_stop(self) -> None:
        """Begin an asynchronous stop where the mode supports one (a
        subprocess gets its SIGTERM now, drains while its siblings
        drain); ``terminate()`` still completes the teardown. Fleet
        shutdown signals every replica first so the worst case is ONE
        drain window, not N of them stacked sequentially."""

    def process_alive(self) -> Optional[bool]:
        """False when the replica's process/loop is definitely gone;
        None when only the probe can tell (subprocess still running,
        threaded server object present)."""
        return None

    # -- router-side load accounting -----------------------------------------
    def begin_request(self) -> None:
        with self.lock:
            self._outstanding += 1

    def end_request(self) -> None:
        with self.lock:
            self._outstanding = max(0, self._outstanding - 1)

    def outstanding(self) -> int:
        with self.lock:
            return self._outstanding

    # -- shared plumbing -----------------------------------------------------
    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            outstanding = self._outstanding
        return {
            "name": self.name,
            "mode": type(self).__name__.replace("Replica", "").lower(),
            "port": self.port if self.state != DEAD else None,
            "state": self.state,
            "version": self.version,
            "restarts": self.restarts,
            "outstanding": outstanding,
            "lastProbe": self.last_probe,
        }


class ThreadedReplica(Replica):
    """An in-process engine server on an ephemeral port — the tier-1
    CPU mode. Same HTTP surface as a subprocess replica, so the
    supervisor, router and chaos tests exercise the production path."""

    def __init__(self, name: str, factory: Callable[[str], Any]):
        super().__init__(name)
        self._factory = factory
        self.server = None

    @property
    def port(self) -> int:
        return self.server.port if self.server is not None else 0

    def launch(self) -> None:
        self.server = self._factory(self.name).start()

    def terminate(self, drain: bool = True) -> None:
        server, self.server = self.server, None
        if server is None:
            return
        try:
            server.stop()
        except Exception:  # noqa: BLE001 — a half-dead server (killed
            # socket) must not fail the restart that replaces it
            log.exception("stopping threaded replica %s failed", self.name)

    def process_alive(self) -> Optional[bool]:
        if self.server is None:
            return False
        try:
            # a closed listening socket (fileno -1) IS this mode's
            # "process exited": kill() and real OSError deaths leave
            # the server object in place, so presence alone can't
            # clear a DRAINING replica whose loop died
            if self.server.httpd.socket.fileno() < 0:
                return False
        except (OSError, AttributeError):
            return False
        return None

    def kill(self) -> None:
        """Chaos hook: die like a crashed process — the listening
        socket closes abruptly (new connections refused, serve loop
        dead), nothing is drained or deregistered."""
        if self.server is not None:
            try:
                self.server.httpd.socket.close()
            except OSError:
                pass


class SubprocessReplica(Replica):
    """A child ``pio deploy`` on an ephemeral port — the production
    mode. SIGTERM on terminate: the child's install_drain_handler
    (serving/http.py) drains in-flight requests before exiting."""

    def __init__(self, name: str, argv: List[str],
                 env: Optional[Dict[str, str]] = None):
        super().__init__(name)
        #: argv with a ``{port}`` placeholder, e.g.
        #: [sys.executable, "-m", "predictionio_tpu.tools.cli",
        #:  "deploy", "--engine-json", "engine.json",
        #:  "--ip", "127.0.0.1", "--port", "{port}"]
        self._argv = argv
        self._env = env or {}
        self._port = 0
        self.proc: Optional[subprocess.Popen] = None
        self._term_sent = False

    @property
    def port(self) -> int:
        return self._port

    def launch(self) -> None:
        self._port = _free_port()
        argv = [a.format(port=self._port) for a in self._argv]
        # PIO_REPLICAS must not leak into the child: a replica is a
        # single server by definition (see deploy_fleet_argv — this is
        # the second belt on the fork-bomb guard)
        env = {**os.environ, **self._env, "PIO_CHAOS_TAG": self.name,
               "PIO_REPLICAS": "1"}
        self.proc = subprocess.Popen(argv, env=env)
        self._term_sent = False
        log.info("replica %s: spawned pid %d on port %d", self.name,
                 self.proc.pid, self._port)

    def request_stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            self._term_sent = True

    def terminate(self, drain: bool = True) -> None:
        proc, self.proc = self.proc, None
        if proc is None or proc.poll() is not None:
            return
        if not self._term_sent:
            # a SECOND SIGTERM would spawn a second concurrent drain
            # thread in the child — signal exactly once
            proc.terminate()  # SIGTERM -> child drains via its handler
        self._term_sent = False
        try:
            proc.wait(timeout=(drain_timeout() + 5.0) if drain else 5.0)
        except subprocess.TimeoutExpired:
            log.warning("replica %s ignored SIGTERM; killing", self.name)
            proc.kill()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                log.error("replica %s unkillable (pid %d)", self.name,
                          proc.pid)

    def process_alive(self) -> Optional[bool]:
        return False if (self.proc is None
                         or self.proc.poll() is not None) else None


def threaded_fleet(n: int, factory: Callable[[str], Any],
                   prefix: str = "r") -> List[ThreadedReplica]:
    """N threaded replicas named ``r0..rN-1``; ``factory(name)`` must
    return an UNstarted EngineServer bound to port 0."""
    return [ThreadedReplica(f"{prefix}{i}", factory) for i in range(n)]


def subprocess_fleet(n: int, argv: List[str],
                     env: Optional[Dict[str, str]] = None,
                     prefix: str = "r") -> List[SubprocessReplica]:
    return [SubprocessReplica(f"{prefix}{i}", argv, env)
            for i in range(n)]


class FleetSupervisor:
    """Owns the replicas: spawn, probe, evict/re-admit, restart with
    backoff, and coordinate the rolling hot-swap."""

    def __init__(
        self,
        replicas: List[Replica],
        probe_interval: Optional[float] = None,
        restart_policy: Optional[Policy] = None,
        version_source: Optional[Callable[[], Optional[str]]] = None,
        backoff: Optional[Callable[[int], float]] = None,
        canary_mode: Optional[bool] = None,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas = list(replicas)
        self._probe_interval = probe_interval
        self._policy = restart_policy or Policy(
            deadline=metrics.env_float("PIO_FLEET_PROBE_DEADLINE", 2.0),
            retries=0,
            backoff_base=metrics.env_float("PIO_FLEET_BACKOFF_BASE", 0.5),
            backoff_cap=metrics.env_float("PIO_FLEET_BACKOFF_CAP", 30.0),
        )
        # injectable for deterministic backoff tests; defaults to the
        # policy's full-jitter schedule
        self._backoff = backoff or self._policy.backoff_seconds
        #: latest COMPLETED instance id (storage watch) — drives the
        #: optional auto-swap and names the swap target in snapshots
        self._version_source = version_source
        self._stop_evt = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._swap_lock = threading.Lock()
        self._swap_thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self._swap: Dict[str, Any] = {"active": False, "last": None}
        self._last_watch = 0.0
        #: None = read PIO_FLEET_CANARY at watch time; explicit bool =
        #: `pio deploy --canary` / tests
        self._canary_mode = canary_mode
        self._canary: Dict[str, Any] = {"active": False, "last": None}
        self._canary_thread: Optional[threading.Thread] = None
        #: hot-path copy of the active canary replica's name (plain
        #: attribute read — the router checks it on every answer)
        self._canary_name: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        for replica in self.replicas:
            self._launch(replica)
        health.REGISTRY.register("fleet", self._fleet_probe)
        timeline.TIMELINE.add_collector(self._timeline_collector)
        ACTIVE.append(self)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True, name="fleet-monitor")
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        self._canary_name = None  # routers must stop shadow-sampling now
        if self._monitor is not None:
            self._monitor.join(timeout=10)
        # signal everyone first (subprocess drains run in PARALLEL —
        # sequential terminate() would stack up to N drain windows and
        # blow through orchestrator stop timeouts), then reap each
        for replica in self.replicas:
            self._set_state(replica, STOPPED)
            replica.request_stop()
        for replica in self.replicas:
            replica.terminate()
            # retire this fleet's per-replica series: a later fleet in
            # the same process (bench's 1/2/4 sweep) must not inherit
            # phantom replicas still exported at 0 / on an old version
            _REPLICA_UP.remove(replica.name)
            if replica.version:
                _REPLICA_VERSION.remove(replica.name, replica.version)
        health.REGISTRY.unregister("fleet", self._fleet_probe)
        timeline.TIMELINE.remove_collector(self._timeline_collector)
        if self in ACTIVE:
            ACTIVE.remove(self)
        _READY_GAUGE.set(0.0)

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 60.0) -> bool:
        """Block until ``n`` (default: all) replicas are READY."""
        want = len(self.replicas) if n is None else n
        return self._await(lambda: self.ready_count() >= want, timeout)

    # -- rotation view (the router reads these) ------------------------------
    def ready_replicas(self) -> List[Replica]:
        return [r for r in self.replicas if r.state == READY]

    def ready_count(self) -> int:
        return len(self.ready_replicas())

    def size(self) -> int:
        return len(self.replicas)

    # -- supervision loop ----------------------------------------------------
    def probe_interval(self) -> float:
        if self._probe_interval is not None:
            return self._probe_interval
        return max(0.05, metrics.env_float("PIO_FLEET_PROBE_SEC", 0.5))

    def _monitor_loop(self) -> None:
        while not self._stop_evt.wait(self.probe_interval()):
            try:
                for replica in list(self.replicas):
                    if self._stop_evt.is_set():
                        return
                    self._tick(replica)
                self._maybe_auto_swap()
                self._maybe_canary_decision()
                _READY_GAUGE.set(float(self.ready_count()))
            except Exception:  # noqa: BLE001 — the supervisor dying
                # silently IS the outage this module exists to prevent
                log.exception("fleet monitor iteration failed")

    def _tick(self, replica: Replica) -> None:
        if replica.state == STOPPED:
            return
        if replica.state == DRAINING:
            # a drain parks the replica out of rotation on purpose, so
            # no probing (a green probe must not re-admit it) — but a
            # crash while parked must still be noticed, or an
            # operator-held replica whose process died reads
            # "draining" (with a live-looking port) forever
            if replica.process_alive() is False:
                self._mark_dead(replica, "process exited while draining")
            return
        if replica.state == DEAD:
            if time.monotonic() >= replica.next_restart_at:
                self._restart(replica)
            return
        if replica.process_alive() is False:
            self._mark_dead(replica, "process exited")
            return
        self.probe_and_update(replica)

    def probe_and_update(self, replica: Replica) -> None:
        """One readiness probe, state updated from the verdict. Called
        by the monitor each tick and by the rolling swap's waits (the
        swap must not be hostage to the monitor cadence). DRAINING is
        deliberate (an operator's or the swap's own eviction) and
        DEAD/STOPPED are terminal-until-restart: a green probe must
        never silently overrule them."""
        if replica.state in (DRAINING, DEAD, STOPPED):
            return
        status, body = self._probe(replica)
        if replica.state in (DRAINING, DEAD, STOPPED):
            # the state changed under the (up to deadline-long) probe —
            # an operator drain, the swap's own eviction, or a
            # concurrent death verdict. Acting on the stale probe here
            # would put a deliberately-drained replica back in rotation.
            return
        if status is None:
            # a STARTING replica whose process is alive gets a boot
            # grace window: connection-refused during the jax import /
            # model load / warm-up is a slow boot, not a crash —
            # restarting it would respawn an equally slow boot forever
            if (replica.state == STARTING
                    and replica.process_alive() is not False
                    and time.monotonic() - replica.launched_at
                    < metrics.env_float("PIO_FLEET_STARTUP_GRACE",
                                        DEFAULT_STARTUP_GRACE_SEC)):
                return
            replica.probe_failures += 1
            if replica.probe_failures >= CRASH_THRESHOLD:
                self._mark_dead(replica, str(body))
            else:
                self._set_state(replica, EVICTED)
            return
        replica.probe_failures = 0
        replica.last_probe = {"status": status,
                              "overall": (body or {}).get("status")}
        if status == 200:
            if replica.state != READY:
                self._refresh_version(replica)
                replica.ready_since = time.monotonic()
                self._set_state(replica, READY)
            elif replica.backoff_attempt and (
                    time.monotonic() - replica.ready_since
                    > STABLE_RESET_SEC):
                replica.backoff_attempt = 0
        else:
            # alive but not ready (readyz FAILED): out of rotation
            # until the probe greens — eviction, not a restart
            self._set_state(replica, EVICTED)

    def _probe(self, replica: Replica):
        """(status, parsed body) — (None, error) on transport failure."""
        try:
            req = urllib.request.Request(f"{replica.base_url}/readyz",
                                         headers=trace.traced_headers())
            with urllib.request.urlopen(
                    req, timeout=self._policy.deadline) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except (ValueError, OSError):
                body = {}
            return e.code, body
        except (OSError, ValueError) as e:
            return None, f"{type(e).__name__}: {e}"

    def _refresh_version(self, replica: Replica) -> None:
        """The engine instance a replica serves, from its status page
        (works identically for threaded and subprocess replicas)."""
        if self._stop_evt.is_set():
            # stop() retires this fleet's per-replica series; a
            # straggling swap thread must not re-mint them
            return
        try:
            req = urllib.request.Request(f"{replica.base_url}/",
                                         headers=trace.traced_headers())
            with urllib.request.urlopen(
                    req, timeout=self._policy.deadline) as resp:
                status = json.loads(resp.read() or b"{}")
        except (OSError, ValueError):
            return
        version = status.get("engineInstanceId")
        if version and version != replica.version:
            if replica.version:
                _REPLICA_VERSION.remove(replica.name, replica.version)
            replica.version = version
            _REPLICA_VERSION.labels(replica.name, version).set(1.0)

    def _set_state(self, replica: Replica, state: str,
                   deliberate: bool = False) -> None:
        """``deliberate`` marks an operator/swap transition; without it
        a probe-driven READY or EVICTED write loses to a concurrent
        drain/death verdict."""
        with self._state_lock:
            if replica.state == state:
                return
            if state != STOPPED and self._stop_evt.is_set():
                # stop() owns every replica's final state: a rolling
                # swap still in flight (it checks the stop event only
                # BETWEEN replicas, and _reload can block minutes) must
                # not flip a STOPPED replica back or re-mint the gauge
                # children stop() just removed
                return
            if (not deliberate and state in (READY, EVICTED)
                    and replica.state in (DRAINING, DEAD, STOPPED)):
                # a probe verdict racing a concurrent drain/death: the
                # deliberate transition wins (probe_and_update's
                # re-check closes the wide window; this closes the
                # residual one between that re-check and the write —
                # for BOTH probe outcomes: a green probe must not
                # readmit a drained replica, and a failed probe must
                # not flip it to EVICTED, where the next green probe
                # would readmit it)
                return
            old = replica.state
            replica.state = state
            _REPLICA_UP.labels(replica.name).set(
                1.0 if state == READY else 0.0)
        journal.emit("replica_state", replica=replica.name, prev=old,
                     state=state, deliberate=deliberate)
        log.info("replica %s: %s -> %s", replica.name, old, state)

    def _mark_dead(self, replica: Replica, reason: str) -> None:
        if replica.state == DEAD:
            return
        self._schedule_restart(replica, reason)

    def _schedule_restart(self, replica: Replica, reason: str) -> None:
        delay = self._backoff(replica.backoff_attempt)
        replica.backoff_attempt += 1
        replica.next_restart_at = time.monotonic() + delay
        self._set_state(replica, DEAD)
        log.warning("replica %s dead (%s); restart #%d in %.2fs",
                    replica.name, reason, replica.restarts + 1, delay)

    def _launch(self, replica: Replica) -> None:
        try:
            replica.launch()
            replica.probe_failures = 0
            replica.launched_at = time.monotonic()
            self._set_state(replica, STARTING)
        except Exception:  # noqa: BLE001 — a failed spawn re-enters
            # the backoff schedule instead of crashing the supervisor.
            # Restarts arrive here already DEAD, where _mark_dead's
            # idempotence guard would skip rescheduling and the next
            # monitor tick would retry the failing launch immediately —
            # schedule the next attempt unconditionally.
            log.exception("launching replica %s failed", replica.name)
            self._schedule_restart(replica, "launch failed")

    def _restart(self, replica: Replica) -> None:
        _RESTARTS.labels(replica.name).inc()
        replica.restarts += 1
        replica.terminate(drain=False)  # clear any half-dead remnant
        self._launch(replica)

    # -- rolling hot-swap ----------------------------------------------------
    def rolling_reload(self, force: bool = False) -> Dict[str, Any]:
        """Roll every live replica onto the newest COMPLETED instance,
        one at a time: wait for the REST of the fleet to be ready,
        drain this replica from rotation (router in-flight falls to
        zero), ``GET /reload`` (load + warm happens before the
        in-replica swap, so the replica itself never serves a cold
        model), then rejoin before the next replica drains — the fleet
        never drops below N-1 ready replicas and traffic never waits
        on a compile. DEAD replicas are skipped: their restart path
        already boots from the latest instance. ``force`` overrides
        each replica's device-memory preflight (obs/memacct.py — a
        refusal otherwise answers 507 and the replica rejoins on its
        old model)."""
        with self._swap_lock:
            with self._state_lock:
                self._swap = {"active": True, "started_unix": time.time(),
                              "last": self._swap.get("last")}
            journal.emit("swap", phase="start", forced=force)
            result = self._rolling_reload_locked(force=force)
            with self._state_lock:
                self._swap = {"active": False, "last": result}
            _SWAPS.labels(result["outcome"]).inc()
            journal.emit("swap", phase="end",
                         outcome=result["outcome"],
                         swapped=result["swapped"],
                         errors=len(result["errors"]) or None,
                         version=result["version"])
            return result

    def _rolling_reload_locked(self, force: bool = False) -> Dict[str, Any]:
        swapped: List[str] = []
        errors: List[str] = []
        for replica in list(self.replicas):
            if self._stop_evt.is_set():
                errors.append("fleet stopping")
                break
            if replica.state in (DEAD, STOPPED):
                continue
            if replica.state == DRAINING:
                # operator-held (pio fleet --drain): the swap must not
                # reload-and-readmit a replica someone deliberately
                # pulled for debugging — it picks the new version up
                # whenever it is readmitted or restarted
                errors.append(f"{replica.name}: operator-drained; "
                              "skipped")
                continue
            outcome = self._swap_one(replica, errors, force=force)
            if outcome == "abort":
                break
            if outcome == "swapped":
                swapped.append(replica.name)
        return {
            "outcome": "ok" if not errors else "partial",
            "swapped": swapped,
            "errors": errors,
            "version": self.version(),
            "finished_unix": round(time.time(), 3),
        }

    def _swap_one(self, replica: Replica, errors: List[str],
                  instance_id: Optional[str] = None,
                  force: bool = False) -> str:
        """Drain→reload→rejoin ONE replica under the fleet's N-1 floor
        guards — the shared core of the rolling swap and the canary
        lane (``instance_id`` targets a specific completed instance,
        the canary rollback; ``force`` overrides the replica's
        device-memory preflight). Appends operator-facing error
        strings; returns "swapped", "skip" (this replica failed/was
        skipped but siblings may proceed) or "abort" (the fleet never
        converged — nothing later can safely drain either)."""
        # hold the N-1 floor: every OTHER live replica must be
        # back in rotation before this one leaves it
        if not self._await_others_ready(replica, timeout=60.0):
            errors.append(f"{replica.name}: fleet never converged "
                          "to ready before drain")
            return "abort"
        # _await_others_ready converges VACUOUSLY when every peer
        # is DEAD/STOPPED — draining the last ready replica would
        # take the fleet to zero for a whole reload+warm window.
        # Skip it; dead peers boot onto the new version anyway.
        if not any(p.state == READY for p in self.replicas
                   if p is not replica):
            errors.append(f"{replica.name}: only ready replica — "
                          "refusing to drain the fleet to zero")
            return "skip"
        self._set_state(replica, DRAINING)
        if not self._await(lambda: replica.outstanding() == 0,
                           timeout=drain_timeout()):
            errors.append(f"{replica.name}: drain window expired "
                          f"with {replica.outstanding()} in flight")
            # proceed anyway: the replica keeps answering its
            # stragglers from the OLD model while it reloads
        status, body = self._reload(replica, instance_id, force=force)
        if status == 507:
            # the replica's OOM preflight (obs/memacct.py) refused the
            # candidate: a capacity verdict, not a failure — the
            # replica rejoins on its old model and the reason (sizes,
            # headroom) surfaces through `pio fleet` / /admin/fleet;
            # retry with {"force": true} to override
            errors.append(f"{replica.name}: preflight refused the "
                          f"deploy (507 insufficient device memory): "
                          f"{body}")
            journal.emit("preflight_refused", replica=replica.name,
                         instance=instance_id, detail=str(body)[:200])
        elif status != 200:
            errors.append(f"{replica.name}: reload answered "
                          f"{status}: {body}")
        if status != 200:
            # re-enter rotation on the old model: a failed swap
            # must degrade to "stale replica", never "lost replica"
            self._set_state(replica, EVICTED, deliberate=True)
            self.probe_and_update(replica)
            return "skip"
        self._refresh_version(replica)
        self._set_state(replica, EVICTED, deliberate=True)
        if not self._await(lambda: replica.state == READY,
                           timeout=60.0, probe=replica):
            errors.append(f"{replica.name}: not ready after reload")
            return "skip"
        return "swapped"

    def _reload(self, replica: Replica,
                instance_id: Optional[str] = None,
                force: bool = False):
        """One replica's ``GET /reload`` — generous timeout: the warm
        compile is exactly what we drained the replica to hide. With
        ``instance_id``, the replica reloads that SPECIFIC completed
        instance (``?instance=`` — the canary rollback lane);
        ``force=1`` overrides its device-memory preflight."""
        try:
            params = []
            if instance_id:
                params.append(
                    "instance=" + urllib.parse.quote(instance_id))
            if force:
                params.append("force=1")
            url = f"{replica.base_url}/reload"
            if params:
                url += "?" + "&".join(params)
            req = urllib.request.Request(
                url, headers=trace.traced_headers())
            reload_timeout = metrics.env_float(
                "PIO_FLEET_RELOAD_TIMEOUT", 300.0)
            with urllib.request.urlopen(req, timeout=reload_timeout) as resp:  # graftlint: disable=JT21 — _swap_lock exists to serialize rolling swaps fleet-wide: one replica drains/reloads at a time BY DESIGN; a concurrent swap is the outage this wait prevents
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode(errors="replace")[:200]
        except (OSError, ValueError) as e:
            return None, f"{type(e).__name__}: {e}"

    def _await(self, predicate: Callable[[], bool], timeout: float,
               probe: Optional[Replica] = None) -> bool:
        """Poll ``predicate`` to ``timeout``; with ``probe`` given, also
        re-probe that replica — PACED at the fleet's probe interval
        (the predicate polls at 50 Hz, but each probe is a full /readyz
        round on the target incl. storage round-trips; firing those at
        poll speed would hammer a replica that is busy converging)."""
        deadline = time.monotonic() + timeout
        interval = self.probe_interval()
        next_probe = 0.0
        while time.monotonic() < deadline:
            if predicate():
                return True
            now = time.monotonic()
            if probe is not None and now >= next_probe:
                self.probe_and_update(probe)
                next_probe = now + interval
            time.sleep(0.02)
        return bool(predicate())

    def _await_others_ready(self, replica: Replica,
                            timeout: float) -> bool:
        """Wait for every live replica EXCEPT ``replica`` to be READY,
        probing the laggards directly (the swap must not be hostage to
        the monitor's tick alignment) — paced at the probe interval,
        same rationale as ``_await``."""
        interval = self.probe_interval()
        next_probe = [0.0]

        def others_converged() -> bool:
            converged = True
            now = time.monotonic()
            may_probe = now >= next_probe[0]
            if may_probe:
                next_probe[0] = now + interval
            for peer in self.replicas:
                # DRAINING peers are operator-held: waiting on them
                # would deadlock the swap, probing them would readmit
                # them against the operator's intent — neither
                if peer is replica or peer.state in (DEAD, STOPPED,
                                                     DRAINING):
                    continue
                if peer.state != READY:
                    if may_probe:
                        self.probe_and_update(peer)
                    converged = converged and peer.state == READY
            return converged

        return self._await(others_converged, timeout)

    def start_rolling_reload(self, force: bool = False) -> bool:
        """Kick a rolling swap on a background thread (the admin/route
        entry point — a swap can take minutes of warm compile per
        replica). False when one is already running. ``force``
        overrides each replica's device-memory preflight."""
        with self._state_lock:
            # check-and-spawn atomically: two concurrent callers (an
            # operator /reload racing the auto-swap watch) must not both
            # see "no swap running" and queue two back-to-back swaps
            if self._stop_evt.is_set():
                return False
            if self._swap.get("active"):
                return False
            if self._canary.get("active") or (
                    self._canary_thread is not None
                    and self._canary_thread.is_alive()):
                # rolling everything would silently promote the
                # candidate — including during the DEPLOY window, where
                # _canary["active"] is still False but the canary
                # thread is mid-drain/reload; the canary verdict (or an
                # explicit promote/rollback) owns leaving the canary
                # state
                return False
            if (self._swap_thread is not None
                    and self._swap_thread.is_alive()):
                return False
            self._swap_thread = threading.Thread(
                target=self._swap_guarded, args=(force,), daemon=True,
                name="fleet-swap")
            self._swap_thread.start()
            return True

    def _swap_guarded(self, force: bool = False) -> None:
        try:
            self.rolling_reload(force=force)
        except Exception:  # noqa: BLE001 — a crashed background swap
            # must leave a visible verdict, not a forever-"active" state
            log.exception("rolling reload failed")
            with self._state_lock:
                self._swap = {"active": False,
                              "last": {"outcome": "crashed"}}

    # -- canary lane ---------------------------------------------------------
    def canary_mode(self) -> bool:
        """Whether a new COMPLETED instance should land as a CANARY
        (one replica + verdict) instead of a full rolling swap."""
        if self._canary_mode is not None:
            return self._canary_mode
        return metrics.env_int("PIO_FLEET_CANARY", 0) > 0

    def canary(self) -> Dict[str, Any]:
        with self._state_lock:
            return dict(self._canary)

    def canary_replica_name(self) -> Optional[str]:
        """The active canary replica's name, or None — the router's
        hot-path check (a plain attribute read, no lock)."""
        return self._canary_name

    def start_canary(self, force: bool = False) -> bool:
        """Kick a canary deploy on a background thread: the newest
        COMPLETED instance lands on exactly ONE replica through the
        drain→reload→rejoin machinery; the router then tags lanes and
        samples paired answers until a verdict (auto or operator)
        promotes or rolls back. False when a swap or canary is already
        running (or the fleet is stopping). ``force`` overrides the
        canary replica's device-memory preflight — an oversized
        candidate is otherwise refused (507) before it can OOM the
        replica, and the canary ends in an error verdict."""
        with self._state_lock:
            if self._stop_evt.is_set():
                return False
            if self._swap.get("active") or self._canary.get("active"):
                return False
            if (self._swap_thread is not None
                    and self._swap_thread.is_alive()):
                return False
            if (self._canary_thread is not None
                    and self._canary_thread.is_alive()):
                return False
            self._canary_thread = threading.Thread(
                target=self._canary_start_guarded, args=(force,),
                daemon=True, name="fleet-canary")
            self._canary_thread.start()
            return True

    def _canary_start_guarded(self, force: bool = False) -> None:
        try:
            self._start_canary(force=force)
        except Exception:  # noqa: BLE001 — a crashed canary deploy must
            # leave a visible verdict, not a forever-"starting" state
            log.exception("canary deploy failed")
            with self._state_lock:
                self._canary = {"active": False,
                                "last": {"outcome": "crashed"}}
            self._canary_name = None

    def _start_canary(self, force: bool = False) -> None:
        from predictionio_tpu.obs import quality

        with self._swap_lock:  # a canary IS a (one-replica) swap:
            # serialize against rolling swaps so the two can never
            # drain the same fleet concurrently
            errors: List[str] = []
            baseline = self.version()
            candidate = None
            if self._version_source is not None:
                try:
                    candidate = self._version_source()
                except Exception as e:  # noqa: BLE001 — a storage blip
                    # is an error verdict, not a crash
                    errors.append(f"version source failed: {e}")
            if baseline is None:
                errors.append("fleet is not on a single version — "
                              "converge (rolling reload) before a canary")
            elif not candidate or candidate == baseline:
                errors.append("no NEW completed instance to canary "
                              f"(fleet already on {baseline})")
            replica = None
            if not errors:
                # the LAST ready replica: a stable, predictable pick
                # that keeps r0 (the one operators poke first) on the
                # baseline
                ready = self.ready_replicas()
                replica = ready[-1] if ready else None
                if replica is None:
                    errors.append("no ready replica to canary onto")
            if not errors:
                outcome = self._swap_one(replica, errors, force=force)
                if outcome != "swapped":
                    errors.append(f"{replica.name}: canary deploy did "
                                  "not reach READY on the candidate")
            if errors:
                with self._state_lock:
                    self._canary = {"active": False,
                                    "last": {"outcome": "error",
                                             "errors": errors}}
                log.warning("canary not started: %s", "; ".join(errors))
                return
            with self._state_lock:
                self._canary = {
                    "active": True,
                    "replica": replica.name,
                    "baseline_version": baseline,
                    "candidate_version": replica.version or candidate,
                    "started_unix": round(time.time(), 3),
                    # a force-started canary (the candidate failed the
                    # memory preflight) must promote with the same
                    # force, or every OTHER replica's 507 would strand
                    # the fleet permanently mixed
                    "forced": bool(force),
                }
            self._canary_name = replica.name
            journal.emit("canary_start", replica=replica.name,
                         baseline=baseline,
                         candidate=replica.version or candidate,
                         forced=bool(force) or None)
            quality.STATE.canary_begin(replica.name, baseline,
                                       replica.version or candidate)
            log.info("canary ACTIVE: %s serves candidate %s against "
                     "baseline %s", replica.name, candidate, baseline)

    def _end_canary(self, outcome: str, verdict: Optional[Dict[str, Any]],
                    extra: Optional[Dict[str, Any]] = None) -> None:
        from predictionio_tpu.obs import quality

        with self._state_lock:
            last = {**{k: v for k, v in self._canary.items()
                       if k not in ("active", "last", "deciding")},
                    "outcome": outcome, **(extra or {})}
            self._canary = {"active": False, "last": last}
        self._canary_name = None
        journal.emit("canary_verdict", outcome=outcome,
                     replica=last.get("replica"),
                     baseline=last.get("baseline_version"),
                     candidate=last.get("candidate_version"),
                     rejected=last.get("rejected_version"))
        quality.STATE.canary_end(
            outcome, {"verdict": verdict} if verdict else None)

    def promote_canary(self,
                       verdict: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        """The candidate won: roll the REST of the fleet onto it
        through the ordinary rolling swap (the canary replica's reload
        is an idempotent no-op there). Clears the canary state first so
        the router stops shadow-sampling mid-promotion."""
        info = self.canary()
        if not info.get("active"):
            raise ValueError("no active canary to promote")
        log.info("canary verdict PROMOTE for %s: rolling the fleet onto "
                 "%s", info.get("replica"), info.get("candidate_version"))
        journal.emit("canary_promote", replica=info.get("replica"),
                     candidate=info.get("candidate_version"))
        self._end_canary("promoted", verdict)
        # a force-started canary promotes with the same force — the
        # operator already owned the OOM risk at start
        result = self.rolling_reload(force=bool(info.get("forced")))
        return {"action": "promote", "swap": result}

    def rollback_canary(self,
                        verdict: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
        """The candidate lost: swap the canary replica BACK onto the
        baseline instance (``/reload?instance=``) through the same
        drain→rejoin machinery — clients keep answering from the other
        replicas throughout."""
        info = self.canary()
        if not info.get("active"):
            raise ValueError("no active canary to roll back")
        replica = next((r for r in self.replicas
                        if r.name == info.get("replica")), None)
        baseline = info.get("baseline_version")
        log.warning("canary verdict ROLLBACK for %s: restoring baseline "
                    "%s", info.get("replica"), baseline)
        # stop shadow traffic first, then restore — the rejected
        # candidate version is remembered so the canary-mode watch does
        # not immediately re-canary it (see _maybe_auto_swap)
        journal.emit("canary_rollback", replica=info.get("replica"),
                     baseline=baseline,
                     rejected=info.get("candidate_version"))
        self._end_canary("rolled_back", verdict,
                         extra={"rejected_version":
                                info.get("candidate_version")})
        errors: List[str] = []
        if replica is None:
            errors.append(f"canary replica {info.get('replica')!r} is "
                          "gone")
        elif baseline:
            with self._swap_lock:
                # force=True: restoring the KNOWN-GOOD baseline is the
                # emergency exit from a degraded candidate — the
                # replica's in-use still counts the fat candidate it
                # is about to drop, so a preflight here could 507 the
                # very rollback that frees the memory
                outcome = self._swap_one(replica, errors,
                                         instance_id=baseline,
                                         force=True)
            if outcome != "swapped":
                errors.append(f"{replica.name}: rollback reload did not "
                              "reach READY on the baseline")
        else:
            errors.append("no baseline version recorded — leaving the "
                          "replica on the candidate")
        return {"action": "rollback", "errors": errors,
                "version": self.version()}

    def _maybe_canary_decision(self) -> None:
        """Monitor-loop hook: while a canary is active (and
        ``PIO_CANARY_AUTO`` is on, the default), read the verdict off
        obs/quality.py and act on it — promote/rollback run on a
        background thread (a promotion is a full rolling swap; the
        monitor must keep probing through it)."""
        if self._canary_name is None:
            return
        if metrics.env_int("PIO_CANARY_AUTO", 1) <= 0:
            return
        with self._state_lock:
            if not self._canary.get("active") or self._canary.get(
                    "deciding"):
                return
        from predictionio_tpu.obs import quality

        verdict = quality.STATE.canary_verdict()
        action = verdict.get("verdict")
        if action not in ("promote", "rollback"):
            return
        with self._state_lock:
            if not self._canary.get("active") or self._canary.get(
                    "deciding"):
                return
            self._canary["deciding"] = True

        def decide() -> None:
            try:
                if action == "promote":
                    self.promote_canary(verdict)
                else:
                    self.rollback_canary(verdict)
            except Exception:  # noqa: BLE001 — a failed decision must
                # not strand the canary "deciding" forever
                log.exception("canary %s failed", action)
                with self._state_lock:
                    self._canary.pop("deciding", None)

        threading.Thread(target=decide, daemon=True,
                         name="fleet-canary-verdict").start()

    def _maybe_auto_swap(self) -> None:
        """With ``PIO_FLEET_WATCH_SEC`` > 0 and a version source, a new
        COMPLETED instance triggers the rolling swap automatically —
        train-to-serving with no operator in the loop. In canary mode
        the same watch starts a CANARY instead, and a candidate the
        last canary ROLLED BACK is never auto-retried (a fresh retrain
        — a new instance id — re-arms the watch)."""
        watch = metrics.env_float("PIO_FLEET_WATCH_SEC", 0.0)
        if watch <= 0 or self._version_source is None:
            return
        now = time.monotonic()
        if now - self._last_watch < watch:
            return
        self._last_watch = now
        try:
            latest = self._version_source()
        except Exception:  # noqa: BLE001 — storage blips must not kill
            # the monitor; the next watch tick retries
            log.exception("fleet version watch failed")
            return
        # any ready replica NOT on the latest instance means a swap is
        # due — including a mixed-version fleet left by a partial swap
        # (version() would be None there, and requiring it non-None
        # would leave the fleet stuck mixed forever) and replicas whose
        # version read failed (a redundant reload is idempotent)
        versions = {r.version for r in self.ready_replicas()}
        if not (latest and versions and versions != {latest}):
            return
        with self._state_lock:
            canary_active = self._canary.get("active")
            last = self._canary.get("last") or {}
        if last.get("rejected_version") == latest:
            # the quality gate ROLLED THIS INSTANCE BACK: neither watch
            # path may silently redeploy it (in non-canary mode the
            # full rolling swap would undo the rollback one watch tick
            # later) — a human decision or a NEW retrain re-arms
            log.debug("watch: latest instance %s was canary-rejected; "
                      "holding", latest)
            return
        if self.canary_mode():
            if canary_active:
                return  # the mixed fleet IS the canary
            log.info("COMPLETED instance %s vs fleet on %s: starting "
                     "CANARY", latest, sorted(str(v) for v in versions))
            self.start_canary()
            return
        log.info("COMPLETED instance %s vs fleet on %s: starting "
                 "rolling swap", latest,
                 sorted(str(v) for v in versions))
        self.start_rolling_reload()

    # -- introspection -------------------------------------------------------
    def version(self) -> Optional[str]:
        """The fleet's serving version: the version shared by every
        ready replica, else None (mid-swap / mixed)."""
        versions = {r.version for r in self.ready_replicas() if r.version}
        return versions.pop() if len(versions) == 1 else None

    def _fleet_probe(self) -> health.ProbeResult:
        """Informational fleet probe on the process-global registry.
        DEGRADED at worst, never FAILED: in the threaded tier-1 mode
        the replicas SHARE this registry, and a FAILED fleet probe
        would 503 every replica's own /readyz — a bootstrap deadlock
        (no replica can become ready while none is). The hard "cannot
        place a query" verdict lives in the router's readyz override
        (serving/router.py), which only that server reports."""
        ready, size = self.ready_count(), self.size()
        if ready < size:
            return health.degraded(f"{ready}/{size} replicas ready")
        return health.ok(f"{ready}/{size} replicas ready")

    def _timeline_collector(self, _now: float) -> Dict[str, float]:
        return {"fleet.ready": float(self.ready_count()),
                "fleet.size": float(self.size())}

    def snapshot(self) -> Dict[str, Any]:
        with self._state_lock:
            swap = dict(self._swap)
            canary = dict(self._canary)
        return {
            "size": self.size(),
            "ready": self.ready_count(),
            "version": self.version(),
            "replicas": [r.snapshot() for r in self.replicas],
            "swap": swap,
            "canary": canary,
        }

    def apply_admin(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """``POST /admin/fleet`` body -> action. ``{"reload": true}``
        starts a rolling swap (202 from the route; ``started`` False
        when one is already running), ``{"drain": name}`` /
        ``{"readmit": name}`` move a replica out of / back into
        rotation, ``{"canary": "start"|"promote"|"rollback"}`` drives
        the canary lane (start answers 202 and deploys on a background
        thread; promote/rollback run their swap in the background
        too — progress in the snapshot's ``canary`` block).
        ``{"force": true}`` beside ``reload``/``canary: start``
        overrides the replicas' device-memory preflight — the admin
        acknowledgment lane for a 507-refused deploy. Raises
        ValueError on anything else (the route answers 400)."""
        if not isinstance(payload, dict):
            raise ValueError("fleet admin body must be a JSON object")
        force = bool(payload.get("force"))
        requested = [k for k in ("reload", "drain", "readmit", "canary")
                     if payload.get(k)]
        if len(requested) > 1:
            # only the first in precedence would run; silently dropping
            # the rest would leave the operator believing both happened
            raise ValueError("one action per call, got: "
                             + ", ".join(requested))
        if payload.get("canary"):
            action = payload["canary"]
            if action == "start":
                started = self.start_canary(force=force)
                return {"started": started,
                        "message": ("canary deploy started" if started
                                    else "a canary or rolling swap is "
                                         "already running")}
            if action in ("promote", "rollback"):
                if not self.canary().get("active"):
                    raise ValueError("no active canary to " + action)
                runner = (self.promote_canary if action == "promote"
                          else self.rollback_canary)

                def run_decision() -> None:
                    try:
                        runner()
                    except Exception:  # noqa: BLE001 — the operator
                        # reads the outcome off the snapshot; a crashed
                        # decision must be logged, not silent
                        log.exception("canary %s failed", action)

                threading.Thread(target=run_decision, daemon=True,
                                 name="fleet-canary-admin").start()
                return {"started": True,
                        "message": f"canary {action} started"}
            raise ValueError('canary action must be "start", "promote" '
                             'or "rollback"')
        if payload.get("reload"):
            started = self.start_rolling_reload(force=force)
            return {"started": started,
                    "message": ("rolling reload started" if started
                                else "a rolling reload is already "
                                     "running")}
        for action, state in (("drain", DRAINING), ("readmit", EVICTED)):
            name = payload.get(action)
            if name:
                replica = next((r for r in self.replicas
                                if r.name == name), None)
                if replica is None:
                    raise ValueError(f"no replica named {name!r}")
                if action == "drain" and replica.state in (DEAD, STOPPED):
                    # draining a DEAD replica would cancel its pending
                    # restart forever (_tick skips DRAINING) and report
                    # a dead process as deliberately held
                    raise ValueError(
                        f"replica {name!r} is {replica.state}, not in "
                        "rotation — nothing to drain")
                if action == "readmit" and replica.state == DEAD:
                    # flipping a DEAD replica to EVICTED would bypass
                    # the restart branch and trade its almost-due
                    # restart for a fresh (longer) backoff; the
                    # operator's intent is "bring it back NOW" — skip
                    # the remaining wait, the next tick relaunches it
                    replica.next_restart_at = 0.0
                    return {"replica": name, "state": replica.state,
                            "message": "dead replica: restart "
                                       "fast-tracked"}
                if action == "readmit" and replica.state == STOPPED:
                    raise ValueError(
                        f"replica {name!r} is stopped — the fleet is "
                        "shutting down")
                self._set_state(replica, state, deliberate=True)
                if state == EVICTED:
                    self.probe_and_update(replica)  # readmit fast
                return {"replica": name, "state": replica.state}
        raise ValueError('fleet admin body needs "reload", "drain", '
                         '"readmit" or "canary"')


def format_swap(swap: Optional[Dict[str, Any]]) -> str:
    """One operator-facing line for ``snapshot()['swap']`` — the CLI
    and the dashboard render the same state through the same string."""
    swap = swap or {}
    if swap.get("active"):
        return "rolling swap: IN PROGRESS"
    last = swap.get("last")
    if not last:
        return "no rolling swap yet"
    line = (f"last swap: {last.get('outcome')} "
            f"(swapped {', '.join(last.get('swapped') or []) or 'none'}")
    if last.get("errors"):
        line += "; errors: " + "; ".join(last["errors"])
    return line + ")"


def deploy_fleet_argv(engine_json: str, ip: str = "127.0.0.1") -> List[str]:
    """The argv template a subprocess fleet spawns per replica: a
    plain single-server ``pio deploy`` child with a ``{port}``
    placeholder (the supervisor fills an ephemeral port per launch).

    ``--replicas 1`` is explicit and load-bearing: the child inherits
    the parent's environment, so a fleet started via ``PIO_REPLICAS=N``
    would otherwise re-enter the fleet path in every child and spawn
    grandchildren recursively — a fork bomb, not a fleet."""
    return [sys.executable, "-m", "predictionio_tpu.tools.cli",
            "deploy", "--engine-json", engine_json, "--replicas", "1",
            "--ip", ip, "--port", "{port}"]
