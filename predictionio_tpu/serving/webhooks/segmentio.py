"""Segment.io JSON webhook connector.

Behavior contract from the reference
(data/.../webhooks/segmentio/SegmentIOConnector.scala:25): requires the
common fields ``type`` + ``timestamp``; supports the ``identify`` call,
mapping it to an event named after the type on a ``user`` entity with
context/traits folded into properties. Unknown types are a connector
error (HTTP 400), matching the reference's ConnectorException.
"""

from __future__ import annotations

from predictionio_tpu.serving.webhooks import ConnectorError, JsonConnector, register_json_connector


class SegmentIOConnector(JsonConnector):
    def to_event_json(self, payload: dict) -> dict:
        for field in ("type", "timestamp"):
            if field not in payload:
                raise ConnectorError(
                    f"Cannot extract common field {field!r} from segmentio payload."
                )
        kind = payload["type"]
        if kind != "identify":
            raise ConnectorError(f"Cannot convert unknown type {kind} to event JSON.")
        if "userId" not in payload:
            raise ConnectorError("identify requires userId.")
        properties = {}
        if payload.get("context") is not None:
            properties["context"] = payload["context"]
        if payload.get("traits") is not None:
            properties["traits"] = payload["traits"]
        return {
            "event": kind,
            "entityType": "user",
            "entityId": payload["userId"],
            "eventTime": payload["timestamp"],
            "properties": properties,
        }


register_json_connector("segmentio", SegmentIOConnector())
