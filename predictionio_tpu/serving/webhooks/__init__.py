"""Webhook connector framework.

Behavior contract from the reference (data/.../webhooks/JsonConnector.scala:29,
FormConnector.scala:30, api/WebhooksConnectors.scala:24): a connector
translates a third-party payload (JSON body or form fields) into the
event-server Event JSON; the registry maps URL path segments
(``/webhooks/<name>.json`` for JSON, ``/webhooks/<name>`` for form)
to connectors. Built-ins: segmentio (JSON), mailchimp (form).
"""

from __future__ import annotations

import abc
from typing import Dict, Mapping


class ConnectorError(ValueError):
    """Payload cannot be translated (-> HTTP 400)."""


class JsonConnector(abc.ABC):
    """ref: JsonConnector.scala:29."""

    @abc.abstractmethod
    def to_event_json(self, payload: dict) -> dict:
        """3rd-party JSON -> Event JSON dict."""


class FormConnector(abc.ABC):
    """ref: FormConnector.scala:30."""

    @abc.abstractmethod
    def to_event_json(self, fields: Mapping[str, str]) -> dict:
        """3rd-party form fields -> Event JSON dict."""


_JSON_CONNECTORS: Dict[str, JsonConnector] = {}
_FORM_CONNECTORS: Dict[str, FormConnector] = {}


def register_json_connector(name: str, connector: JsonConnector) -> None:
    _JSON_CONNECTORS[name] = connector


def register_form_connector(name: str, connector: FormConnector) -> None:
    _FORM_CONNECTORS[name] = connector


def json_connector(name: str) -> JsonConnector:
    _load_builtins()
    if name not in _JSON_CONNECTORS:
        raise KeyError(name)
    return _JSON_CONNECTORS[name]


def form_connector(name: str) -> FormConnector:
    _load_builtins()
    if name not in _FORM_CONNECTORS:
        raise KeyError(name)
    return _FORM_CONNECTORS[name]


def _load_builtins() -> None:
    # registration side effects (ref: WebhooksConnectors.scala:24)
    from predictionio_tpu.serving.webhooks import mailchimp, segmentio  # noqa: F401
