"""MailChimp form webhook connector.

Behavior contract from the reference
(data/.../webhooks/mailchimp/MailChimpConnector.scala:29): handles the
``subscribe`` form payload, mapping it to a ``subscribe`` event from
user ``data[id]`` to list ``data[list_id]`` with email/merge fields as
properties; ``fired_at`` ("yyyy-MM-dd HH:mm:ss", UTC) becomes the event
time. Missing ``type`` or an unknown type is a connector error.
"""

from __future__ import annotations

import datetime as _dt
from typing import Mapping

from predictionio_tpu.serving.webhooks import ConnectorError, FormConnector, register_form_connector

UTC = _dt.timezone.utc


def _parse_mailchimp_time(s: str) -> str:
    try:
        t = _dt.datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=UTC)
    except ValueError as e:
        raise ConnectorError(f"Cannot parse fired_at {s!r}: {e}")
    return t.isoformat()


class MailChimpConnector(FormConnector):
    def to_event_json(self, fields: Mapping[str, str]) -> dict:
        kind = fields.get("type")
        if kind is None:
            raise ConnectorError("The field 'type' is required for MailChimp data.")
        if kind != "subscribe":
            raise ConnectorError(
                f"Cannot convert unknown MailChimp data type {kind} to event JSON"
            )
        try:
            properties = {
                "email": fields["data[email]"],
                "email_type": fields["data[email_type]"],
                "merges": {
                    "EMAIL": fields["data[merges][EMAIL]"],
                    "FNAME": fields["data[merges][FNAME]"],
                    "LNAME": fields["data[merges][LNAME]"],
                },
                "ip_opt": fields["data[ip_opt]"],
                "ip_signup": fields["data[ip_signup]"],
            }
            interests = fields.get("data[merges][INTERESTS]")
            if interests is not None:
                properties["merges"]["INTERESTS"] = interests
            return {
                "event": "subscribe",
                "entityType": "user",
                "entityId": fields["data[id]"],
                "targetEntityType": "list",
                "targetEntityId": fields["data[list_id]"],
                "eventTime": _parse_mailchimp_time(fields["fired_at"]),
                "properties": properties,
            }
        except KeyError as e:
            raise ConnectorError(f"MailChimp subscribe payload missing field {e.args[0]}")


register_form_connector("mailchimp", MailChimpConnector())
