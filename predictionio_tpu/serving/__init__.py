"""HTTP servers: event collection + query serving + ops stats.

Maps the reference's server layer (SURVEY.md §1 L6):

  event_server  — REST event ingestion, port 7070
                  (ref: data/.../api/EventAPI.scala)
  engine_server — deployed-engine query serving, port 8000
                  (ref: core/.../workflow/CreateServer.scala)
  fleet         — replica supervisor: N engine-server replicas,
                  readyz-driven rotation, backoff restarts, rolling
                  zero-downtime hot-swap (beyond the reference's
                  single process)
  router        — the fleet's public front door: least-loaded
                  placement, per-replica circuit breakers, hedged
                  tail-latency requests, 429/degraded passthrough
  stats         — per-app operational counters
                  (ref: data/.../api/Stats.scala, StatsActor.scala)
  webhooks      — third-party payload connectors
                  (ref: data/.../webhooks/)

Servers are stdlib ThreadingHTTPServer-based: the compute hot path
(predict) is one jitted device call, so an async reactor adds nothing
the thread pool doesn't already give at this tier.
"""
