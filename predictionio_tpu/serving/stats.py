"""Per-app operational counters with hourly cutoff.

Behavior contract from the reference (data/.../api/Stats.scala:48 +
StatsActor.scala:33): the event server keeps in-memory counts of
(status-code, event name, entity type) per appId, bucketed by hour;
``/stats.json`` reports the previous + current hour. The reference
routes bookkeeping through an Akka actor; here a lock suffices.
"""

from __future__ import annotations

import datetime as _dt
import threading
from collections import defaultdict
from typing import Dict, Optional, Tuple

UTC = _dt.timezone.utc


def _hour_bucket(t: Optional[_dt.datetime] = None) -> _dt.datetime:
    t = t or _dt.datetime.now(tz=UTC)
    return t.replace(minute=0, second=0, microsecond=0)


class Stats:
    """ref: Stats.scala:48."""

    def __init__(self):
        self._lock = threading.Lock()
        # hour -> app_id -> (status, event, entity_type) -> count
        self._buckets: Dict[_dt.datetime, Dict[int, Dict[Tuple, int]]] = defaultdict(
            lambda: defaultdict(lambda: defaultdict(int))
        )
        self.start_time = _dt.datetime.now(tz=UTC)

    def _prune_locked(self) -> _dt.datetime:
        """Drop buckets older than the previous hour (hourly cutoff,
        ref: StatsActor bookkeeping); returns the cutoff. Caller holds
        the lock."""
        cutoff = _hour_bucket() - _dt.timedelta(hours=1)
        for old in [b for b in self._buckets if b < cutoff]:
            del self._buckets[old]
        return cutoff

    def update(self, app_id: int, status: int, event: str, entity_type: str) -> None:
        with self._lock:
            self._buckets[_hour_bucket()][int(app_id)][
                (status, event, entity_type)] += 1
            self._prune_locked()

    def report(self, app_id: int) -> dict:
        """Previous + current hour counts for one app (ref: /stats.json)."""
        with self._lock:
            # prune here too: update() only runs when events arrive, so
            # on a quiet app stale hours would otherwise sit in memory
            # (and one filter bug away from being reported) indefinitely
            cutoff = self._prune_locked()
            out = []
            for bucket in sorted(b for b in self._buckets if b >= cutoff):
                counts = self._buckets[bucket].get(int(app_id), {})
                if not counts:
                    continue
                out.append(
                    {
                        "hour": bucket.isoformat(),
                        "counts": [
                            {
                                "status": status,
                                "event": event,
                                "entityType": entity_type,
                                "count": count,
                            }
                            for (status, event, entity_type), count in sorted(counts.items())
                        ],
                    }
                )
            return {"appId": int(app_id), "startTime": self.start_time.isoformat(), "buckets": out}
