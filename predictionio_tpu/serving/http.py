"""Shared HTTP plumbing for the framework's servers.

One copy of the JSON response writer, body reader, bind-retry loop and
thread lifecycle used by the event server, engine server, dashboard and
admin API (the reference gets this from spray; each server here is a
stdlib ThreadingHTTPServer).

Every server also inherits the shared operator surface from the
``_instrument`` wrapper:

  GET  /healthz          liveness (cheap, no probes)
  GET  /readyz           readiness (health probes; 503 on any FAILED)
  GET  /metrics          Prometheus text, or OpenMetrics with
                         exemplars under ``Accept:
                         application/openmetrics-text``
  GET  /admin/flight     flight-recorder dump        } bearer-token
  POST /admin/profile    on-demand profiler window   } guarded when
  GET  /admin/slo        SLO burn-rate evaluation    } PIO_ADMIN_TOKEN
  GET/POST /admin/chaos  fault-injection rule set    } is set
  GET  /admin/resilience breaker/admission/chaos     }
                         snapshot                    }
  GET  /admin/timeline   metric timelines + the      }
                         data-path ledger            }
  GET  /admin/tail       tail-latency attribution    }
                         (above-p95 stage shares)    }
  GET/POST /admin/fleet  replica fleet snapshot /    }
                         rolling-swap + canary       }
                         control (404 on servers     }
                         without a fleet)            }
  GET/POST /admin/quality model-quality report:      }
                         drift gauges' source, last  }
                         replay diff, canary verdict }
  GET  /admin/memory     device-memory accounting:   }
                         per-model HBM ledger,       }
                         headroom, train peaks,      }
                         preflight state             }
  GET  /admin/spans      this process's span ring    }
                         (?trace=&n=; the federation }
                         collector's query surface)  }
  GET  /admin/trace      cross-process stitched      }
                         trace (?id=; obs/collect.py }
                         fans out to the fleet)      }
  GET  /admin/fleet/metrics merged member /metrics   }
                         (counters sum, histograms   }
                         bucket-wise, gauges get a   }
                         member label) + fleet SLO   }
                         burn (404 without a fleet)  }
  GET  /admin/fleet/tail fleet-wide tail attribution }
                         over every member's flight  }
                         recorder (404 w/o a fleet)  }
  GET  /admin/prof       continuous host profiler    }
                         flame (?format=collapsed,   }
                         ?endpoint=, ?slow=1 slices) }
  GET  /admin/fleet/prof member-merged continuous    }
                         profile (404 w/o a fleet)   }
  GET  /admin/journal    ops journal ring (?n=&kind= }
                         &since=): reloads, canary   }
                         verdicts, breaker flips,    }
                         shed episodes, anomalies    }
  GET  /admin/anomaly    regression sentinel report: }
                         active change-points with   }
                         causal attribution to the   }
                         journal + recent resolves   }
  GET  /admin/fleet/journal member-merged journal    }
                         stream (404 w/o a fleet)    }
  GET  /admin/fleet/anomaly per-member sentinel      }
                         reports + active union      }
                         (404 w/o a fleet)           }
  GET  /admin/data       data-plane report (?top=):  }
                         ingest rates, entity heavy  }
                         hitters + Zipf skew, HLL    }
                         cardinality, quantiles,     }
                         schema drift, unknown-      }
                         entity coverage             }
  GET  /admin/fleet/data per-member data reports +   }
                         merged totals (404 w/o a    }
                         fleet)                      }

``/healthz``, ``/readyz`` and ``/metrics`` stay unauthenticated — a
liveness prober or scraper holds no operator secrets; the ``/admin/*``
diagnostics expose request payloads/traces and so require
``Authorization: Bearer $PIO_ADMIN_TOKEN`` once the operator sets it.
"""

from __future__ import annotations

import functools
import hmac
import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

from predictionio_tpu.obs import (anomaly, contprof, flight, health,
                                  journal, metrics, perfacct, profiler,
                                  push, slo, timeline, trace)
from predictionio_tpu.resilience import alerts, chaos
from predictionio_tpu.resilience import policy as respolicy

log = logging.getLogger(__name__)

# -- built-in request telemetry (tentpole: every server inherits these) -------

_REQUESTS_TOTAL = metrics.counter(
    "pio_http_requests_total",
    "HTTP requests answered, by server, method, route and status",
    ("server", "method", "route", "status"),
)
_REQUEST_SECONDS = metrics.histogram(
    "pio_http_request_duration_seconds",
    "HTTP request handling wall time (request parsed -> response written)",
    ("server", "method", "route"),
)
_IN_FLIGHT = metrics.gauge(
    "pio_http_requests_in_flight",
    "Requests currently being handled, by server",
    ("server",),
)

#: path segments that are data ids (event/model/scan ids, uuid hexes):
#: collapsed to ":id" so metric label cardinality stays bounded
_ID_SEGMENT = re.compile(r"^[0-9a-fA-F-]{16,}$")

#: hard cap on distinct route labels per process: the real servers have
#: ~25 routes; beyond this, new paths (scanners probing random 404s)
#: collapse to ":other" instead of growing the registry forever
_MAX_ROUTES = 64
_routes_seen: set = set()


def metrics_route(path: str) -> str:
    """A bounded-cardinality route label for a request path."""
    out = []
    for seg in path.split("/"):
        if not seg:
            continue
        stem, dot, ext = seg.rpartition(".")
        base = stem if dot else seg
        if _ID_SEGMENT.match(base) or len(base) > 48:
            seg = ":id" + (dot + ext if dot else "")
        out.append(seg)
    route = "/" + "/".join(out)
    if route in _routes_seen:
        return route
    if len(_routes_seen) < _MAX_ROUTES:  # benign race: cap is approximate
        _routes_seen.add(route)
        return route
    return ":other"


def _admin_authorized(handler) -> bool:
    """Bearer-token gate for the ``/admin/*`` diagnostics: with
    ``PIO_ADMIN_TOKEN`` unset everything stays open (trusted-network
    default, the pre-auth behavior); once set, requests must carry
    ``Authorization: Bearer <token>`` (constant-time compare)."""
    token = os.environ.get("PIO_ADMIN_TOKEN")
    if not token:
        return True
    supplied = handler.headers.get("Authorization") or ""
    return hmac.compare_digest(supplied, f"Bearer {token}")


def _server_storage(server_ref) -> Any:
    """The serving object's storage, wherever the server keeps it (the
    event server nests it inside its core)."""
    storage = getattr(server_ref, "storage", None)
    if storage is None:
        storage = getattr(getattr(server_ref, "core", None), "storage", None)
    return storage


def _serve_readyz(handler) -> None:
    """``GET /readyz``: run the process health probes plus THIS
    server's storage probe; 200 while nothing FAILED (DEGRADED still
    serves — readiness is "can answer", not "is pristine"), 503 with
    the same per-probe detail otherwise. A server may override its
    storage probe via a ``storage_readyz_probe`` method — the engine
    server does, mapping storage loss to DEGRADED (it can still answer
    queries from the last-loaded model)."""
    health.install_default_probes()
    override = getattr(handler.server_ref, "storage_readyz_probe", None)
    if override is not None:
        extra = {"storage": override}
    else:
        storage = _server_storage(handler.server_ref)
        extra = {"storage": lambda: health.storage_probe(storage)}
    overall, detail = health.REGISTRY.run(extra=extra)
    status = 503 if overall == health.FAILED else 200
    handler._send(status, {"status": overall, "probes": detail})


def _serve_metrics(handler, query: str) -> None:
    """``GET /metrics``: Prometheus text by default; the OpenMetrics
    document (counter `_total` families, histogram exemplars, `# EOF`)
    under ``Accept: application/openmetrics-text`` or
    ``?format=openmetrics``."""
    accept = handler.headers.get("Accept") or ""
    fmt = (parse_qs(query).get("format") or [""])[0]
    if "application/openmetrics-text" in accept or fmt == "openmetrics":
        handler._send(200, metrics.REGISTRY.render_openmetrics(),
                      content_type=metrics.OPENMETRICS_CONTENT_TYPE)
    else:
        handler._send(200, metrics.REGISTRY.render(),
                      content_type=metrics.CONTENT_TYPE)


def _serve_admin_flight(handler, query: str) -> None:
    """``GET /admin/flight``: the flight-recorder dump as JSON.
    ``?n=N`` limits to the last N records, ``?slow=1`` keeps only
    slow/errored ones. Captured query payloads (PIO_FLIGHT_PAYLOADS)
    are included only when an admin token is CONFIGURED — the bearer
    gate above then guarantees it was presented; on a token-less
    (trusted-network-default) server the payload bodies stay redacted,
    only the capture counts show."""
    params = parse_qs(query)
    try:
        n = int(params["n"][0]) if "n" in params else None
    except ValueError:
        handler._send(400, {"message": "n must be an integer"})
        return
    slow_only = (params.get("slow") or ["0"])[0].lower() in ("1", "true")
    include_payloads = bool(os.environ.get("PIO_ADMIN_TOKEN"))
    handler._send(200, flight.RECORDER.dump(
        n, slow_only=slow_only, include_payloads=include_payloads))


def _serve_admin_quality(handler) -> None:
    """``GET /admin/quality``: the model-quality report (obs/quality.py
    STATE) — latest drift probe, latest replay comparison, canary
    progress + verdict. ``POST /admin/quality`` with ``{"replay":
    {...}}`` and/or ``{"drift": {...}}`` registers an
    externally-computed report — the ``pio replay`` CLI pushes its
    result here, and a split-deployment ``pio stream`` daemon pushes
    its drift probes to the fleet it patches, so the fleet's one
    quality surface carries both even when measured in another
    process."""
    from predictionio_tpu.obs import quality

    if handler.command == "GET":
        handler._send(200, quality.STATE.report())
        return
    if handler.command != "POST":
        handler._send(405, {"message": "GET or POST"})
        return
    try:
        payload = handler._read_json()
    except json.JSONDecodeError as e:
        handler._send(400, {"message": f"invalid JSON: {e}"})
        return
    registered = []
    if isinstance(payload, dict):
        if isinstance(payload.get("replay"), dict):
            quality.STATE.set_replay(payload["replay"])
            registered.append("replay")
        if isinstance(payload.get("drift"), dict):
            quality.STATE.set_drift(payload["drift"])
            registered.append("drift")
    if not registered:
        handler._send(400, {"message": 'body needs a "replay" and/or '
                                       '"drift" object'})
        return
    handler._send(200, {"message": "registered: " + ", ".join(registered)})


def _serve_admin_profile(handler, query: str) -> None:
    """``POST /admin/profile?seconds=N``: record a JAX profiler window
    of THIS process and answer the artifact path; 501 on CPU backends
    (no device timeline to record), 409 while a capture is running.
    The handler thread sleeps through the window by design — the
    capture is of the OTHER threads doing device work."""
    params = parse_qs(query)
    try:
        seconds = float((params.get("seconds") or ["3"])[0])
    except ValueError:
        handler._send(400, {"message": "seconds must be a number"})
        return
    # echo the EFFECTIVE window (capture clamps a typo'd N): the answer
    # must describe the trace the operator actually holds
    seconds = profiler.clamp_seconds(seconds)
    try:
        artifact = profiler.capture(seconds)
    except profiler.ProfilerUnavailable as e:
        # actionable, not a bare status line: on CPU backends the
        # continuous HOST profiler is the one that has the answer
        handler._send(501, {
            "message": str(e),
            "backend": profiler.backend(),
            "hint": "no device timeline on this backend; the "
                    "continuous host profiler is always on — use "
                    "GET /admin/prof (?format=collapsed, ?endpoint=, "
                    "?slow=1) or `pio prof`",
            "host_profiler": "/admin/prof",
        })
        return
    except profiler.ProfilerBusy as e:
        handler._send(409, {"message": str(e)})
        return
    handler._send(200, {"artifact": artifact, "seconds": seconds,
                        "backend": profiler.backend()})


def _serve_admin_chaos(handler) -> None:
    """``GET /admin/chaos``: the active fault-injection rule set.
    ``POST /admin/chaos``: mutate it — ``{"spec": "..."}`` replaces,
    ``{"add": "..."}`` appends, ``{"clear": true | "site"}`` drops
    (resilience/chaos.py spec grammar). Admin-token-guarded like every
    ``/admin/*`` route: fault injection against a production server is
    an operator action, not a drive-by."""
    if handler.command == "GET":
        handler._send(200, chaos.describe())
        return
    if handler.command != "POST":
        handler._send(405, {"message": "GET or POST"})
        return
    try:
        payload = handler._read_json()
        result = chaos.apply_admin(payload)
    except (json.JSONDecodeError, ValueError) as e:
        handler._send(400, {"message": str(e)})
        return
    handler._send(200, result)


def _serve_admin_timeline(handler) -> None:
    """``GET /admin/timeline``: the bounded metric-timeline rings
    (obs/timeline.py) plus the data-path ledger + staleness clock
    (obs/perfacct.py). The read itself ticks the sampler (rate-limited
    by the cadence), so watching a server builds its history."""
    timeline.TIMELINE.sample()
    payload = timeline.TIMELINE.series()
    payload["datapath"] = perfacct.LEDGER.snapshot()
    handler._send(200, payload)


def _serve_admin_tail(handler, query: str) -> None:
    """``GET /admin/tail``: tail-latency attribution over the flight
    recorder's stage timings — for requests above ``?q=`` (default
    0.95), which stage dominates vs the median request."""
    params = parse_qs(query)
    try:
        q = float((params.get("q") or ["0.95"])[0])
        report = perfacct.tail_report(q=q)
    except ValueError as e:
        handler._send(400, {"message": str(e)})
        return
    handler._send(200, report)


def _serve_admin_spans(handler, query: str) -> None:
    """``GET /admin/spans?trace=<id>&n=N``: THIS process's span ring
    (obs/trace.py) — the federation collector's (obs/collect.py)
    span-query surface, served on every server like ``/metrics``. The
    payload carries the ring capacity (``PIO_SPAN_RING``) and the
    eviction counter so a partial trace comes with its why."""
    from predictionio_tpu.obs import collect

    params = parse_qs(query)
    trace_id = (params.get("trace") or [None])[0]
    if trace_id is not None and not trace.valid_trace_id(trace_id):
        handler._send(400, {"message": "trace must be id-shaped"})
        return
    try:
        n = int(params["n"][0]) if "n" in params else None
    except ValueError:
        handler._send(400, {"message": "n must be an integer"})
        return
    server = handler.server_version.split("/", 1)[0]
    handler._send(200, collect.span_page(server, trace_id, n))


def _serve_admin_trace(handler, query: str) -> None:
    """``GET /admin/trace?id=<trace>``: the CROSS-PROCESS stitched
    trace — this server fans out to its federation members (its fleet's
    replicas, the ACTIVE supervisors of this process, and the
    ``PIO_OBS_MEMBERS`` extras), dedupes and assembles one annotated
    tree (obs/collect.py). ``pio trace <id>`` and the dashboard's
    ``/trace`` view render the same document."""
    from predictionio_tpu.obs import collect

    params = parse_qs(query)
    trace_id = (params.get("id") or params.get("trace") or [None])[0]
    if not trace_id or not trace.valid_trace_id(trace_id):
        handler._send(400, {"message": "need an id-shaped ?id=<trace>"})
        return
    members = collect.default_members(handler.server_ref)
    handler._send(200, collect.stitch_trace(trace_id, members))


def _fleet_federation_members(handler):
    """The member list for the fleet-scoped federations (metrics,
    tail): the supervised fleet's replicas plus configured extras —
    None (-> 404) on a server with neither, mirroring /admin/fleet."""
    from predictionio_tpu.obs import collect

    fleet = getattr(handler.server_ref, "fleet", None)
    members = collect.fleet_members(fleet) + collect.env_members()
    # first occurrence wins (same contract as collect.default_members):
    # a replica ALSO listed in PIO_OBS_MEMBERS must not be scraped
    # twice — the merge would double-sum its counters and buckets
    seen: set = set()
    deduped = []
    for m in members:
        key = (m.name, m.url)
        if m.name in seen or m.url in seen:
            continue
        seen.update(key)
        deduped.append(m)
    return deduped or None


def _serve_fleet_metrics(handler, query: str) -> None:
    """``GET /admin/fleet/metrics``: the members' /metrics snapshots
    merged (counters sum, histograms bucket-wise, gauges keep a
    ``member`` label) + the fleet-level SLO burn over the merged
    serving histogram. ``?format=prom`` answers the merged document in
    Prometheus text form for a fleet-level scraper; default is the
    JSON report. A member mid-restart degrades the merge, never fails
    it."""
    from predictionio_tpu.obs import collect

    members = _fleet_federation_members(handler)
    if members is None:
        handler._send(404, {"message": "no fleet supervised by this "
                                       "server and no PIO_OBS_MEMBERS "
                                       "configured"})
        return
    report = collect.federate_metrics(members)
    merged = report.pop("_merged")
    fmt = (parse_qs(query).get("format") or [""])[0]
    if fmt in ("prom", "prometheus", "text"):
        handler._send(200, collect.render_merged(merged),
                      content_type=metrics.CONTENT_TYPE)
        return
    handler._send(200, report)


def _serve_fleet_tail(handler, query: str) -> None:
    """``GET /admin/fleet/tail?q=``: tail attribution over the WHOLE
    fleet's flight recorders — the members' stage timings merged
    through the same perfacct.tail_report a single process serves at
    /admin/tail, plus the per-member tail split."""
    from predictionio_tpu.obs import collect

    members = _fleet_federation_members(handler)
    if members is None:
        handler._send(404, {"message": "no fleet supervised by this "
                                       "server and no PIO_OBS_MEMBERS "
                                       "configured"})
        return
    params = parse_qs(query)
    try:
        q = float((params.get("q") or ["0.95"])[0])
        n = int(params["n"][0]) if "n" in params else None
        report = collect.federate_tail(members, q=q, n=n)
    except ValueError as e:
        handler._send(400, {"message": str(e)})
        return
    handler._send(200, report)


def _parse_prof_slices(query: str):
    """Shared ?slow=1 / ?endpoint= / ?format= parsing for the local and
    fleet profile routes."""
    params = parse_qs(query)
    slow = (params.get("slow") or ["0"])[0].lower() in ("1", "true")
    endpoint = (params.get("endpoint") or [None])[0]
    fmt = (params.get("format") or [""])[0]
    return slow, endpoint, fmt


def _serve_admin_prof(handler, query: str) -> None:
    """``GET /admin/prof``: the continuous host profiler's aggregated
    flame (obs/contprof.py) — the answer ``POST /admin/profile`` cannot
    give on CPU backends. ``?format=collapsed`` emits folded
    ``stack count`` lines for external flamegraph tools; ``?endpoint=``
    slices one route's trie; ``?slow=1`` the above-``PIO_SLOW_MS`` tail
    cohort, whose payload also names the slow requests' trace ids (they
    join against the flight recorder's slow ring)."""
    slow, endpoint, fmt = _parse_prof_slices(query)
    payload = contprof.snapshot(endpoint=endpoint, slow=slow)
    if fmt == "collapsed":
        handler._send(200, contprof.collapsed_text(payload),
                      content_type="text/plain; charset=UTF-8")
        return
    handler._send(200, payload)


def _serve_fleet_prof(handler, query: str) -> None:
    """``GET /admin/fleet/prof``: the members' continuous profiles
    member-merged through the federation plane (obs/collect.py) —
    folded stacks summed, per-member sample counts and errors
    annotated; a dead member degrades the merge, never fails it. Same
    ``?slow=1`` / ``?endpoint=`` / ``?format=collapsed`` slices as the
    single-process route."""
    from predictionio_tpu.obs import collect

    members = _fleet_federation_members(handler)
    if members is None:
        handler._send(404, {"message": "no fleet supervised by this "
                                       "server and no PIO_OBS_MEMBERS "
                                       "configured"})
        return
    slow, endpoint, fmt = _parse_prof_slices(query)
    report = collect.federate_prof(members, endpoint=endpoint, slow=slow)
    if fmt == "collapsed":
        handler._send(200, contprof.collapsed_text(report["merged"]),
                      content_type="text/plain; charset=UTF-8")
        return
    handler._send(200, report)


def _serve_admin_journal(handler, query: str) -> None:
    """``GET /admin/journal?n=&kind=&since=``: this process's ops
    journal ring, newest last — reloads, canary verdicts, breaker
    flips, shed episodes, anomaly onsets (obs/journal.py). ``kind``
    filters one event kind exactly; ``since`` is a unix-seconds floor;
    ``n`` caps the page (default 200)."""
    params = parse_qs(query)
    try:
        n = int((params.get("n") or ["200"])[0])
        since = float(params["since"][0]) if "since" in params else None
    except ValueError as e:
        handler._send(400, {"message": f"bad n/since: {e}"})
        return
    kind = (params.get("kind") or [None])[0]
    handler._send(200, journal.JOURNAL.page(n=n, kind=kind, since=since))


def _serve_fleet_journal(handler, query: str) -> None:
    """``GET /admin/fleet/journal``: the members' journals merged into
    one member-annotated, time-ordered stream (same ?n=&kind=&since=
    slices); a dead member degrades the merge, never fails it."""
    from predictionio_tpu.obs import collect

    members = _fleet_federation_members(handler)
    if members is None:
        handler._send(404, {"message": "no fleet supervised by this "
                                       "server and no PIO_OBS_MEMBERS "
                                       "configured"})
        return
    params = parse_qs(query)
    try:
        n = int((params.get("n") or ["200"])[0])
        since = float(params["since"][0]) if "since" in params else None
    except ValueError as e:
        handler._send(400, {"message": f"bad n/since: {e}"})
        return
    kind = (params.get("kind") or [None])[0]
    handler._send(200, collect.federate_journal(members, n=n, kind=kind,
                                                since=since))


def _serve_admin_anomaly(handler) -> None:
    """``GET /admin/anomaly``: the regression sentinel's report —
    active change-points per timeline series (direction, z, CUSUM,
    onset, the journal event each is attributed to) plus recently
    resolved episodes (obs/anomaly.py). The read itself scans, so an
    idle server still verdicts while someone is watching."""
    handler._send(200, anomaly.SENTINEL.scan())


def _serve_fleet_anomaly(handler) -> None:
    """``GET /admin/fleet/anomaly``: every member's sentinel report
    side by side + the union of active anomalies (a regression on ANY
    replica is a fleet regression)."""
    from predictionio_tpu.obs import collect

    members = _fleet_federation_members(handler)
    if members is None:
        handler._send(404, {"message": "no fleet supervised by this "
                                       "server and no PIO_OBS_MEMBERS "
                                       "configured"})
        return
    handler._send(200, collect.federate_anomaly(members))


def _serve_admin_data(handler, query: str) -> None:
    """``GET /admin/data``: the data plane's report (obs/dataobs.py) —
    ingest rates per (app, event), entity heavy hitters with the
    fitted Zipf skew, HLL cardinalities, payload/value/inter-arrival
    quantiles, the live-vs-frozen schema diff and the unknown-entity
    coverage ratio. ``?top=`` sizes the heavy-hitter table."""
    from predictionio_tpu.obs import dataobs

    params = parse_qs(query)
    try:
        top = int((params.get("top") or ["20"])[0])
    except ValueError as e:
        handler._send(400, {"message": f"bad top: {e}"})
        return
    handler._send(200, dataobs.DATAOBS.report(top_n=top))


def _serve_fleet_data(handler) -> None:
    """``GET /admin/fleet/data``: every member's data-plane report side
    by side plus fleet-merged totals (summed counters, max skew, the
    union of schema changes); a dead member degrades, never fails."""
    from predictionio_tpu.obs import collect

    members = _fleet_federation_members(handler)
    if members is None:
        handler._send(404, {"message": "no fleet supervised by this "
                                       "server and no PIO_OBS_MEMBERS "
                                       "configured"})
        return
    handler._send(200, collect.federate_data(members))


def _serve_admin_fleet(handler) -> None:
    """``GET /admin/fleet``: the replica fleet's snapshot (states,
    versions, restart counts, swap progress). ``POST /admin/fleet``:
    control — ``{"reload": true}`` starts a rolling zero-downtime
    hot-swap, ``{"drain"|"readmit": "<replica>"}`` takes a replica out
    of / back into rotation. 404 on servers that supervise no fleet."""
    fleet = getattr(handler.server_ref, "fleet", None)
    if fleet is None:
        handler._send(404, {"message": "no fleet supervised by this "
                                       "server"})
        return
    if handler.command == "GET":
        handler._send(200, fleet.snapshot())
        return
    if handler.command != "POST":
        handler._send(405, {"message": "GET or POST"})
        return
    try:
        result = fleet.apply_admin(handler._read_json())
    except (json.JSONDecodeError, ValueError) as e:
        handler._send(400, {"message": str(e)})
        return
    if "started" in result:
        # mirror the router's GET /reload: 202 on a freshly started
        # swap, 409 when one is already running (a 200 here read as
        # "done" to callers probing either route)
        handler._send(202 if result["started"] else 409, result)
        return
    handler._send(200, result)


def _instrument(fn):
    """Wrap a do_METHOD handler: serve the shared routes (``GET
    /metrics``, ``GET /admin/flight``, ``POST /admin/profile``),
    activate the request's trace context (minting or accepting an
    ``X-PIO-Trace-Id``), open a flight-recorder record, and record the
    built-in request metrics. Applied once to every handler subclass
    via ``__init_subclass__`` — servers inherit all of it without
    touching their routing code."""
    if getattr(fn, "_pio_instrumented", False):
        return fn

    @functools.wraps(fn)
    def wrapper(self):
        parsed = urlparse(self.path)
        path = parsed.path
        server = self.server_version.split("/", 1)[0]
        # shared operator routes: before any per-server auth (a
        # scraper/diagnoser holds no storage keys) and outside their
        # own request counts, traces and flight records
        if self.command == "GET" and path == "/healthz":
            # liveness: no probes, no locks beyond _send — a wedged
            # process fails this by not answering, nothing else does
            self._send(200, {"status": "alive"})
            return
        if self.command == "GET" and path == "/readyz":
            _serve_readyz(self)
            return
        if self.command == "GET" and path == "/metrics":
            _serve_metrics(self, parsed.query)
            return
        if path.startswith("/admin/"):
            # diagnostics expose payloads and traces: bearer-gated once
            # PIO_ADMIN_TOKEN is set (liveness/metrics stay open above)
            if not _admin_authorized(self):
                self._send(401, {"message": "missing or invalid bearer "
                                            "token (PIO_ADMIN_TOKEN)"},
                           extra_headers={"WWW-Authenticate": "Bearer"})
                return
            if self.command == "GET" and path == "/admin/flight":
                _serve_admin_flight(self, parsed.query)
                return
            if self.command == "POST" and path == "/admin/profile":
                _serve_admin_profile(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/slo":
                self._send(200, slo.MONITOR.report())
                return
            if path == "/admin/chaos":
                _serve_admin_chaos(self)
                return
            if self.command == "GET" and path == "/admin/timeline":
                _serve_admin_timeline(self)
                return
            if self.command == "GET" and path == "/admin/tail":
                _serve_admin_tail(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/spans":
                _serve_admin_spans(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/trace":
                _serve_admin_trace(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/fleet/metrics":
                _serve_fleet_metrics(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/fleet/tail":
                _serve_fleet_tail(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/prof":
                _serve_admin_prof(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/fleet/prof":
                _serve_fleet_prof(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/journal":
                _serve_admin_journal(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/anomaly":
                _serve_admin_anomaly(self)
                return
            if self.command == "GET" and path == "/admin/fleet/journal":
                _serve_fleet_journal(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/fleet/anomaly":
                _serve_fleet_anomaly(self)
                return
            if self.command == "GET" and path == "/admin/data":
                _serve_admin_data(self, parsed.query)
                return
            if self.command == "GET" and path == "/admin/fleet/data":
                _serve_fleet_data(self)
                return
            if path == "/admin/fleet":
                _serve_admin_fleet(self)
                return
            if path == "/admin/quality":
                _serve_admin_quality(self)
                return
            if self.command == "GET" and path == "/admin/memory":
                # device-memory accounting plane (obs/memacct.py):
                # per-model ledger attribution, headroom + basis,
                # train peaks and the last preflight decision
                from predictionio_tpu.obs import memacct

                self._send(200, memacct.report())
                return
            if self.command == "GET" and path == "/admin/resilience":
                # breaker states + admission snapshot (when the server
                # has one) + active chaos: the one-stop degraded-mode
                # diagnosis surface
                admission = getattr(self.server_ref, "admission", None)
                self._send(200, {
                    "circuits": respolicy.breakers_snapshot(),
                    "admission": (admission.snapshot()
                                  if admission is not None else None),
                    "chaos": chaos.describe(),
                })
                return
        # the inbound id is untrusted: anything not id-shaped (header
        # injection attempts, oversized strings) is re-minted, never
        # echoed into response headers or span logs
        raw_id = self.headers.get(trace.TRACE_HEADER, "")
        accepted = trace.valid_trace_id(raw_id)
        trace_id = raw_id if accepted else trace.new_trace_id()
        # cross-process parenting (obs/collect.py stitching): the
        # caller's span id rides X-PIO-Parent-Span; this edge's span
        # parents to it so the per-process rings assemble into ONE
        # tree. Only honored beside an ACCEPTED trace id — a parent
        # with no trace is noise, same shape discipline as the id.
        raw_parent = self.headers.get(trace.PARENT_HEADER, "")
        parent_span = raw_parent if (
            accepted and trace.valid_span_id(raw_parent)) else None
        token = trace.activate(trace_id, parent_span)
        route = metrics_route(path)
        fkey = flight.begin(trace_id, server, self.command, route)
        # register this handler thread with the continuous profiler:
        # samples taken during the request carry its trace id + route
        # (per-endpoint and slow-cohort flame slices)
        contprof.request_begin(trace_id, route)
        inflight = _IN_FLIGHT.labels(server)
        inflight.inc()
        t0 = time.perf_counter()
        name = server.lower()
        name = name.removeprefix("pio") or name
        error: Optional[str] = None
        try:
            # server= stamps the owning process on the edge span: the
            # trace collector attributes every descendant span to the
            # nearest ancestor edge's server (a shared-ring threaded
            # fleet cannot attribute by which member answered)
            with trace.span(f"http.{name}", method=self.command,
                            route=route, server=name):
                fn(self)
        except BaseException as e:
            # an exception ESCAPING a handler (their own except blocks
            # already answered anything they understood) is exactly the
            # evidence the flight recorder exists for
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            inflight.dec()
            status = getattr(self, "_metrics_status", None)
            # the dominant host frame the sampler observed during this
            # request's window stamps the record BEFORE it seals, so a
            # slow record names code, not just stages
            dominant = contprof.request_end()
            if dominant is not None:
                flight.note_field("dominant_frame", dominant)
            # seal the flight record while the trace is still active so
            # the slow-request log line carries the trace id
            flight.finish(fkey, status, error)
            trace.deactivate(token)
            if status is not None:
                _REQUESTS_TOTAL.labels(server, self.command, route,
                                       str(status)).inc()
                # the trace id rides along as an OpenMetrics exemplar:
                # a collector can jump from a latency bucket straight
                # to this request's trace
                _REQUEST_SECONDS.labels(server, self.command, route).observe(
                    time.perf_counter() - t0,
                    exemplar={"trace_id": trace_id})

    wrapper._pio_instrumented = True
    return wrapper


class JSONRequestHandler(BaseHTTPRequestHandler):
    """Base handler: JSON responses, body parsing, quiet logging."""

    server_version = "PIOServer/0.1"
    server_ref: Any = None  # set via subclass attribute by each server
    # HTTP/1.1 keep-alive: every response carries Content-Length via
    # _send (which also drains unread request bodies), so persistent
    # connections are safe — serving clients skip per-request TCP setup.
    # Idle connections release their handler thread after `timeout`.
    protocol_version = "HTTP/1.1"
    timeout = 120
    # TCP_NODELAY (socketserver.StreamRequestHandler knob): without it,
    # Nagle + the client's delayed ACK add a flat ~40ms to every small
    # request/response pair — 4x the entire serving latency budget
    # (BASELINE north-star: p50 < 10ms)
    disable_nagle_algorithm = True

    def __init_subclass__(cls, **kwargs):
        # telemetry is attached HERE, once: any subclass's do_* routing
        # methods are wrapped with the /metrics route, trace-context
        # activation and request metrics — the event server, engine
        # server, storage server, dashboard and admin API inherit the
        # whole observability surface without per-server wiring
        super().__init_subclass__(**kwargs)
        for mname in ("do_GET", "do_POST", "do_PUT", "do_DELETE"):
            fn = cls.__dict__.get(mname)
            if fn is not None:
                setattr(cls, mname, _instrument(fn))

    def log_message(self, fmt, *args):
        log.debug("%s: " + fmt, self.server_version, *args)

    def handle_one_request(self):
        # per-request state: the handler object lives for a whole
        # keep-alive connection, and routes that stream their response
        # without _send (NDJSON finds, scan fetches) would otherwise
        # leave a stale True that makes the NEXT request's drain guard
        # skip an unread body and desynchronize the connection
        self._body_consumed = False
        self._metrics_status = None  # captured by send_response
        super().handle_one_request()

    def send_response(self, code, message=None):
        # every response path (including streamed NDJSON/scan bodies
        # that never go through _send) funnels through here — the one
        # place the final status is always known for request metrics
        self._metrics_status = code
        super().send_response(code, message)

    def _send(self, status: int, body: Any,
              content_type: str = "application/json; charset=UTF-8",
              extra_headers: Optional[dict] = None) -> None:
        t_ser = time.perf_counter()
        if isinstance(body, bytes):
            data = body
        elif isinstance(body, str):
            data = body.encode()
        else:
            data = json.dumps(body).encode()
        # Consume any unread request body before responding: under
        # HTTP/1.1 keep-alive an unread body desynchronizes the
        # connection — the next request would be parsed from leftover
        # body bytes (matters for short-circuit responses: auth denial,
        # unknown route). Cheap no-op when the handler already read it.
        # Oversized undrained bodies (> 1 MB — only short-circuit paths
        # leave bodies unread) and chunked request bodies (no length to
        # drain by) close the connection instead.
        try:
            unread = int(self.headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            unread = 0
        if not getattr(self, "_body_consumed", False):
            if self.headers.get("Transfer-Encoding"):
                self.close_connection = True
            elif unread > (1 << 20):
                self.close_connection = True
            elif unread:
                self.rfile.read(unread)
        self._body_consumed = True  # this request's body is settled
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        trace_id = trace.current_trace_id()
        if trace_id:
            # echo the request's trace id so clients can join their logs
            self.send_header(trace.TRACE_HEADER, trace_id)
        if self.close_connection:
            self.send_header("Connection", "close")
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)
        # response encode+write billed to the request's flight record
        # (no-op when no record is open, e.g. the shared /metrics route)
        flight.note_stage("serialize", time.perf_counter() - t_ser)

    def _read_body(self) -> bytes:
        t0 = time.perf_counter()
        length = int(self.headers.get("Content-Length", 0))
        self._body_consumed = True
        data = self.rfile.read(length) if length else b""
        flight.note_stage("parse", time.perf_counter() - t0)
        return data

    def _read_json(self) -> Any:
        """Parsed JSON body; raises json.JSONDecodeError."""
        return json.loads(self._read_body() or b"{}")

    def _do_get_fallback(self):
        self._send(404, {"message": "Not Found"})

    def _do_post_fallback(self):
        self._send(404, {"message": "Not Found"})

    # servers that define no do_GET/do_POST of their own still expose
    # the shared routes (/metrics, /admin/flight, /admin/profile —
    # served by the _instrument wrapper) and 404 everything else
    do_GET = _instrument(_do_get_fallback)
    do_POST = _instrument(_do_post_fallback)


class _ThreadingHTTPServer(ThreadingHTTPServer):
    # the stdlib default backlog of 5 drops connections under serving
    # bursts (micro-batched engines legitimately queue dozens)
    request_queue_size = 128


class HTTPServerBase:
    """Bind (with retry), run on a daemon thread, stop cleanly.

    Bind-retry contract from the reference engine server
    (CreateServer.scala:340-350): ``bind_retries`` attempts, 1s apart.
    """

    def __init__(self, host: str, port: int, handler_cls: type,
                 bind_retries: int = 1):
        # the in-flight gauge's label for THIS server class — drain
        # derives it the same way _instrument does, so a rename cannot
        # silently point the drain wait at an untouched child
        self._server_label = handler_cls.server_version.split("/", 1)[0]
        handler = type("Handler", (handler_cls,), {"server_ref": self})
        attempts = max(1, bind_retries)
        for attempt in range(attempts):
            try:
                self.httpd = _ThreadingHTTPServer((host, port), handler)
                break
            except OSError as e:
                log.warning("bind attempt %d failed: %s", attempt + 1, e)
                if attempt + 1 == attempts:
                    raise
                time.sleep(1)
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        # this instance's hold on the process-global continuous
        # profiler: retained on start, released exactly once on stop
        # (drain_stop -> stop must not double-release the refcount)
        self._prof_owner = f"{type(self).__name__}:{id(self):#x}"
        self._prof_retained = False

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @staticmethod
    def _start_env_services() -> None:
        """Env-driven process services every server boot wires up:
        the metrics pusher, the SLO alert webhook sink, declarative
        SLO objectives, and the chaos harness (all no-ops without
        their env vars)."""
        push.start_from_env()
        alerts.start_from_env()
        slo.configure_from_env()
        chaos.configure_from_env()

    def _retain_profiler(self) -> None:
        """Hold the continuous profiler while this server serves —
        refcounted and idempotent in contprof, so multi-server
        processes share ONE sampler and a /reload (stop + start of the
        same instance) never leaves a second one behind."""
        if not self._prof_retained:
            self._prof_retained = True
            contprof.retain(self._prof_owner)

    def _release_profiler(self) -> None:
        if self._prof_retained:
            self._prof_retained = False
            contprof.release(self._prof_owner)

    def start(self):
        # flag set BEFORE the thread is scheduled so a stop() racing
        # start() still runs shutdown() (which blocks until the serve
        # loop has run and exited) instead of closing the socket under it
        self._serving = True
        self._start_env_services()
        self._retain_profiler()
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        log.info("%s listening on %s", type(self).__name__, self.port)
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self._start_env_services()
        self._retain_profiler()
        self.httpd.serve_forever()

    def stop(self) -> None:
        """Stop serving and close the socket; the port is free on return.

        Safe from handler threads (they are daemons, so server_close
        does not join them) and from threads that never started serving.
        """
        if self._serving:
            self.httpd.shutdown()
            self._serving = False
        self.httpd.server_close()
        self._release_profiler()

    def inflight_count(self) -> float:
        """Requests currently inside handlers of THIS server class
        (shared-process caveat: the gauge is labeled per server CLASS,
        so two same-class servers in one process read a joint count —
        the drain then waits for both, which errs safe)."""
        family = metrics.REGISTRY.get("pio_http_requests_in_flight")
        if family is None:
            return 0.0
        return family.labels(self._server_label).value

    def drain_stop(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop ACCEPTING first (serve loop halted,
        listening socket closed so new connections are refused instead
        of rotting in the backlog), then wait — bounded by ``timeout``
        (default ``PIO_DRAIN_TIMEOUT``, 30s) — for in-flight handlers
        to write their responses, then ``stop()`` (which also stops
        per-server subsystems, e.g. the engine server's batcher).
        Returns True when everything drained inside the window."""
        if timeout is None:
            timeout = drain_timeout()
        if self._serving:
            self.httpd.shutdown()
            self._serving = False
        self.httpd.server_close()
        deadline = time.monotonic() + max(0.0, timeout)
        while self.inflight_count() > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        leftover = int(self.inflight_count())
        if leftover:
            log.warning(
                "%s drain window (%.1fs) expired with %d request(s) "
                "still in flight — stopping anyway", type(self).__name__,
                timeout, leftover)
        self.stop()
        return leftover == 0


DEFAULT_DRAIN_TIMEOUT_SEC = 30.0


def drain_timeout() -> float:
    """The SIGTERM drain window (``PIO_DRAIN_TIMEOUT`` seconds)."""
    return max(0.0, metrics.env_float("PIO_DRAIN_TIMEOUT",
                                      DEFAULT_DRAIN_TIMEOUT_SEC))


def install_drain_handler(*servers, timeout: Optional[float] = None):
    """SIGTERM -> drain-then-stop for every server of this process.

    The one graceful-shutdown path shared by the engine, event and
    storage server mains (previously a kill mid-request dropped the
    connection on the floor): on SIGTERM each server stops accepting,
    finishes what it already admitted (bounded by ``PIO_DRAIN_TIMEOUT``)
    and stops — after which ``serve_forever`` returns and the main
    exits normally. The drain runs on its OWN NON-daemon thread, and
    both properties are load-bearing: the signal fires in the main
    thread — usually the one blocked inside ``serve_forever`` — so
    calling ``shutdown()`` there would deadlock waiting for a serve
    loop that cannot advance under the handler; and the very first
    thing ``drain_stop`` does is unblock that ``serve_forever``, after
    which the main returns and the interpreter starts exiting — a
    DAEMON drain thread (and the daemon handler threads still writing
    responses) would be killed mid-drain, dropping exactly the
    connections this handler exists to protect. Non-daemon, the
    interpreter waits for the drain to finish before finalizing.

    Returns the installed handler so tests can invoke it directly
    (``handler()``) without delivering a real signal. Must be called
    from the main thread (CPython signal contract)."""
    import signal

    def _drain(signum=None, frame=None):
        def run():
            log.info("SIGTERM: draining %d server(s), window %.1fs",
                     len(servers),
                     drain_timeout() if timeout is None else timeout)
            for server in servers:
                try:
                    server.drain_stop(timeout)
                except Exception:  # noqa: BLE001 — one server's failed
                    # drain must not strand its siblings un-stopped
                    log.exception("drain failed for %r", server)

        # non-daemon: holds the interpreter open until the drain
        # completes (see docstring) — bounded by drain_stop's window
        threading.Thread(target=run, daemon=False,
                         name="pio-drain").start()

    signal.signal(signal.SIGTERM, _drain)
    return _drain
