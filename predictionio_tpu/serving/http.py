"""Shared HTTP plumbing for the framework's servers.

One copy of the JSON response writer, body reader, bind-retry loop and
thread lifecycle used by the event server, engine server, dashboard and
admin API (the reference gets this from spray; each server here is a
stdlib ThreadingHTTPServer).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

log = logging.getLogger(__name__)


class JSONRequestHandler(BaseHTTPRequestHandler):
    """Base handler: JSON responses, body parsing, quiet logging."""

    server_version = "PIOServer/0.1"
    server_ref: Any = None  # set via subclass attribute by each server
    # HTTP/1.1 keep-alive: every response carries Content-Length via
    # _send (which also drains unread request bodies), so persistent
    # connections are safe — serving clients skip per-request TCP setup.
    # Idle connections release their handler thread after `timeout`.
    protocol_version = "HTTP/1.1"
    timeout = 120
    # TCP_NODELAY (socketserver.StreamRequestHandler knob): without it,
    # Nagle + the client's delayed ACK add a flat ~40ms to every small
    # request/response pair — 4x the entire serving latency budget
    # (BASELINE north-star: p50 < 10ms)
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        log.debug("%s: " + fmt, self.server_version, *args)

    def handle_one_request(self):
        # per-request state: the handler object lives for a whole
        # keep-alive connection, and routes that stream their response
        # without _send (NDJSON finds, scan fetches) would otherwise
        # leave a stale True that makes the NEXT request's drain guard
        # skip an unread body and desynchronize the connection
        self._body_consumed = False
        super().handle_one_request()

    def _send(self, status: int, body: Any,
              content_type: str = "application/json; charset=UTF-8",
              extra_headers: Optional[dict] = None) -> None:
        if isinstance(body, bytes):
            data = body
        elif isinstance(body, str):
            data = body.encode()
        else:
            data = json.dumps(body).encode()
        # Consume any unread request body before responding: under
        # HTTP/1.1 keep-alive an unread body desynchronizes the
        # connection — the next request would be parsed from leftover
        # body bytes (matters for short-circuit responses: auth denial,
        # unknown route). Cheap no-op when the handler already read it.
        # Oversized undrained bodies (> 1 MB — only short-circuit paths
        # leave bodies unread) and chunked request bodies (no length to
        # drain by) close the connection instead.
        try:
            unread = int(self.headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            unread = 0
        if not getattr(self, "_body_consumed", False):
            if self.headers.get("Transfer-Encoding"):
                self.close_connection = True
            elif unread > (1 << 20):
                self.close_connection = True
            elif unread:
                self.rfile.read(unread)
        self._body_consumed = True  # this request's body is settled
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        if self.close_connection:
            self.send_header("Connection", "close")
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        self._body_consumed = True
        return self.rfile.read(length) if length else b""

    def _read_json(self) -> Any:
        """Parsed JSON body; raises json.JSONDecodeError."""
        return json.loads(self._read_body() or b"{}")


class _ThreadingHTTPServer(ThreadingHTTPServer):
    # the stdlib default backlog of 5 drops connections under serving
    # bursts (micro-batched engines legitimately queue dozens)
    request_queue_size = 128


class HTTPServerBase:
    """Bind (with retry), run on a daemon thread, stop cleanly.

    Bind-retry contract from the reference engine server
    (CreateServer.scala:340-350): ``bind_retries`` attempts, 1s apart.
    """

    def __init__(self, host: str, port: int, handler_cls: type,
                 bind_retries: int = 1):
        handler = type("Handler", (handler_cls,), {"server_ref": self})
        attempts = max(1, bind_retries)
        for attempt in range(attempts):
            try:
                self.httpd = _ThreadingHTTPServer((host, port), handler)
                break
            except OSError as e:
                log.warning("bind attempt %d failed: %s", attempt + 1, e)
                if attempt + 1 == attempts:
                    raise
                time.sleep(1)
        self._thread: Optional[threading.Thread] = None
        self._serving = False

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self):
        # flag set BEFORE the thread is scheduled so a stop() racing
        # start() still runs shutdown() (which blocks until the serve
        # loop has run and exited) instead of closing the socket under it
        self._serving = True
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        log.info("%s listening on %s", type(self).__name__, self.port)
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self.httpd.serve_forever()

    def stop(self) -> None:
        """Stop serving and close the socket; the port is free on return.

        Safe from handler threads (they are daemons, so server_close
        does not join them) and from threads that never started serving.
        """
        if self._serving:
            self.httpd.shutdown()
            self._serving = False
        self.httpd.server_close()
