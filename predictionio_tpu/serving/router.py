"""Health-routed query router: the fleet's public front door.

One process on the public port places ``POST /queries.json`` across
the supervisor's replicas (serving/fleet.py):

  placement    power-of-two-choices least-loaded: sample two ready
               replicas, send to the one with fewer outstanding
               router requests — near-best-of-N balance at O(1) cost,
               and a hung replica's growing outstanding count
               deprioritizes it automatically
  breakers     each replica sits behind its own CircuitBreaker
               (``replica:<name>``, resilience/policy.py): transport
               failures open it and the router routes around the
               replica until a half-open probe succeeds
  reroute      a transport-level failure (connection refused, reset —
               a crashed replica) is retried ONCE against a different
               replica; with >=2 replicas a single crash costs zero
               client-visible 5xx. ``X-PIO-Non-Idempotent`` requests
               reroute only on provably-unsent failures (connection
               refused) — a reset mid-exchange may already have
               executed the query's side effect
  hedging      when a reply exceeds the trailing-quantile hedge
               deadline (``PIO_HEDGE_QUANTILE`` of the recent latency
               window, floored at ``PIO_HEDGE_MIN_MS``), a second
               request races on another replica and the first answer
               wins — the direct lever on the straggler-set p99
               (idempotent queries only: ``X-PIO-Non-Idempotent: 1``
               or ``PIO_HEDGE_QUANTILE=0`` opts out)
  canary lane  while the fleet runs a canary (serving/fleet.py), every
               2xx answer is also observed into the per-lane
               ``pio_canary_request_seconds{lane}`` histogram
               (baseline vs canary), and every
               ``PIO_CANARY_SAMPLE_EVERY``-th baseline-served
               idempotent query is SHADOWED to the canary replica
               after the client is answered: the paired answers are
               diffed through obs/quality.py's comparer and feed the
               promote/rollback verdict — the client never waits on
               the shadow
  passthrough  a replica's application answer is the client's answer:
               ``429 Retry-After`` (admission shed) and
               ``X-PIO-Degraded`` pass through UN-retried — retrying
               shed traffic amplifies the overload it signals —
               counted in ``pio_router_passthrough_total{reason}``

Forwarded attempts (and hedges) run on a small REUSABLE worker pool
(``PIO_ROUTER_POOL_SIZE``, default 16) instead of a fresh thread per
proxied query; when every worker is busy the attempt runs on a one-off
overflow thread (a hedge timer must not queue behind a stalled fleet)
and ``pio_router_pool_saturated_total`` counts it.

Everything else of the operator surface (``/healthz``, ``/readyz``
with a fleet-readiness probe, ``/metrics``, ``/admin/fleet``, ...)
is inherited from serving/http.py. ``GET /reload`` starts the
fleet-coordinated rolling hot-swap (202; progress at /admin/fleet) —
the multi-replica analogue of the single server's reload contract.
"""

from __future__ import annotations

import collections
import http.client
import json
import logging
import os
import queue
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from predictionio_tpu.obs import health, metrics, quality, trace
from predictionio_tpu.resilience.policy import breaker_for
from predictionio_tpu.serving.fleet import FleetSupervisor, Replica
from predictionio_tpu.serving.http import (HTTPServerBase,
                                           JSONRequestHandler,
                                           _admin_authorized)

log = logging.getLogger(__name__)

DEFAULT_PORT = 8000

_HEDGES = metrics.counter(
    "pio_router_hedges_total",
    "Hedged second requests issued after the hedge deadline",
)
_REROUTES = metrics.counter(
    "pio_router_reroutes_total",
    "Requests rerouted to another replica after a transport failure",
)
_PASSTHROUGH = metrics.counter(
    "pio_router_passthrough_total",
    "Replica answers passed through un-retried, by reason "
    "(shed = 429 Retry-After, degraded = X-PIO-Degraded)",
    ("reason",),
)
_NO_REPLICA = metrics.counter(
    "pio_router_no_replica_total",
    "Requests answered 503 because no ready replica was selectable",
)
_HEDGE_DEADLINE = metrics.gauge(
    "pio_router_hedge_deadline_seconds",
    "Current trailing-quantile hedge deadline (0 while unarmed)",
)
_HEDGE_RESCUES = metrics.counter(
    "pio_router_hedge_rescues_total",
    "Hedged requests whose hedge answer won while the primary attempt "
    "was still in flight: the client got a timely answer, so the "
    "serving-latency SLO credits these as good even though the slow "
    "primary's eventual completion lands an over-threshold histogram "
    "observation (obs/slo.py good_credit_metric)",
)
_POOL_SATURATED = metrics.counter(
    "pio_router_pool_saturated_total",
    "route_query submissions that found every pooled worker busy and "
    "ran on a one-off overflow thread instead (raise "
    "PIO_ROUTER_POOL_SIZE if this grows under steady load)",
)


class _WorkerPool:
    """Reusable worker threads for the router's forwarded attempts
    (ROADMAP item B follow-up): every proxied query used to spawn a
    fresh thread — and a hedge a second one — putting thread-spawn
    cost and churn on the hot path at real qps. ``size`` long-lived
    workers (started lazily) drain a task queue instead. When every
    worker is occupied, the task runs on a one-off overflow thread
    rather than queueing — a hedge fired at the deadline must not wait
    behind a stalled fleet's attempts — and the saturation is counted
    in ``pio_router_pool_saturated_total``."""

    def __init__(self, size: int):
        self._size = max(1, size)
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._outstanding = 0   # tasks queued or running on pool workers
        self._started = 0
        self._stopped = False

    def _worker(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                return
            fn, args = task
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — a task error must not
                # kill the shared worker (attempts report their own
                # failures through the results queue)
                log.exception("router pool task failed")
            finally:
                with self._lock:
                    self._outstanding -= 1

    def submit(self, fn, *args) -> None:
        overflow = False
        with self._lock:
            if self._stopped:
                overflow = True
            elif self._outstanding >= self._size:
                overflow = True
            else:
                self._outstanding += 1
                if self._started < min(self._outstanding, self._size):
                    self._started += 1
                    threading.Thread(
                        target=self._worker, daemon=True,
                        name=f"router-pool-{self._started}").start()
        if overflow:
            _POOL_SATURATED.inc()
            threading.Thread(target=self._run_overflow, args=(fn, args),
                             daemon=True,
                             name="router-pool-overflow").start()
        else:
            self._q.put((fn, args))

    @staticmethod
    def _run_overflow(fn, args) -> None:
        try:
            fn(*args)
        except Exception:  # noqa: BLE001 — same contract as _worker
            log.exception("router overflow task failed")

    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            started = self._started
        for _ in range(started):
            self._q.put(None)


class HedgeClock:
    """Trailing latency window -> the hedge deadline.

    Armed only once ``min_samples`` replies have built a trustworthy
    quantile (hedging off a cold window would hedge everything);
    floored at ``PIO_HEDGE_MIN_MS`` so scheduler noise at microsecond
    latencies cannot turn every request into two.

    ``deadline()`` runs on every routed query: the window sort is
    amortized by caching the quantile estimate and recomputing only
    after ``RECALC_EVERY`` new observations (the trailing quantile is
    an estimate already — a <=16-sample-stale one changes nothing)."""

    WINDOW = 512
    RECALC_EVERY = 16

    def __init__(self, min_samples: int = 20):
        self._lock = threading.Lock()
        self._window: collections.deque = collections.deque(
            maxlen=self.WINDOW)
        self.min_samples = min_samples
        self._dirty = 0
        self._cached: Optional[Tuple[float, float]] = None  # (q, estimate)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._window.append(seconds)
            self._dirty += 1

    def deadline(self) -> Optional[float]:
        q = metrics.env_float("PIO_HEDGE_QUANTILE", 0.95)
        if q <= 0.0:
            return None
        q = min(q, 1.0)
        with self._lock:
            n = len(self._window)
            if n < self.min_samples:
                _HEDGE_DEADLINE.set(0.0)
                return None
            if (self._cached is None or self._cached[0] != q
                    or self._dirty >= self.RECALC_EVERY):
                values = sorted(self._window)
                self._cached = (q, values[min(n - 1, int(n * q))])
                self._dirty = 0
            estimate = self._cached[1]
        floor = metrics.env_float("PIO_HEDGE_MIN_MS", 10.0) / 1e3
        deadline = max(estimate, floor)
        _HEDGE_DEADLINE.set(deadline)
        return deadline


class ReplicaTransportError(ConnectionError):
    """Transport failure talking to a replica. ``maybe_executed`` is
    False only when the request provably never reached the replica
    (connection refused) — the reroute/replay decision for
    non-idempotent queries hangs on it."""

    def __init__(self, message: str, maybe_executed: bool = True):
        super().__init__(message)
        self.maybe_executed = maybe_executed


class _ReplicaClient:
    """A keep-alive connection pool to one replica address (pooled
    per (name, port): a restarted replica lands on a new port and
    therefore a fresh pool)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._lock = threading.Lock()
        self._idle: List[http.client.HTTPConnection] = []

    def request(self, method: str, path: str, body: Optional[bytes],
                headers: Dict[str, str], timeout: float,
                replay_safe: bool = True):
        """(status, body bytes, headers dict); transport problems raise
        ReplicaTransportError so the policy/breaker taxonomy applies.

        A POOLED connection that dies before yielding any response is
        retried ONCE on a fresh connection silently: the replica's
        handler legitimately closes idle keep-alives after its read
        timeout, and a post-lull burst popping a stack of stale sockets
        must not read as replica failures (it would open the breaker of
        a perfectly healthy replica). Only the fresh-connection verdict
        escapes to the caller/breaker. With ``replay_safe=False``
        (non-idempotent queries) the silent replay only happens when
        the pooled attempt provably never sent (connection refused) —
        a mid-exchange death may have executed the query already."""
        with self._lock:
            conn = self._idle.pop() if self._idle else None
        pooled = conn is not None
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=timeout)
        try:
            return self._one_request(conn, method, path, body, headers,
                                     timeout)
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            # never replay a TIMEOUT: the stale-keepalive failures the
            # replay exists for (reset/BadStatusLine on a dead socket)
            # surface instantly, while a timeout already consumed the
            # full attempt budget — replaying would spend it twice on a
            # hung replica AND queue a duplicate query there, doubling
            # the breaker's failure-detection window
            if pooled and not isinstance(e, TimeoutError) and (
                    replay_safe or isinstance(e, ConnectionRefusedError)):
                fresh = http.client.HTTPConnection(self.host, self.port,
                                                   timeout=timeout)
                try:
                    return self._one_request(fresh, method, path, body,
                                             headers, timeout)
                except (OSError, http.client.HTTPException) as e2:
                    fresh.close()
                    e = e2
            raise ReplicaTransportError(
                f"replica {self.host}:{self.port}: "
                f"{type(e).__name__}: {e}",
                maybe_executed=not isinstance(e, ConnectionRefusedError),
            ) from e

    def _one_request(self, conn, method, path, body, headers, timeout):
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        else:
            conn.timeout = timeout
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        resp_headers = dict(resp.headers)
        if resp.will_close:
            conn.close()
        else:
            with self._lock:
                if len(self._idle) < 32:
                    self._idle.append(conn)
                    conn = None
            if conn is not None:
                conn.close()
        return resp.status, data, resp_headers

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()


class _RouterRequestHandler(JSONRequestHandler):
    server_version = "PIORouter/0.1"

    def do_GET(self):
        path = urlparse(self.path).path
        if path == "/":
            self._send(200, self.server_ref.status())
        elif path == "/reload":
            # same bearer gate as POST /admin/fleet {"reload": true} —
            # an unauthenticated route to the identical fleet-wide
            # drain+recompile would bypass the token one route over
            if not _admin_authorized(self):
                self._send(401, {"message": "missing or invalid bearer "
                                            "token (PIO_ADMIN_TOKEN)"},
                           extra_headers={"WWW-Authenticate": "Bearer"})
                return
            from urllib.parse import parse_qs

            force = (parse_qs(urlparse(self.path).query)
                     .get("force") or ["0"])[0].lower() in ("1", "true")
            started = self.server_ref.fleet.start_rolling_reload(
                force=force)
            self._send(
                202 if started else 409,
                {"message": ("rolling reload started — progress at "
                             "/admin/fleet" if started else
                             "a rolling reload is already running")})
        else:
            self._send(404, {"message": "Not Found"})

    def do_POST(self):
        path = urlparse(self.path).path
        if path == "/queries.json":
            body = self._read_body()
            idempotent = (self.headers.get("X-PIO-Non-Idempotent")
                          or "").lower() not in ("1", "true")
            status, data, extra, ctype = self.server_ref.route_query(
                body, idempotent=idempotent)
            self._send(status, data, content_type=ctype,
                       extra_headers=extra)
        else:
            self._send(404, {"message": "Not Found"})


class QueryRouter(HTTPServerBase):
    """The fleet's public HTTP front door (one per fleet)."""

    def __init__(
        self,
        fleet: FleetSupervisor,
        host: str = "0.0.0.0",
        port: int = DEFAULT_PORT,
        bind_retries: int = 3,
        rng: Optional[random.Random] = None,
    ):
        self.fleet = fleet
        self.storage = None  # the router holds no storage of its own
        self.hedge = HedgeClock()
        self._rng = rng or random.Random()
        self._pools: Dict[Tuple[str, int], _ReplicaClient] = {}
        self._pools_lock = threading.Lock()
        # hot-path worker pool: forwarded attempts (and hedges) run on
        # reusable threads instead of a fresh spawn per query
        self._worker_pool = _WorkerPool(
            metrics.env_int("PIO_ROUTER_POOL_SIZE", 16))
        # canary paired-sampling cadence (every Nth baseline answer
        # shadows to the canary replica)
        self._pair_lock = threading.Lock()
        self._pair_counter = 0
        super().__init__(host, port, _RouterRequestHandler,
                         bind_retries=bind_retries)

    # -- readiness: the router is ready while it can place a query ----------
    def storage_readyz_probe(self) -> health.ProbeResult:
        n, size = self.fleet.ready_count(), self.fleet.size()
        if n == 0:
            return health.failed("no ready replicas to route to")
        if n < size:
            return health.degraded(f"{n}/{size} replicas in rotation")
        return health.ok(f"{n}/{size} replicas in rotation")

    # -- replica selection ---------------------------------------------------
    def _client(self, replica: Replica) -> _ReplicaClient:
        key = ("127.0.0.1", replica.port)
        with self._pools_lock:
            client = self._pools.get(key)
            if client is None:
                client = self._pools[key] = _ReplicaClient(*key)
                # prune pools for ports no replica listens on anymore
                # (restarts move ports; dead pools pin dead sockets)
                live = {("127.0.0.1", r.port) for r in self.fleet.replicas}
                for stale in [k for k in self._pools if k not in live]:
                    self._pools.pop(stale).close()
        return client

    def _select(self, exclude: set) -> Optional[Replica]:
        """Power-of-two-choices among ready, breaker-admitted
        replicas not yet tried for this request."""
        candidates = [r for r in self.fleet.ready_replicas()
                      if r.name not in exclude]
        while candidates:
            if len(candidates) == 1:
                pick = candidates[0]
            else:
                a, b = self._rng.sample(candidates, 2)
                pick = a if a.outstanding() <= b.outstanding() else b
            if breaker_for(f"replica:{pick.name}").allow():
                return pick
            candidates.remove(pick)
        return None

    # -- the forwarding core -------------------------------------------------
    def _attempt(self, replica: Replica, body: bytes,
                 headers: Dict[str, str], deadline: float,
                 results: "queue.Queue",
                 idempotent: bool = True,
                 ctx: Optional[trace.SpanContext] = None,
                 hedge: bool = False) -> None:
        """One forwarded request; its verdict lands in ``results`` as
        (replica, (status, data, headers)) or (replica, exception).

        Each attempt runs under its OWN ``router.attempt`` span (the
        request's trace context is re-activated on this pool thread):
        a hedged second attempt is a SIBLING span marked ``hedge``, and
        the replica's edge span parents to the attempt via the headers
        ``trace.traced_headers`` attaches — the federation collector
        (obs/collect.py) stitches the whole placement decision into one
        tree."""
        breaker = breaker_for(f"replica:{replica.name}")
        replica.begin_request()
        token = trace.activate_context(ctx) if ctx is not None else None
        t0 = time.perf_counter()
        try:
            attrs = {"replica": replica.name}
            if hedge:
                attrs["hedge"] = True
            with trace.span("router.attempt", **attrs):
                answer = self._client(replica).request(
                    "POST", "/queries.json", body,
                    trace.traced_headers(headers),
                    timeout=max(0.05, deadline - time.monotonic()),
                    replay_safe=idempotent)
        except ConnectionError as e:
            breaker.record_failure()
            results.put((replica, e))
            return
        except Exception as e:  # noqa: BLE001 — an attempt thread
            # dying silently would strand the waiting handler
            log.exception("attempt against %s failed", replica.name)
            results.put((replica, e))
            return
        finally:
            replica.end_request()
            if token is not None:
                trace.deactivate(token)
        breaker.record_success()
        # only SERVED answers train the hedge clock: sub-millisecond
        # 429 sheds (or error fast-paths) under overload would collapse
        # the deadline to its floor and make every admitted query hedge
        # a duplicate onto the overloaded fleet — the amplification the
        # 429 passthrough exists to prevent
        if 200 <= answer[0] < 300:
            elapsed = time.perf_counter() - t0
            self.hedge.observe(elapsed)
            # canary analysis: the same served answers, tagged by lane,
            # feed the verdict's latency gate (obs/quality.py reads the
            # buckets back through the SLO burn math)
            canary_name = self.fleet.canary_replica_name()
            if canary_name is not None:
                quality.CANARY_SECONDS.labels(
                    quality.LANE_CANARY if replica.name == canary_name
                    else quality.LANE_BASELINE).observe(elapsed)
        results.put((replica, answer))

    def route_query(self, body: bytes, idempotent: bool = True):
        """Place one query: select, forward, hedge past the deadline,
        reroute transport failures, pass application answers through.
        Returns (status, payload, extra_headers, content_type) for the
        handler's ``_send``."""
        total = metrics.env_float("PIO_ROUTER_TIMEOUT", 30.0)
        deadline = time.monotonic() + total
        headers = {"Content-Type": "application/json"}
        # the trace context travels to the attempt's pool thread, where
        # each attempt opens its own span and attaches the trace/parent
        # headers (trace.TRACE_HEADER propagation lives there now)
        ctx = trace.current_context()
        results: "queue.Queue" = queue.Queue()
        tried: set = set()

        def launch(replica: Replica, hedge: bool = False) -> None:
            tried.add(replica.name)
            self._worker_pool.submit(
                self._attempt, replica, body, headers, deadline, results,
                idempotent, ctx, hedge)

        first = self._select(tried)
        if first is None:
            _NO_REPLICA.inc()
            return (503, {"message": "no ready replicas"},
                    {"Retry-After": "1"}, "application/json; charset=UTF-8")
        launch(first)
        hedge_after = self.hedge.deadline() if idempotent else None
        hedge_at = (time.monotonic() + hedge_after
                    if hedge_after is not None else None)
        outstanding = 1
        hedge_name: Optional[str] = None
        last_error: Optional[BaseException] = None
        # first non-2xx application answer, held while another attempt
        # is still in flight (see below)
        held = None
        while outstanding:
            now = time.monotonic()
            wait = deadline - now
            if hedge_at is not None:
                wait = min(wait, hedge_at - now)
            try:
                replica, outcome = results.get(timeout=max(0.001, wait))
            except queue.Empty:
                if hedge_at is not None and time.monotonic() >= hedge_at:
                    # slow first answer: race a second replica; first
                    # answer (either one) wins. One hedge per request —
                    # a second timer tick must not fan out further.
                    hedge_at = None
                    second = self._select(tried)
                    if second is not None:
                        _HEDGES.inc()
                        hedge_name = second.name
                        launch(second, hedge=True)
                        outstanding += 1
                    continue
                if time.monotonic() >= deadline:
                    break  # total deadline expired
                continue
            if isinstance(outcome, BaseException):
                outstanding -= 1
                last_error = outcome
                # transport failure: reroute once to a fresh replica
                # (bounded fan-out: primary + hedge + one reroute).
                # Non-idempotent queries only reroute when the failed
                # attempt provably never reached a replica — a
                # mid-exchange death may have executed the side effect
                maybe_executed = getattr(outcome, "maybe_executed", True)
                if (held is None and len(tried) < 3
                        and (idempotent or not maybe_executed)):
                    retry = self._select(tried)
                    if retry is not None:
                        _REROUTES.inc()
                        launch(retry)
                        outstanding += 1
                continue
            status, data, replica_headers = outcome
            outstanding -= 1
            if 200 <= status < 300 or not outstanding:
                if 200 <= status < 300 and idempotent:
                    # canary paired sampling: AFTER the client has its
                    # answer in hand (the shadow runs on the worker
                    # pool, never on this request's latency budget)
                    self._maybe_canary_pair(replica, body, data)
                if (200 <= status < 300 and outstanding
                        and replica.name == hedge_name):
                    # the hedge SAVED this request: its answer returns
                    # while the slow primary is still in flight. The
                    # primary's eventual completion will land an
                    # over-threshold serving-latency observation the
                    # client never experienced — this counter credits
                    # it back in the SLO burn accounting (obs/slo.py)
                    _HEDGE_RESCUES.inc()
                return self._passthrough(replica, status, data,
                                         replica_headers)
            # a non-2xx racer answer must not beat a primary attempt
            # that may yet succeed: a hedge landing on a shedding
            # replica answers 429 in sub-milliseconds, and returning it
            # immediately would convert a would-be-success into a
            # client-visible error. Hold it; it is the answer only if
            # nothing better arrives before the deadline.
            if held is None:
                held = (replica, outcome)
        if held is not None:
            replica, (status, data, replica_headers) = held
            return self._passthrough(replica, status, data,
                                     replica_headers)
        if last_error is not None:
            message = (f"all {len(tried)} attempted replica(s) failed: "
                       f"{type(last_error).__name__}: {last_error}")
        else:
            message = (f"no replica answered within {total:g}s "
                       f"({len(tried)} attempted)")
        return (502, {"message": message}, None,
                "application/json; charset=UTF-8")

    def _passthrough(self, replica: Replica, status: int, data: bytes,
                     replica_headers: Dict[str, str]):
        """A replica's application answer IS the client's answer —
        shed (429) and degraded responses especially travel un-retried,
        headers intact."""
        extra: Dict[str, str] = {"X-PIO-Replica": replica.name}
        if status == 429:
            _PASSTHROUGH.labels("shed").inc()
            retry_after = replica_headers.get("Retry-After")
            if retry_after:
                extra["Retry-After"] = retry_after
        degraded = replica_headers.get("X-PIO-Degraded")
        if degraded:
            _PASSTHROUGH.labels("degraded").inc()
            extra["X-PIO-Degraded"] = degraded
        ctype = replica_headers.get(
            "Content-Type", "application/json; charset=UTF-8")
        return status, data, extra, ctype

    # -- canary paired sampling ----------------------------------------------
    def _maybe_canary_pair(self, replica: Replica, body: bytes,
                           base_data: bytes) -> None:
        """While a canary is active: every ``PIO_CANARY_SAMPLE_EVERY``-th
        baseline-served 2xx answer re-plays the SAME query against the
        canary replica on a pool worker and feeds the answer diff into
        obs/quality.py's paired accumulators — the online analogue of
        the offline replay harness, through the identical differ."""
        canary_name = self.fleet.canary_replica_name()
        if canary_name is None or replica.name == canary_name:
            return
        every = max(1, metrics.env_int("PIO_CANARY_SAMPLE_EVERY", 4))
        with self._pair_lock:
            self._pair_counter += 1
            if self._pair_counter % every:
                return
        canary_replica = next(
            (r for r in self.fleet.replicas if r.name == canary_name), None)
        if canary_replica is None:
            return
        self._worker_pool.submit(self._canary_shadow, canary_replica,
                                 body, base_data, trace.current_context())

    def _canary_shadow(self, canary_replica: Replica, body: bytes,
                       base_data: bytes,
                       ctx: Optional[trace.SpanContext] = None) -> None:
        timeout = metrics.env_float("PIO_ROUTER_TIMEOUT", 30.0)
        canary_replica.begin_request()  # shadow load is real load:
        # p2c must see it, or paired sampling would overload the canary
        # invisibly
        # the shadow rides the ORIGINAL request's trace as its own
        # marked sibling span: a stitched trace shows exactly which
        # query was shadow-sampled and what the canary did with it
        token = trace.activate_context(ctx) if ctx is not None else None
        t0 = time.perf_counter()
        try:
            with trace.span("router.shadow", replica=canary_replica.name,
                            shadow=True):
                status, data, _headers = self._client(
                    canary_replica).request(
                    "POST", "/queries.json", body,
                    trace.traced_headers(
                        {"Content-Type": "application/json"}),
                    timeout=timeout)
        except Exception as e:  # noqa: BLE001 — a failing canary IS the
            # evidence: counted as a paired error, never raised
            quality.STATE.add_paired(None, error=f"{type(e).__name__}: {e}")
            return
        finally:
            canary_replica.end_request()
            if token is not None:
                trace.deactivate(token)
        if not 200 <= status < 300:
            quality.STATE.add_paired(None,
                                     error=f"canary answered {status}")
            return
        quality.CANARY_SECONDS.labels(quality.LANE_CANARY).observe(
            time.perf_counter() - t0)
        try:
            diff = quality.compare_answers(json.loads(base_data or b"null"),
                                           json.loads(data or b"null"))
        except ValueError as e:
            quality.STATE.add_paired(None, error=f"unparseable answer: {e}")
            return
        quality.STATE.add_paired(diff)

    # -- operator surface ----------------------------------------------------
    def status(self) -> Dict[str, Any]:
        deadline = self.hedge.deadline()
        # once the operator gates /admin/fleet behind PIO_ADMIN_TOKEN,
        # the public status page must not hand out the byte-identical
        # snapshot (replica ports, instance ids, probe verdicts) one
        # route over — shrink it to the aggregate counts
        if os.environ.get("PIO_ADMIN_TOKEN"):
            fleet_view: Dict[str, Any] = {
                "size": self.fleet.size(),
                "ready": self.fleet.ready_count(),
            }
        else:
            fleet_view = self.fleet.snapshot()
        return {
            "status": "alive",
            "role": "router",
            "fleet": fleet_view,
            "hedge": {
                "deadlineMs": (None if deadline is None
                               else round(deadline * 1e3, 2)),
                "quantile": metrics.env_float("PIO_HEDGE_QUANTILE", 0.95),
                "hedges": int(_HEDGES.value),
                "reroutes": int(_REROUTES.value),
            },
        }

    def stop(self) -> None:
        super().stop()
        self._worker_pool.stop()
        with self._pools_lock:
            pools, self._pools = list(self._pools.values()), {}
        for pool in pools:
            pool.close()
