"""The Engine Server: deployed-engine query serving, default port 8000.

Behavior contract from the reference (core/.../workflow/CreateServer.scala):

  - boots from the latest COMPLETED EngineInstance for an engine
    (Console.deploy picks it, Console.scala:845-852), reloading models
    from the Models repo (createServerActorWithEngine:190)
  - ``POST /queries.json`` (:462): JSON query -> every algorithm's
    predict on its model -> Serving combines -> JSON response; per
    request stats (requestCount / avg serving time :552-559); optional
    feedback loop POSTs a ``predict`` event (+prId) back to the event
    server (:488-550)
  - ``GET /`` status page with engine info, params and request stats
    (:433-459)
  - ``GET /reload`` hot-swaps to the latest completed instance (:592)
  - ``POST /stop`` shuts the server down (:600)
  - bind retry x3 with 1s backoff (MasterActor, :340-350)

The reference's Akka Master/Server actor pair collapses into one
threaded HTTP server with a swappable Deployment reference. Concurrent
queries are micro-batched (MicroBatcher): handler threads queue
payloads, a worker drains the queue into ONE vectorized
``Deployment.query_batch`` dispatch — batches form exactly when the
device is the bottleneck, and a lone request pays no extra latency
(SURVEY.md §7.5).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import threading
import time
import urllib.request
import uuid
from typing import Any, List, Optional
from urllib.parse import urlparse

from predictionio_tpu.core.engine import Engine
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.obs import (dataobs, flight, health, journal, metrics,
                                  slo as slo_mod, trace)
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.resilience import chaos
from predictionio_tpu.resilience.admission import AdmissionController
from predictionio_tpu.resilience.policy import CLOSED as _BREAKER_CLOSED
from predictionio_tpu.resilience.policy import breaker_for
from predictionio_tpu.serving.http import HTTPServerBase, JSONRequestHandler
from predictionio_tpu.workflow.deploy import Deployment, prepare_deploy

log = logging.getLogger(__name__)

DEFAULT_PORT = 8000  # ref: CreateServer.scala:83
UTC = _dt.timezone.utc

#: the one serving-latency series (obs tentpole): the status page's
#: count/avg/p50/p99 and the /metrics histogram read the SAME child, so
#: a dashboard and the operator landing page can never disagree
_SERVING_SECONDS = metrics.histogram(
    "pio_serving_request_seconds",
    "End-to-end serve time per query (queue wait + dispatch), recorded "
    "inside the engine server",
    ("engine",),
)

#: stall detection over micro-batch dispatches: armed once enough
#: dispatches have built a trailing median, fires when one exceeds
#: PIO_STALL_FACTOR x that median (floor 1s x factor)
_DISPATCH_WATCHDOG = health.Watchdog("serving_dispatch")

#: streaming model patches (workflow/stream.py fold-in lane): applied /
#: stale-instance-rejected / unsupported-or-malformed
_MODEL_PATCHES = metrics.counter(
    "pio_model_patches_total",
    "Streaming model patches received by outcome (applied / stale / "
    "rejected)",
    ("result",),
)


def _http_inflight() -> float:
    """Requests currently inside this engine server (the shared HTTP
    layer's in-flight gauge) — the admission controller's concurrency
    signal. The label is derived from the handler's server_version the
    same way serving/http.py derives it, so a rename cannot silently
    point this at an untouched gauge child reading 0.0 forever."""
    family = metrics.REGISTRY.get("pio_http_requests_in_flight")
    if family is None:
        return 0.0
    label = _EngineRequestHandler.server_version.split("/", 1)[0]
    return family.labels(label).value


class ServingStats:
    """Request bookkeeping (ref: CreateServer.scala:552-559).

    Every record lands in the shared, engine-wide
    ``pio_serving_request_seconds{engine=...}`` histogram — the
    percentiles on the status page and ``GET /metrics`` report from
    that one source of truth. Counts/totals are additionally tracked
    per ServingStats (per server — fleet replicas need per-replica
    numbers), and a bounded window of raw per-request times is kept
    alongside for ``recent()`` (bench.py reads exact server-side
    samples; histogram buckets would quantize them)."""

    WINDOW = 8192

    def __init__(self, engine_id: str = "default"):
        import collections

        self._lock = threading.Lock()
        # the registry child is process-global per engine: every live
        # server for this engine (N threaded fleet replicas included)
        # records into the SAME series, so /metrics, the serving-latency
        # SLO and burn-driven shedding see ALL traffic — a regression
        # confined to one replica must still move the shared histogram.
        # Per-SERVER bookkeeping (status page counts, recent()) lives
        # locally: a new server starts its own counts from zero while
        # the registry series stays cumulative, Prometheus-style.
        self._hist = _SERVING_SECONDS.labels(engine_id)
        self._count = 0
        self._sum = 0.0
        self.last_serving_sec = 0.0
        self.start_time = _dt.datetime.now(tz=UTC)
        self._window: collections.deque = collections.deque(maxlen=self.WINDOW)

    @property
    def request_count(self) -> int:
        return self._count

    @property
    def total_serving_sec(self) -> float:
        return self._sum

    def record(self, seconds: float) -> None:
        # the serving request's trace id rides along as an OpenMetrics
        # exemplar on whichever latency bucket this query landed in
        trace_id = trace.current_trace_id()
        self._hist.observe(
            seconds,
            exemplar={"trace_id": trace_id} if trace_id else None)
        with self._lock:
            self._count += 1
            self._sum += seconds
            self.last_serving_sec = seconds
            self._window.append(seconds)

    def recent(self, n: Optional[int] = None) -> List[float]:
        """The last ``n`` (default: all windowed) serving times."""
        with self._lock:
            out = list(self._window)
        return out if n is None else out[-n:]

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self._count, self._sum
        return {
            "startTime": self.start_time.isoformat(),
            "requestCount": count,
            "avgServingSec": total / count if count else 0.0,
            "lastServingSec": self.last_serving_sec,
            # bucket-interpolated, the PromQL histogram_quantile
            # estimate over the engine-wide shared series (all
            # in-process servers for this engine, /metrics' view)
            "p50ServingSec": self._hist.quantile(0.50),
            "p99ServingSec": self._hist.quantile(0.99),
        }


class _Pending:
    __slots__ = ("payload", "event", "result", "error", "abandoned",
                 "t_submit", "trace_ctx")

    def __init__(self, payload):
        self.payload = payload
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.abandoned = False  # submitter timed out; skip device work
        self.t_submit = time.perf_counter()
        # the submitting handler thread's trace context: contextvars do
        # not cross the hop to the batcher worker, so it rides along and
        # is re-activated around a lone dispatch; a >1 batch dispatches
        # under its own ``serve.batch`` span carrying every member's
        # trace id (the ROADMAP obs follow-up)
        self.trace_ctx = trace.current_context()


class MicroBatcher:
    """Coalesce concurrent queries into one vectorized dispatch.

    Handler threads submit; one worker drains whatever is queued (up to
    ``max_batch``) and answers the whole batch through
    ``Deployment.query_batch`` — one device dispatch amortized over all
    waiters. No artificial wait window: a lone request is served
    immediately, and batches form naturally while the device is busy
    with the previous one (the reference serves queries one-per-request
    inside detached futures, CreateServer.scala:472 — this is the TPU
    dispatch-amortizing upgrade on that contract).

    A failing batch falls back to per-item evaluation so one malformed
    query 400s alone instead of poisoning its batchmates.

    Health wiring: every dispatch runs under the ``serving_dispatch``
    watchdog (a dispatch exceeding PIO_STALL_FACTOR x the trailing
    median fires ``pio_watchdog_stall_total`` + a ``pio.stall`` log),
    and the queue's depth is a registered readiness probe — a backlog
    of ``PIO_QUEUE_DEPTH_LIMIT`` (default 8 x max_batch) turns
    ``/readyz`` DEGRADED before callers start timing out.
    """

    def __init__(self, run_batch, run_one, max_batch: int = 64,
                 chaos_tag: Optional[str] = None):
        import queue as _queue
        import weakref

        self._run_batch = run_batch
        self._run_one = run_one
        self._max_batch = max_batch
        # names THIS batcher at the chaos seam: a fleet tags each
        # replica's batcher by replica name, so `batcher@r1:hang:5s`
        # hangs one replica while its peers keep answering
        self._chaos_tag = chaos_tag
        self._queue: "_queue.Queue[_Pending]" = _queue.Queue()
        # readiness probe over the queue depth (weakref: a dropped
        # batcher must not be kept alive by the health registry)
        queue_ref = weakref.ref(self._queue)
        depth_limit = metrics.env_int("PIO_QUEUE_DEPTH_LIMIT",
                                      max_batch * 8)
        self._queue_probe = health.queue_depth_probe(
            lambda: (q.qsize() if (q := queue_ref()) is not None
                     else None),
            max(1, depth_limit))
        # namespaced per replica on the shared process registry:
        # threaded fleet replicas each get their own probe (an
        # un-namespaced name is last-registration-wins, which would
        # hide every other replica's queue backlog from readiness)
        self._probe_name = ("serving_queue" if chaos_tag is None
                            else f"serving_queue:{chaos_tag}")
        health.REGISTRY.register(self._probe_name, self._queue_probe)
        # batch-size histogram: the observable proof that amortization
        # actually happens under load (VERDICT r3 item 6) — exposed in
        # the server's status JSON
        self._hist_lock = threading.Lock()
        self._hist: dict = {}
        # rolling (queue_wait, dispatch) seconds per answered request:
        # separates time spent WAITING for the worker from time inside
        # the model dispatch — the split a concurrency sweep needs to
        # tell queueing from device work (VERDICT r4 item 5)
        from collections import deque

        self._splits = deque(maxlen=50_000)
        # abandoned submitters (timed out waiting) are counted here and
        # EXCLUDED from the splits: their queue wait is the caller's
        # timeout and their dispatch time covers work the worker skipped
        # — folding them in would skew the bench's srv_queue /
        # srv_dispatch percentiles with numbers no served request saw
        self._abandoned = 0
        self._stop = False
        # orders submit()'s stop-check+enqueue against stop()'s flag+wake,
        # so nothing can be enqueued after the worker's shutdown drain
        self._stop_lock = threading.Lock()
        # named so the continuous profiler (obs/contprof.py) labels the
        # batch loop's samples with the "batcher" role
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="pio-batcher")
        self._worker.start()

    def submit(self, payload, timeout: float = 30.0):
        pending = _Pending(payload)
        with self._stop_lock:
            if self._stop:
                raise RuntimeError("serving batcher is stopped")
            self._queue.put(pending)
        if not pending.event.wait(timeout):
            # leave a tombstone so the worker spends no device time
            # answering a waiter that already gave up
            pending.abandoned = True
            raise TimeoutError("query timed out in the serving batcher")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def stop(self) -> None:
        with self._stop_lock:
            if self._stop:
                return
            self._stop = True
            self._queue.put(_Pending(None))  # wake the worker
        # remove only OUR probe: if a newer in-process batcher already
        # re-registered the name, its live probe must survive this stop
        health.REGISTRY.unregister(self._probe_name, self._queue_probe)
        # the worker's shutdown drain answers everything still queued, so
        # no submitter blocks out its full timeout on a dying server
        self._worker.join(timeout=60)

    def _loop(self) -> None:
        import queue as _queue

        leftover: List[_Pending] = []
        while True:
            first = self._queue.get()
            if self._stop:
                leftover.append(first)
                break
            batch = [first]
            try:
                while len(batch) < self._max_batch:
                    try:
                        batch.append(self._queue.get_nowait())
                    except _queue.Empty:
                        break
                with _DISPATCH_WATCHDOG.watch():
                    # chaos seam: injected latency/hangs land INSIDE the
                    # dispatch watchdog's watch window (a chaos hang is
                    # what tier-1 uses to prove the watchdog still
                    # fires), injected errors fail this batch's waiters
                    chaos.inject("batcher", tag=self._chaos_tag)
                    self._answer(batch)
            except Exception as e:  # noqa: BLE001 — a dead worker starves
                # every future submitter silently; log, fail THIS batch's
                # waiters, keep the loop alive
                log.exception("batch worker iteration failed")
                for p in batch:
                    if not p.event.is_set():
                        p.error = e
                        p.event.set()
        # shutdown drain: only the worker consumes the queue, so nothing
        # races it; the stop-lock guarantees no later enqueues. A drain
        # failure must be logged too — stranded submitters block out
        # their full timeout with no symptom otherwise.
        try:
            while True:
                try:
                    leftover.append(self._queue.get_nowait())
                except _queue.Empty:
                    break
            for p in leftover:
                if p.payload is not None and not p.event.is_set():
                    p.error = RuntimeError("serving batcher stopped")
                    p.event.set()
        except Exception:  # noqa: BLE001 — see above
            log.exception("batcher shutdown drain failed")

    def histogram(self) -> dict:
        """Dispatch-size distribution since start: {"1": lone requests,
        "2": two-query dispatches, ...}. Sizes > 1 are queries that
        shared one device dispatch."""
        with self._hist_lock:
            hist = {str(k): v for k, v in sorted(self._hist.items())}
            abandoned = self._abandoned
        return {
            "maxBatch": self._max_batch,
            "dispatches": sum(hist.values()),
            "batchSizeHistogram": hist,
            # timed-out submitters, kept OUT of the latency splits
            "abandonedRequests": abandoned,
        }

    def _answer(self, batch) -> None:
        live = [p for p in batch if not p.abandoned]
        if len(live) < len(batch):
            with self._hist_lock:
                self._abandoned += len(batch) - len(live)
        batch = live
        if not batch:
            return
        with self._hist_lock:
            self._hist[len(batch)] = self._hist.get(len(batch), 0) + 1
        t_start = time.perf_counter()
        if len(batch) == 1:
            p = batch[0]
            token = (trace.activate_context(p.trace_ctx)
                     if p.trace_ctx is not None else None)
            try:
                with trace.span("serve.dispatch", batch_size=1):
                    p.result = self._run_one(p.payload)
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                p.error = e
            finally:
                if token is not None:
                    trace.deactivate(token)
            self._record_splits(batch, t_start)
            p.event.set()
            return
        # the multi-query dispatch gets its OWN span: one record, under
        # a batch-minted trace id, carrying every member's trace id —
        # so a member's span chain joins its batchmates' (previously a
        # >1 batch ran untraced), and each member's flight record
        # learns the dispatch size it shared
        members = [p.trace_ctx.trace_id for p in batch
                   if p.trace_ctx is not None]
        for tid in members:
            flight.note_field("batch_size", len(batch), trace_id=tid)
        try:
            batch_token = trace.activate(trace.new_trace_id())
            try:
                with trace.span("serve.batch", batch_size=len(batch),
                                members=members):
                    results = self._run_batch([p.payload for p in batch])
            finally:
                trace.deactivate(batch_token)
            for p, r in zip(batch, results):
                p.result = r
        except BaseException as e:
            # isolate the poison query: each waiter gets its own verdict
            # (and the fallback costs a serial re-dispatch — worth a log)
            log.warning("batch dispatch of %d queries failed (%s: %s); "
                        "re-running individually to isolate the poison "
                        "query", len(batch), type(e).__name__, e)
            for p in batch:
                token = (trace.activate_context(p.trace_ctx)
                         if p.trace_ctx is not None else None)
                try:
                    with trace.span("serve.dispatch", batch_size=1,
                                    fallback=True):
                        p.result = self._run_one(p.payload)
                except BaseException as e:  # noqa: BLE001
                    p.error = e
                finally:
                    if token is not None:
                        trace.deactivate(token)
        self._record_splits(batch, t_start)
        for p in batch:
            p.event.set()

    def _record_splits(self, batch, t_start: float) -> None:
        t_done = time.perf_counter()
        with self._hist_lock:
            for p in batch:
                if p.abandoned:
                    # the submitter's timeout raced the dispatch (the
                    # entry filter in _answer only catches tombstones
                    # laid BEFORE the drain): count it, don't let its
                    # give-up-sized wait skew the percentiles
                    self._abandoned += 1
                    continue
                self._splits.append((t_start - p.t_submit, t_done - t_start))
        # the same split, attributed to each request's flight record
        # (outside the histogram lock: flight takes its own)
        for p in batch:
            if p.abandoned or p.trace_ctx is None:
                continue
            tid = p.trace_ctx.trace_id
            flight.note_stage("queue", t_start - p.t_submit, trace_id=tid)
            flight.note_stage("dispatch", t_done - t_start, trace_id=tid)

    def recent_splits(self, n: int):
        """Last ``n`` answered requests' (queue_wait_sec, dispatch_sec)
        pairs, oldest first."""
        with self._hist_lock:
            items = list(self._splits)
        return items[-n:]

    def queue_depth(self) -> int:
        """Requests waiting for the worker right now (the admission
        controller's primary shed signal)."""
        return self._queue.qsize()


class EngineServer(HTTPServerBase):
    """One deployed engine behind HTTP (ref: CreateServer.scala:100,106)."""

    def __init__(
        self,
        engine: Engine,
        engine_id: str,
        engine_version: str = "0",
        engine_variant: str = "default",
        host: str = "0.0.0.0",
        port: int = DEFAULT_PORT,
        ctx: Optional[MeshContext] = None,
        storage: Optional[Storage] = None,
        feedback_url: Optional[str] = None,
        feedback_access_key: Optional[str] = None,
        log_url: Optional[str] = None,
        bind_retries: int = 3,
        micro_batch: bool = True,
        max_batch: int = 64,
        slo_conf: Optional[dict] = None,
        chaos_tag: Optional[str] = None,
    ):
        self.engine = engine
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self.ctx = ctx or MeshContext()
        self.storage = storage or get_storage()
        self.feedback_url = feedback_url
        self.feedback_access_key = feedback_access_key
        self.log_url = log_url
        self.stats = ServingStats(engine_id)
        self._deployment_lock = threading.Lock()
        # degraded-mode circuit: fed by readiness storage probes and
        # reloads. While not closed, queries keep answering from the
        # last-loaded model with an X-PIO-Degraded stamp and /readyz
        # reports DEGRADED (not FAILED) — losing storage must not read
        # as losing the server.
        self._storage_breaker = breaker_for(f"storage:{engine_id}",
                                            failure_threshold=2)
        self.deployment: Deployment = self._load_latest()
        # chaos identity: a fleet replica is tagged by its supervisor
        # (subprocess replicas via PIO_CHAOS_TAG) so operators can fault
        # ONE replica of a fleet; a standalone server stays untagged
        self.chaos_tag = chaos_tag or os.environ.get("PIO_CHAOS_TAG") or None
        self._batcher: Optional[MicroBatcher] = (
            MicroBatcher(self._query_batch_now, self._query_now,
                         max_batch=max_batch, chaos_tag=self.chaos_tag)
            if micro_batch else None
        )

        # admission control (resilience tentpole): shed with 429 +
        # Retry-After from queue depth / in-flight / SLO burn signals
        # BEFORE queueing collapse. Thresholds: env defaults, then the
        # PIO_SLO_FILE "shed" block, then the engine.json "slo.shed"
        # block (most specific wins).
        file_conf = slo_mod.configure_from_env() or {}
        if slo_conf:
            # layer the variant block OVER the file's objectives — a
            # variant that only overrides availability must not silently
            # drop the file's latency threshold back to env defaults
            slo_mod.configure({**file_conf, **slo_conf})
        self.admission = AdmissionController(
            "engine",
            queue_depth=lambda: (self._batcher.queue_depth()
                                 if self._batcher is not None else None),
            inflight=_http_inflight,
            max_queue_depth=metrics.env_int("PIO_SHED_QUEUE_DEPTH",
                                            max_batch * 4),
        )
        for conf in (file_conf, slo_conf or {}):
            shed = conf.get("shed") if isinstance(conf, dict) else None
            if shed:
                self.admission.configure(shed)

        # daily version check, no-op unless PIO_UPDATE_URL is configured
        # (ref: UpgradeActor, CreateServer.scala:163-170,246)
        from predictionio_tpu.tools.upgrade import start_upgrade_daemon

        start_upgrade_daemon("engine-server")

        # bind retry x3 with 1s backoff (ref: CreateServer.scala:340-350)
        super().__init__(host, port, _EngineRequestHandler, bind_retries=bind_retries)

    # -- deployment management ----------------------------------------------
    def _resolve_instance(self, instance_id: Optional[str] = None):
        """The COMPLETED instance a (re)load targets: a SPECIFIC one
        when ``instance_id`` names it (the canary rollback lane), else
        the latest. Resolution only — the OOM preflight must see the
        target id before anything is unpickled or device-put."""
        if instance_id:
            instance = self.storage.engine_instances().get(instance_id)
            if instance is None or instance.status != "COMPLETED":
                raise RuntimeError(
                    f"engine instance {instance_id} not found or not "
                    "COMPLETED")
        else:
            instance = self.storage.engine_instances().get_latest_completed(
                self.engine_id, self.engine_version, self.engine_variant
            )
        if instance is None:
            raise RuntimeError(
                f"No valid engine instance found for engine {self.engine_id} "
                f"{self.engine_version} {self.engine_variant}"
            )
        return instance

    def _load_latest(self, instance_id: Optional[str] = None) -> Deployment:
        """Build a warm deployment of the latest COMPLETED instance —
        or of a SPECIFIC completed instance when ``instance_id`` names
        one (the canary rollback lane: the fleet swaps its canary
        replica back onto the baseline instance, not onto "latest",
        which IS the candidate being rolled back)."""
        instance = self._resolve_instance(instance_id)
        deployment = prepare_deploy(self.engine, instance, self.ctx, self.storage)
        self._warmup(deployment)
        return deployment

    def _warmup(self, deployment: Deployment) -> None:
        """Pre-compile each algorithm's serve buckets BEFORE the
        deployment goes live, so the first query after deploy/reload
        pays no XLA compile (SURVEY.md §7.5 hard part #2). Warm-up
        failures never block a deploy — worst case is reference
        behavior (first query compiles)."""
        t0 = time.perf_counter()
        for algo, model in zip(deployment.algorithms, deployment.models):
            try:
                algo.warmup(model, self.ctx)
            except Exception:  # noqa: BLE001
                log.exception("warmup failed for %s", type(algo).__name__)
        log.info("serve warm-up done in %.2fs", time.perf_counter() - t0)

    def reload(self, instance_id: Optional[str] = None,
               force: bool = False) -> str:
        """Hot-swap to the latest completed instance (ref: /reload :592)
        — or to the specific completed instance ``instance_id`` names
        (``GET /reload?instance=<id>``, the canary rollback lane).
        The swap happens only after the new deployment is warm — live
        traffic never waits on the new model's compiles. A reload that
        fails on storage feeds the degraded-mode circuit; one that
        succeeds closes it (recovery path).

        OOM preflight (obs/memacct.py): the target instance is priced
        from its stored blob BEFORE anything loads; an estimate beyond
        current headroom raises :class:`memacct.PreflightRefused`
        (route: 507 + the JSON reason) unless ``force`` — load+warm
        precedes the swap, so during the window BOTH deployments are
        resident and the un-subtracted headroom check is exactly
        right. The successful swap releases the OLD deployment's
        ledger footprints, so gauges drop with the swap, not the GC."""
        from predictionio_tpu.data.storage import StorageError
        from predictionio_tpu.obs import memacct

        try:
            instance = self._resolve_instance(instance_id)
        except (StorageError, ConnectionError):
            self._storage_breaker.record_failure()
            raise
        # may raise PreflightRefused — deliberately OUTSIDE the breaker
        # accounting: a refused deploy is a capacity verdict, not a
        # storage failure, and must not push the server degraded
        try:
            memacct.preflight_check(instance.id, self.storage,
                                    force=force)
        except memacct.PreflightRefused as e:
            journal.emit("preflight_refused", instance=instance.id,
                         detail=str(e)[:200])
            raise
        try:
            deployment = prepare_deploy(self.engine, instance, self.ctx,
                                        self.storage)
        except (StorageError, ConnectionError):
            self._storage_breaker.record_failure()
            raise
        self._warmup(deployment)
        self._storage_breaker.record_success()
        with self._deployment_lock:
            old, self.deployment = self.deployment, deployment
        journal.emit("reload", instance=deployment.instance.id,
                     prev=old.instance.id, requested=instance_id,
                     forced=force or None)
        # retire the swapped-out instance's residency (weakref sweep is
        # the backstop; the deliberate seam keeps gauges honest NOW)
        for model in old.models:
            memacct.release_model(model)
        return deployment.instance.id

    # -- streaming model patches (workflow/stream.py) -----------------------
    class StalePatch(RuntimeError):
        """The patch targets an instance this server no longer serves."""

    def apply_patch(self, payload: dict) -> dict:
        """Apply a streaming fold-in patch to the live deployment —
        the lightweight freshness lane between full reloads. Applied
        under the deployment lock (between queries); each algorithm's
        ``apply_patch`` swaps rows copy-on-write, so in-flight queries
        see old-or-new tables, never torn rows.

        Raises :class:`StalePatch` when ``instanceId`` names another
        instance (the caller should resync), ValueError on malformed or
        unsupported blocks. Returns {"applied": n_blocks}."""
        instance_id = payload.get("instanceId")
        blocks = payload.get("algorithms")
        if not isinstance(blocks, list) or not blocks:
            _MODEL_PATCHES.labels("rejected").inc()
            raise ValueError("patch needs a non-empty 'algorithms' list")
        with self._deployment_lock:
            deployment = self.deployment
            if instance_id and instance_id != deployment.instance.id:
                _MODEL_PATCHES.labels("stale").inc()
                journal.emit("patch", outcome="stale",
                             instance=instance_id,
                             deployed=deployment.instance.id)
                raise self.StalePatch(
                    f"patch targets instance {instance_id} but "
                    f"{deployment.instance.id} is deployed")
            applied = 0
            for block in blocks:
                if not isinstance(block, dict):
                    _MODEL_PATCHES.labels("rejected").inc()
                    raise ValueError("each algorithm block must be an object")
                idx = block.get("index", 0)
                if not isinstance(idx, int) or not (
                        0 <= idx < len(deployment.algorithms)):
                    _MODEL_PATCHES.labels("rejected").inc()
                    raise ValueError(f"algorithm index {idx!r} out of range")
                algo = deployment.algorithms[idx]
                model = deployment.models[idx]
                try:
                    ok = algo.apply_patch(model, block)
                except ValueError:
                    _MODEL_PATCHES.labels("rejected").inc()
                    raise
                if not ok:
                    _MODEL_PATCHES.labels("rejected").inc()
                    raise ValueError(
                        f"algorithm {type(algo).__name__} does not "
                        "support model patches — use /reload")
                applied += 1
        _MODEL_PATCHES.labels("applied").inc()
        journal.emit("patch", outcome="ok", applied=applied,
                     instance=instance_id)
        return {"applied": applied}

    # -- degraded mode ------------------------------------------------------
    def degraded_reason(self) -> Optional[str]:
        """Non-None while serving degraded: the storage circuit is not
        closed, so the last-loaded model answers queries but reloads
        and feedback durability cannot be trusted. The string is the
        ``X-PIO-Degraded`` response header."""
        if self._storage_breaker.state == _BREAKER_CLOSED:
            return None
        with self._deployment_lock:
            instance_id = self.deployment.instance.id
        return ("storage unavailable; serving last-loaded instance "
                f"{instance_id}")

    def storage_readyz_probe(self) -> health.ProbeResult:
        """The engine server's ``/readyz`` storage probe (the shared
        handler prefers this hook over the default
        ``health.storage_probe``): storage loss while a model is loaded
        is DEGRADED, not FAILED — the server can still do its one job
        (answer queries); it cannot reload or verify freshness. The
        probe feeds the degraded-mode circuit: consecutive failures
        open it (after which probes fail FAST instead of stalling every
        readiness check on a dead backend), and the half-open probe's
        eventual success closes it — recovery needs no restart."""
        breaker = self._storage_breaker
        if not breaker.allow():
            return health.degraded(
                f"storage circuit open (next probe in "
                f"{breaker.retry_after():.0f}s); {self.degraded_reason()}")
        try:
            result = health.storage_probe(self.storage)
        except Exception as e:  # noqa: BLE001 — a raising probe IS the finding
            result = health.failed(f"{type(e).__name__}: {e}")
        if result.status == health.FAILED:
            breaker.record_failure()
            return health.degraded(
                f"{result.reason}; serving degraded from the last-loaded "
                "model")
        breaker.record_success()
        return result

    # -- query path ---------------------------------------------------------
    def _query_now(self, payload: Any) -> Any:
        with self._deployment_lock:
            deployment = self.deployment
        return deployment.query(payload)

    def _query_batch_now(self, payloads) -> Any:
        with self._deployment_lock:
            deployment = self.deployment
        return deployment.query_batch(payloads)

    def query(self, payload: Any) -> Any:
        t0 = time.perf_counter()
        with trace.span("serve.query", engine=self.engine_id):
            if self._batcher is not None:
                result = self._batcher.submit(payload)
            else:
                t_disp = time.perf_counter()
                result = self._query_now(payload)
                flight.note_stage("dispatch", time.perf_counter() - t_disp)
        elapsed = time.perf_counter() - t0
        self.stats.record(elapsed)
        self._note_query_coverage(payload)
        if self.feedback_url and self.feedback_access_key:
            # prId lets follow-up events join back to this prediction
            # (ref: CreateServer feedback loop assigns prId :488-550)
            pr_id = uuid.uuid4().hex
            if isinstance(result, dict):
                result = {**result, "prId": pr_id}
            with self._deployment_lock:
                instance_id = self.deployment.instance.id
            threading.Thread(
                target=self._send_feedback,
                args=(payload, result, pr_id, instance_id),
                daemon=True,
            ).start()
        return result

    def _note_query_coverage(self, payload: Any) -> None:
        """Unknown-entity accounting at the query-decode seam
        (obs/dataobs.py): how many user/item references this query
        named, and how many the SERVED model has never seen — the
        "is the model stale for the traffic we actually get" signal.
        Best-effort: accounting must never break serving."""
        try:
            if not isinstance(payload, dict) or not dataobs.DATAOBS.enabled():
                return
            users = [payload["user"]] if payload.get("user") is not None \
                else []
            items = list(payload.get("items") or [])
            if payload.get("item") is not None:
                items.append(payload["item"])
            if not users and not items:
                return
            with self._deployment_lock:
                models = list(self.deployment.models)
            user_maps = [m.user_ids for m in models
                         if getattr(m, "user_ids", None) is not None]
            item_maps = [m.item_ids for m in models
                         if getattr(m, "item_ids", None) is not None]
            refs = unknown = 0
            if users and user_maps:
                refs += len(users)
                unknown += sum(
                    1 for u in users
                    if not any(str(u) in ids for ids in user_maps))
            if items and item_maps:
                refs += len(items)
                unknown += sum(
                    1 for i in items
                    if not any(str(i) in ids for ids in item_maps))
            if refs:
                dataobs.DATAOBS.note_query(refs, unknown)
        except Exception:  # noqa: BLE001
            log.debug("query coverage accounting failed", exc_info=True)

    @staticmethod
    def _post_json(url: str, payload: Any, what: str) -> None:
        """One best-effort JSON POST (shared by the feedback loop and
        remote error log; failures are logged, never raised)."""
        try:
            req = urllib.request.Request(
                url,
                data=json.dumps(payload).encode(),
                # the feedback loop posts to the EVENT SERVER — a fleet
                # member: the trace context (when one is active on this
                # thread) lets the collector stitch prediction ->
                # feedback into one tree (JT17)
                headers=trace.traced_headers(
                    {"Content-Type": "application/json"}),
                method="POST",
            )
            urllib.request.urlopen(req, timeout=5)
        except Exception as e:  # noqa: BLE001 — best-effort
            log.warning("%s POST failed: %s", what, e)

    def remote_log(self, message: str, level: str = "ERROR") -> None:
        """POST an error line to the configured --log-url (ref:
        CreateServer.scala:413-424 remoteLog — fire-and-forget, a dead
        log endpoint must never affect serving)."""
        if not self.log_url:
            return
        payload = {
            "level": level,
            "message": message,
            "engineId": self.engine_id,
            "engineVariant": self.engine_variant,
        }
        threading.Thread(
            target=self._post_json, args=(self.log_url, payload, "remote log"),
            daemon=True,
        ).start()

    def _send_feedback(self, query: Any, prediction: Any, pr_id: str, instance_id: str) -> None:
        """Async predict-event feedback loop (ref: CreateServer.scala:488-550)."""
        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": instance_id,
            "prId": pr_id,
            "properties": {"query": query, "prediction": prediction},
        }
        self._post_json(
            f"{self.feedback_url}/events.json?accessKey={self.feedback_access_key}",
            event, "feedback loop",
        )

    def stop(self) -> None:
        if self._batcher is not None:
            self._batcher.stop()
        # fleet replica stop: retire this server's residency from the
        # memory ledger — a stopped replica's models must not keep
        # exporting pio_model_device_bytes until the GC happens by
        from predictionio_tpu.obs import memacct

        with self._deployment_lock:
            models = list(self.deployment.models)
        for model in models:
            memacct.release_model(model)
        super().stop()

    def status(self) -> dict:
        """ref: status landing page content (CreateServer.scala:433-459)."""
        with self._deployment_lock:
            instance = self.deployment.instance
            models = list(self.deployment.models)
        # retrieval surface: stats of each model's BUILT ANN index
        # (built at warm-up; None for non-retrieval algorithms — a
        # status read must never trigger a build)
        retrieval = [
            m.retrieval_stats() if hasattr(m, "retrieval_stats") else None
            for m in models
        ]
        return {
            "status": "alive",
            "engineId": self.engine_id,
            "engineVersion": self.engine_version,
            "engineVariant": self.engine_variant,
            "engineInstanceId": instance.id,
            "engineFactory": instance.engine_factory,
            "trainedAt": instance.end_time.isoformat(),
            "algorithms": json.loads(instance.algorithms_params or "[]"),
            "stats": self.stats.snapshot(),
            # micro-batching evidence: dispatch-size distribution
            # (None when micro-batching is disabled)
            "batcher": (self._batcher.histogram()
                        if self._batcher is not None else None),
            # resilience surface: shed limits/counters + degraded mode
            "admission": self.admission.snapshot(),
            "degraded": self.degraded_reason(),
            "storageCircuit": self._storage_breaker.snapshot(),
            "retrieval": retrieval,
        }


_STATUS_HTML = """<!DOCTYPE html>
<html><head><title>{engine_id} — PredictionIO-TPU engine</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem auto; max-width: 60rem; }}
 h1 {{ font-size: 1.4rem; }} table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #ccc; padding: .3rem .6rem; text-align: left; }}
 code {{ background: #f4f4f4; padding: 0 .2rem; }}
</style></head><body>
<h1>Engine <code>{engine_id}</code> is deployed</h1>
<table>
<tr><th>Engine variant</th><td>{engine_variant}</td></tr>
<tr><th>Engine instance</th><td>{engine_instance_id}</td></tr>
<tr><th>Engine factory</th><td>{engine_factory}</td></tr>
<tr><th>Trained at</th><td>{trained_at}</td></tr>
<tr><th>Started</th><td>{start_time}</td></tr>
<tr><th>Requests served</th><td>{request_count}</td></tr>
<tr><th>Average serving time</th><td>{avg_ms:.2f} ms</td></tr>
<tr><th>Last serving time</th><td>{last_ms:.2f} ms</td></tr>
</table>
<h2>Algorithms</h2><pre>{algorithms}</pre>
<p>POST queries to <code>/queries.json</code>; JSON status at
<code>/</code> (Accept: application/json); <code>/reload</code> swaps in
the latest trained instance.</p>
</body></html>
"""


class _EngineRequestHandler(JSONRequestHandler):
    server_version = "PIOEngineServer/0.1"

    def do_GET(self):
        path = urlparse(self.path).path
        if path == "/":
            status = self.server_ref.status()
            # browsers get the operator landing page (ref:
            # CreateServer.scala:433-459 + the twirl index template);
            # programmatic clients keep the JSON contract
            if "text/html" in (self.headers.get("Accept") or ""):
                import html as _html

                stats = status["stats"]
                esc = lambda v: _html.escape(str(v))  # noqa: E731
                html = _STATUS_HTML.format(
                    engine_id=esc(status["engineId"]),
                    engine_variant=esc(status["engineVariant"]),
                    engine_instance_id=esc(status["engineInstanceId"]),
                    engine_factory=esc(status["engineFactory"]),
                    trained_at=esc(status["trainedAt"]),
                    start_time=esc(stats["startTime"]),
                    request_count=stats["requestCount"],
                    avg_ms=stats["avgServingSec"] * 1e3,
                    last_ms=stats["lastServingSec"] * 1e3,
                    algorithms=esc(json.dumps(status["algorithms"], indent=2)),
                )
                self._send(200, html, content_type="text/html; charset=UTF-8")
            else:
                self._send(200, status)
        elif path == "/reload":
            from urllib.parse import parse_qs

            from predictionio_tpu.obs import memacct

            params = parse_qs(urlparse(self.path).query)
            target = (params.get("instance") or [None])[0]
            force = (params.get("force") or ["0"])[0].lower() in (
                "1", "true")
            try:
                instance_id = self.server_ref.reload(target, force=force)
                self._send(200, {"message": "reloaded", "engineInstanceId": instance_id})
            except memacct.PreflightRefused as e:
                # 507 Insufficient Storage: the candidate would exceed
                # device-memory headroom — refused BEFORE any load, the
                # serving model untouched; ?force=1 (or the fleet
                # admin's {"force": true}) overrides
                self._send(507, {"message": str(e),
                                 "preflight": e.decision})
            except RuntimeError as e:
                self.server_ref.remote_log(f"reload failed: {e}")
                self._send(404, {"message": str(e)})
            except Exception as e:  # noqa: BLE001 — a dead backend must
                # answer 503, not crash the keep-alive connection; the
                # failure already fed the degraded-mode circuit
                log.exception("reload failed")
                self.server_ref.remote_log(
                    f"reload failed: {type(e).__name__}: {e}")
                self._send(503, {"message": f"reload failed: {e}"})
        else:
            self._send(404, {"message": "Not Found"})

    def do_POST(self):
        path = urlparse(self.path).path
        if path == "/queries.json":
            # admission control FIRST — before the body parse, before
            # any queue time: an overloaded server's cheapest work is
            # saying no (429 + Retry-After), and the shed must be
            # reconstructable (counter + flight record)
            decision = self.server_ref.admission.check()
            if decision is not None:
                flight.note_field("shed", decision.reason)
                self._send(
                    429,
                    {"message": "overloaded — retry after the advised "
                                "delay", "reason": decision.reason,
                     "detail": decision.detail,
                     "retryAfterSec": decision.retry_after},
                    extra_headers={"Retry-After": str(decision.retry_after)})
                return
            try:
                payload = self._read_json()
            except json.JSONDecodeError as e:
                self._send(400, {"message": f"invalid JSON: {e}"})
                return
            # opt-in replay capture (PIO_FLIGHT_PAYLOADS): the byte cap
            # reuses the Content-Length the read already knew
            flight.record_payload(
                "/queries.json", payload,
                nbytes=int(self.headers.get("Content-Length") or 0))
            try:
                result = self.server_ref.query(payload)
            except (KeyError, TypeError, ValueError) as e:
                # malformed query for this engine (ref: 400 on bad query JSON)
                self._send(400, {"message": f"bad query: {e}"})
                return
            except Exception as e:
                log.exception("query failed")
                # the answered-500 path never raises through the
                # instrumented wrapper, so name the error here — the
                # flight record must carry WHAT failed, not just "500"
                flight.note_field("error", f"{type(e).__name__}: {e}")
                self.server_ref.remote_log(
                    f"query failed: {type(e).__name__}: {e}"
                )
                self._send(500, {"message": str(e)})
                return
            degraded = self.server_ref.degraded_reason()
            self._send(200, result,
                       extra_headers=({"X-PIO-Degraded": degraded}
                                      if degraded else None))
        elif path == "/model/patch":
            # same bearer gate as /admin/*: a patch MUTATES the served
            # model — an open route would let anyone rewrite factors
            from predictionio_tpu.serving.http import _admin_authorized

            if not _admin_authorized(self):
                self._send(401, {"message": "missing or invalid bearer "
                                            "token (PIO_ADMIN_TOKEN)"},
                           extra_headers={"WWW-Authenticate": "Bearer"})
                return
            try:
                payload = self._read_json()
            except json.JSONDecodeError as e:
                self._send(400, {"message": f"invalid JSON: {e}"})
                return
            try:
                result = self.server_ref.apply_patch(payload)
            except EngineServer.StalePatch as e:
                self._send(409, {"message": str(e)})
                return
            except (ValueError, TypeError, KeyError) as e:
                self._send(400, {"message": f"bad patch: {e}"})
                return
            except Exception as e:  # noqa: BLE001 — a failing patch must
                # answer 500, never crash the keep-alive connection
                log.exception("model patch failed")
                self._send(500, {"message": str(e)})
                return
            self._send(200, {"message": "patched", **result})
        elif path == "/stop":
            self._send(200, {"message": "stopping"})
            self.server_ref.stop()
        else:
            self._send(404, {"message": "Not Found"})


def deploy(
    engine: Engine,
    engine_id: str,
    engine_version: str = "0",
    engine_variant: str = "default",
    **kwargs,
) -> EngineServer:
    """Convenience: build + start a server for the latest completed
    instance (the `pio deploy` path, Console.scala:830)."""
    return EngineServer(
        engine, engine_id, engine_version=engine_version,
        engine_variant=engine_variant, **kwargs
    ).start()
