"""Metric family + MetricEvaluator + FastEval memoization
(ref specs: MetricTest.scala, MetricEvaluatorTest.scala,
FastEvalEngineTest.scala, EvaluationWorkflowTest.scala)."""

import json
import math

import pytest

from predictionio_tpu.core import Engine, EngineParams
from predictionio_tpu.core.evaluation import (
    AverageMetric,
    EngineParamsGenerator,
    Evaluation,
    FunctionMetric,
    MetricEvaluator,
    OptionAverageMetric,
    StdevMetric,
    SumMetric,
)
from predictionio_tpu.core.fast_eval import FastEvalEngineWorkflow
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.workflow.evaluate import run_evaluation

from tests.sample_engine import (
    Algo0,
    DataSource0,
    IdParams,
    Preparator0,
    Serving0,
)

ctx = MeshContext()


def make_eval_data(scores):
    """One fold whose qpa triples carry the given 'actual' scores."""
    return [(None, [(i, i, s) for i, s in enumerate(scores)])]


class ActualMetric(AverageMetric):
    def calculate_qpa(self, q, p, a):
        return float(a)


class OptionalMetric(OptionAverageMetric):
    def calculate_qpa(self, q, p, a):
        return None if a is None else float(a)


class StdevOfActual(StdevMetric):
    def calculate_qpa(self, q, p, a):
        return float(a)


class SumOfActual(SumMetric):
    def calculate_qpa(self, q, p, a):
        return float(a)


def test_metric_family():
    data = make_eval_data([1.0, 2.0, 3.0, 4.0])
    assert ActualMetric().calculate(ctx, data) == 2.5
    assert SumOfActual().calculate(ctx, data) == 10.0
    assert StdevOfActual().calculate(ctx, data) == pytest.approx(math.sqrt(1.25))
    opt = OptionalMetric().calculate(ctx, make_eval_data([1.0, None, 3.0]))
    assert opt == 2.0
    # multi-fold union (ref: sc.union across folds)
    two_folds = make_eval_data([1.0, 2.0]) + make_eval_data([3.0, 4.0])
    assert ActualMetric().calculate(ctx, two_folds) == 2.5
    assert ActualMetric().calculate(ctx, []) != ActualMetric().calculate(ctx, [])  # nan


def make_engine():
    return Engine(
        data_source_classes={"ds": DataSource0},
        preparator_classes={"prep": Preparator0},
        algorithm_classes={"algo": Algo0},
        serving_classes={"serve": Serving0},
    )


def make_params(algo_id):
    return EngineParams(
        data_source_params=("ds", IdParams(id=1)),
        preparator_params=("prep", IdParams(id=2)),
        algorithm_params_list=[("algo", IdParams(id=algo_id))],
        serving_params=("serve", IdParams(id=0)),
    )


def test_metric_evaluator_ranks_and_saves_best(tmp_path):
    # metric = algo id carried through prediction tags: higher algo id wins
    metric = FunctionMetric(lambda q, p, a: float(p.algo_id), name="algo-id")
    evaluation = Evaluation(engine=make_engine(), metric=metric)
    candidates = [make_params(3), make_params(7), make_params(5)]
    best_json = tmp_path / "best.json"
    evaluator = MetricEvaluator(best_json_path=str(best_json))
    result = evaluator.evaluate(ctx, evaluation, candidates)
    assert result.best_idx == 1
    assert result.best_score == 7.0
    assert result.metric_header == "algo-id"
    saved = json.loads(best_json.read_text())
    assert saved["algorithmParamsList"][0]["params"]["id"] == 7
    assert "7.0000" in result.to_one_liner()
    parsed = json.loads(result.to_json())
    assert parsed["bestIdx"] == 1 and len(parsed["engineParamsScores"]) == 3
    assert "<table" in result.to_html()


def test_lower_is_better_ordering():
    class LossMetric(FunctionMetric):
        higher_is_better = False

    metric = LossMetric(lambda q, p, a: float(p.algo_id), name="loss")
    evaluation = Evaluation(engine=make_engine(), metric=metric)
    result = MetricEvaluator().evaluate(
        ctx, evaluation, [make_params(3), make_params(7)]
    )
    assert result.best_idx == 0


def test_secondary_metrics_reported():
    m1 = FunctionMetric(lambda q, p, a: float(p.algo_id), name="primary")
    m2 = FunctionMetric(lambda q, p, a: float(q.q), name="mean-q")
    evaluation = Evaluation(engine=make_engine(), metric=m1, metrics=[m2])
    result = MetricEvaluator().evaluate(ctx, evaluation, [make_params(2)])
    assert result.other_metric_headers == ["mean-q"]
    assert len(result.engine_params_scores[0].other_scores) == 1


def test_fast_eval_memoizes_prefixes():
    """ref: FastEvalEngineTest.scala — shared prefixes computed once."""
    engine = make_engine()
    workflow = FastEvalEngineWorkflow(engine, ctx)
    # 3 candidates: same ds+prep, two distinct algo params
    eps = [make_params(3), make_params(3), make_params(9)]
    results = [workflow.eval(ep) for ep in eps]
    assert workflow.counts == {"read": 1, "prepare": 1, "train": 2, "predict": 2}
    # identical candidates give identical results
    assert str(results[0]) == str(results[1])
    # different data source params invalidate the whole prefix
    ep_new_ds = make_params(3)
    ep_new_ds.data_source_params = ("ds", IdParams(id=42))
    workflow.eval(ep_new_ds)
    assert workflow.counts["read"] == 2
    assert workflow.counts["prepare"] == 2
    assert workflow.counts["train"] == 3
    # fast-eval result matches the plain engine eval
    plain = engine.eval(ctx, eps[0])
    fast = results[0]
    assert str(plain) == str(fast)


def test_run_evaluation_persists_instance(memory_storage):
    metric = FunctionMetric(lambda q, p, a: float(p.algo_id), name="m")
    evaluation = Evaluation(engine=make_engine(), metric=metric)
    gen = EngineParamsGenerator([make_params(3), make_params(8)])
    result = run_evaluation(
        evaluation,
        generator=gen,
        evaluation_class="tests.MyEval",
        storage=memory_storage,
    )
    assert result.best_score == 8.0
    instances = memory_storage.evaluation_instances().get_completed()
    assert len(instances) == 1
    inst = instances[0]
    assert inst.status == "EVALCOMPLETED"
    assert inst.evaluation_class == "tests.MyEval"
    assert "8.0000" in inst.evaluator_results
    assert json.loads(inst.evaluator_results_json)["bestScore"] == 8.0
    assert "<table" in inst.evaluator_results_html


def test_run_evaluation_failure_marks_instance(memory_storage):
    class BoomMetric(FunctionMetric):
        def calculate(self, ctx, eval_data):
            raise RuntimeError("boom")

    evaluation = Evaluation(engine=make_engine(), metric=BoomMetric(lambda q, p, a: 0.0))
    with pytest.raises(RuntimeError):
        run_evaluation(evaluation, engine_params_list=[make_params(1)], storage=memory_storage)
    instances = memory_storage.evaluation_instances().get_all()
    assert instances[0].status == "FAILED"


def test_nan_score_never_wins_lower_is_better():
    """A NaN-scored candidate (no eval data) must rank worst even when
    higher_is_better=False (sign flip must not turn NaN into +inf)."""

    class LossMetric(FunctionMetric):
        higher_is_better = False

    metric = LossMetric(lambda q, p, a: float(p.algo_id), name="loss")
    evaluation = Evaluation(engine=make_engine(), metric=metric)

    calls = {"n": 0}
    real_engine_eval = make_engine().eval

    def eval_fn(c, ep):
        # candidate 0 yields no eval data -> NaN score
        calls["n"] += 1
        if calls["n"] == 1:
            return []
        return evaluation.engine.eval(c, ep)

    result = MetricEvaluator().evaluate(
        ctx, evaluation, [make_params(9), make_params(4)], eval_fn=eval_fn
    )
    assert result.best_idx == 1
    assert result.best_score == 4.0
