"""Metric family + MetricEvaluator + FastEval memoization
(ref specs: MetricTest.scala, MetricEvaluatorTest.scala,
FastEvalEngineTest.scala, EvaluationWorkflowTest.scala)."""

import json
import math

import pytest

from predictionio_tpu.core import Engine, EngineParams
from predictionio_tpu.core.evaluation import (
    AverageMetric,
    EngineParamsGenerator,
    Evaluation,
    FunctionMetric,
    MetricEvaluator,
    OptionAverageMetric,
    StdevMetric,
    SumMetric,
)
from predictionio_tpu.core.fast_eval import FastEvalEngineWorkflow
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.workflow.evaluate import run_evaluation

from tests.sample_engine import (
    Algo0,
    DataSource0,
    IdParams,
    Preparator0,
    Serving0,
)

ctx = MeshContext()


def make_eval_data(scores):
    """One fold whose qpa triples carry the given 'actual' scores."""
    return [(None, [(i, i, s) for i, s in enumerate(scores)])]


class ActualMetric(AverageMetric):
    def calculate_qpa(self, q, p, a):
        return float(a)


class OptionalMetric(OptionAverageMetric):
    def calculate_qpa(self, q, p, a):
        return None if a is None else float(a)


class StdevOfActual(StdevMetric):
    def calculate_qpa(self, q, p, a):
        return float(a)


class SumOfActual(SumMetric):
    def calculate_qpa(self, q, p, a):
        return float(a)


def test_metric_family():
    data = make_eval_data([1.0, 2.0, 3.0, 4.0])
    assert ActualMetric().calculate(ctx, data) == 2.5
    assert SumOfActual().calculate(ctx, data) == 10.0
    assert StdevOfActual().calculate(ctx, data) == pytest.approx(math.sqrt(1.25))
    opt = OptionalMetric().calculate(ctx, make_eval_data([1.0, None, 3.0]))
    assert opt == 2.0
    # multi-fold union (ref: sc.union across folds)
    two_folds = make_eval_data([1.0, 2.0]) + make_eval_data([3.0, 4.0])
    assert ActualMetric().calculate(ctx, two_folds) == 2.5
    assert ActualMetric().calculate(ctx, []) != ActualMetric().calculate(ctx, [])  # nan


def make_engine():
    return Engine(
        data_source_classes={"ds": DataSource0},
        preparator_classes={"prep": Preparator0},
        algorithm_classes={"algo": Algo0},
        serving_classes={"serve": Serving0},
    )


def make_params(algo_id):
    return EngineParams(
        data_source_params=("ds", IdParams(id=1)),
        preparator_params=("prep", IdParams(id=2)),
        algorithm_params_list=[("algo", IdParams(id=algo_id))],
        serving_params=("serve", IdParams(id=0)),
    )


def test_metric_evaluator_ranks_and_saves_best(tmp_path):
    # metric = algo id carried through prediction tags: higher algo id wins
    metric = FunctionMetric(lambda q, p, a: float(p.algo_id), name="algo-id")
    evaluation = Evaluation(engine=make_engine(), metric=metric)
    candidates = [make_params(3), make_params(7), make_params(5)]
    best_json = tmp_path / "best.json"
    evaluator = MetricEvaluator(best_json_path=str(best_json))
    result = evaluator.evaluate(ctx, evaluation, candidates)
    assert result.best_idx == 1
    assert result.best_score == 7.0
    assert result.metric_header == "algo-id"
    saved = json.loads(best_json.read_text())
    assert saved["algorithmParamsList"][0]["params"]["id"] == 7
    assert "7.0000" in result.to_one_liner()
    parsed = json.loads(result.to_json())
    assert parsed["bestIdx"] == 1 and len(parsed["engineParamsScores"]) == 3
    assert "<table" in result.to_html()


def test_lower_is_better_ordering():
    class LossMetric(FunctionMetric):
        higher_is_better = False

    metric = LossMetric(lambda q, p, a: float(p.algo_id), name="loss")
    evaluation = Evaluation(engine=make_engine(), metric=metric)
    result = MetricEvaluator().evaluate(
        ctx, evaluation, [make_params(3), make_params(7)]
    )
    assert result.best_idx == 0


def test_secondary_metrics_reported():
    m1 = FunctionMetric(lambda q, p, a: float(p.algo_id), name="primary")
    m2 = FunctionMetric(lambda q, p, a: float(q.q), name="mean-q")
    evaluation = Evaluation(engine=make_engine(), metric=m1, metrics=[m2])
    result = MetricEvaluator().evaluate(ctx, evaluation, [make_params(2)])
    assert result.other_metric_headers == ["mean-q"]
    assert len(result.engine_params_scores[0].other_scores) == 1


def test_fast_eval_memoizes_prefixes():
    """ref: FastEvalEngineTest.scala — shared prefixes computed once."""
    engine = make_engine()
    workflow = FastEvalEngineWorkflow(engine, ctx)
    # 3 candidates: same ds+prep, two distinct algo params
    eps = [make_params(3), make_params(3), make_params(9)]
    results = [workflow.eval(ep) for ep in eps]
    assert workflow.counts == {"read": 1, "prepare": 1, "train": 2, "predict": 2,
                               "grid_dispatches": 0}
    # identical candidates give identical results
    assert str(results[0]) == str(results[1])
    # different data source params invalidate the whole prefix
    ep_new_ds = make_params(3)
    ep_new_ds.data_source_params = ("ds", IdParams(id=42))
    workflow.eval(ep_new_ds)
    assert workflow.counts["read"] == 2
    assert workflow.counts["prepare"] == 2
    assert workflow.counts["train"] == 3
    # fast-eval result matches the plain engine eval
    plain = engine.eval(ctx, eps[0])
    fast = results[0]
    assert str(plain) == str(fast)


def test_run_evaluation_persists_instance(memory_storage):
    metric = FunctionMetric(lambda q, p, a: float(p.algo_id), name="m")
    evaluation = Evaluation(engine=make_engine(), metric=metric)
    gen = EngineParamsGenerator([make_params(3), make_params(8)])
    result = run_evaluation(
        evaluation,
        generator=gen,
        evaluation_class="tests.MyEval",
        storage=memory_storage,
    )
    assert result.best_score == 8.0
    instances = memory_storage.evaluation_instances().get_completed()
    assert len(instances) == 1
    inst = instances[0]
    assert inst.status == "EVALCOMPLETED"
    assert inst.evaluation_class == "tests.MyEval"
    assert "8.0000" in inst.evaluator_results
    assert json.loads(inst.evaluator_results_json)["bestScore"] == 8.0
    assert "<table" in inst.evaluator_results_html


def test_run_evaluation_failure_marks_instance(memory_storage):
    class BoomMetric(FunctionMetric):
        def calculate(self, ctx, eval_data):
            raise RuntimeError("boom")

    evaluation = Evaluation(engine=make_engine(), metric=BoomMetric(lambda q, p, a: 0.0))
    with pytest.raises(RuntimeError):
        run_evaluation(evaluation, engine_params_list=[make_params(1)], storage=memory_storage)
    instances = memory_storage.evaluation_instances().get_all()
    assert instances[0].status == "FAILED"


def test_nan_score_never_wins_lower_is_better():
    """A NaN-scored candidate (no eval data) must rank worst even when
    higher_is_better=False (sign flip must not turn NaN into +inf)."""

    class LossMetric(FunctionMetric):
        higher_is_better = False

    metric = LossMetric(lambda q, p, a: float(p.algo_id), name="loss")
    evaluation = Evaluation(engine=make_engine(), metric=metric)

    calls = {"n": 0}
    real_engine_eval = make_engine().eval

    def eval_fn(c, ep):
        # candidate 0 yields no eval data -> NaN score
        calls["n"] += 1
        if calls["n"] == 1:
            return []
        return evaluation.engine.eval(c, ep)

    result = MetricEvaluator().evaluate(
        ctx, evaluation, [make_params(9), make_params(4)], eval_fn=eval_fn
    )
    assert result.best_idx == 1
    assert result.best_score == 4.0


# ---------------------------------------------------------------------------
# Vmapped grid tuning through `pio eval` (VERDICT r3 item 5): when the
# candidates differ only in ALS reg, MetricEvaluator's candidates train
# in ONE compiled dispatch per fold (ALSAlgorithm.grid_train), with
# leaderboard/ranking/best.json identical to the sequential path.
# ---------------------------------------------------------------------------

def _reco_eval_setup(memory_storage, n_users=30, n_items=12, per_user=6):
    import numpy as np

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.templates import recommendation as reco_t

    app = memory_storage.apps().insert("grid-app")
    memory_storage.events().init(app.id)
    rng = np.random.default_rng(5)
    events, m = [], 0
    import datetime as dt

    for u in range(n_users):
        for i in rng.choice(n_items, size=per_user, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{int(i)}",
                properties={"rating": float(1 + (u * int(i)) % 5)},
                event_time=dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
                + dt.timedelta(minutes=m)))
            m += 1
    memory_storage.events().insert_batch(events, app.id)
    return reco_t


def _grid_candidates(reco_t, regs):
    from predictionio_tpu.core.params import EngineParams
    from predictionio_tpu.models.als import ALSParams

    return [
        EngineParams(
            data_source_params=("", reco_t.RecoDataSourceParams(
                app_name="grid-app", columnar=False, eval_k=2)),
            preparator_params=("", None),
            algorithm_params_list=[("als", ALSParams(
                rank=4, num_iterations=3, lambda_=reg, block_size=32,
                compute_dtype="float32", cg_dtype="float32"))],
            serving_params=("", None),
        )
        for reg in regs
    ]


class _RatingMSE(AverageMetric):
    higher_is_better = False

    def calculate_qpa(self, q, p, a):
        match = [s["score"] for s in p["itemScores"]
                 if s["item"] == a["item"]]
        if not match:
            return None
        return (match[0] - a["rating"]) ** 2


def test_als_reg_grid_single_dispatch_matches_sequential(memory_storage):
    """6-point reg grid: one vmapped train dispatch per fold, identical
    ranking to the sequential path (VERDICT r3 item 5 done-criterion)."""
    from predictionio_tpu.core.fast_eval import FastEvalEngineWorkflow
    from predictionio_tpu.parallel.mesh import MeshContext

    reco_t = _reco_eval_setup(memory_storage)
    regs = [0.01, 0.05, 0.1, 0.5, 1.0, 5.0]
    candidates = _grid_candidates(reco_t, regs)
    metric = _RatingMSE()
    engine = reco_t.recommendation_engine()
    ctx = MeshContext()

    # grid path, instrumented
    wf = FastEvalEngineWorkflow(engine, ctx)
    assert wf.prefetch_grid(candidates) == len(regs)
    n_folds = 2
    assert wf.counts["grid_dispatches"] == n_folds
    assert wf.counts["train"] == 0  # no sequential trains happened
    grid_results = [wf.eval(ep) for ep in candidates]
    assert wf.counts["train"] == 0  # scoring hit the seeded cache only
    grid_scores = [metric.calculate(ctx, r) for r in grid_results]

    # sequential oracle: plain per-candidate eval
    wf_seq = FastEvalEngineWorkflow(engine, ctx)
    seq_scores = [metric.calculate(ctx, wf_seq.eval(ep))
                  for ep in candidates]
    assert wf_seq.counts["train"] == len(regs)

    import numpy as np

    np.testing.assert_allclose(grid_scores, seq_scores, rtol=1e-4, atol=1e-5)
    assert np.argsort(grid_scores).tolist() == np.argsort(seq_scores).tolist()


def test_grid_prefetch_declines_heterogeneous_candidates(memory_storage):
    """Candidates differing beyond the reg scalar keep the sequential
    path (grid_train returns None; nothing is mis-cached)."""
    import dataclasses

    from predictionio_tpu.core.fast_eval import FastEvalEngineWorkflow
    from predictionio_tpu.parallel.mesh import MeshContext

    reco_t = _reco_eval_setup(memory_storage)
    candidates = _grid_candidates(reco_t, [0.01, 0.1])
    # second candidate also changes rank -> not a pure reg sweep
    slot_name, p1 = candidates[1].algorithm_params_list[0]
    candidates[1].algorithm_params_list[0] = (
        slot_name, dataclasses.replace(p1, rank=8))
    wf = FastEvalEngineWorkflow(reco_t.recommendation_engine(), MeshContext())
    assert wf.prefetch_grid(candidates) == 0
    assert wf.counts["grid_dispatches"] == 0


def test_run_evaluation_uses_grid_path(memory_storage, caplog):
    """The product `pio eval` path logs the one-dispatch proof."""
    import logging

    reco_t = _reco_eval_setup(memory_storage)
    candidates = _grid_candidates(reco_t, [0.01, 0.1, 1.0])
    evaluation = Evaluation(
        engine=reco_t.recommendation_engine(), metric=_RatingMSE())
    with caplog.at_level(logging.INFO, logger="predictionio_tpu.core.fast_eval"):
        result = run_evaluation(evaluation, engine_params_list=candidates,
                                storage=memory_storage)
    assert any("grid tuning: 3 candidates" in r.message for r in caplog.records)
    assert len(result.engine_params_scores) == 3
    assert result.best_idx in range(3)
