"""Batch view filter/fold surface (ref: view/LBatchView.scala behavior)."""

import datetime as dt

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.view import BatchView, EventSeq, datamap_aggregator

UTC = dt.timezone.utc
T0 = dt.datetime(2026, 1, 1, tzinfo=UTC)


def _ev(event, eid, props=None, minutes=0, etype="user"):
    return Event(event=event, entity_type=etype, entity_id=eid,
                 properties=props or {},
                 event_time=T0 + dt.timedelta(minutes=minutes))


def test_eventseq_filters_compose():
    seq = EventSeq([
        _ev("rate", "u1", minutes=0),
        _ev("buy", "u1", minutes=5),
        _ev("rate", "u2", minutes=10, etype="account"),
        _ev("rate", "u3", minutes=20),
    ])
    assert len(seq.filter(event="rate")) == 3
    assert len(seq.filter(event="rate", entity_type="user")) == 2
    # half-open [start, until): start inclusive, until exclusive
    win = seq.filter(start_time=T0 + dt.timedelta(minutes=5),
                     until_time=T0 + dt.timedelta(minutes=20))
    assert [e.entity_id for e in win] == ["u1", "u2"]
    assert len(seq.filter(predicate=lambda e: e.entity_id == "u3")) == 1


def test_aggregate_by_entity_ordered_is_time_sorted():
    # insert out of order; fold must see event-time order
    seq = EventSeq([
        _ev("$set", "u1", {"a": 2}, minutes=10),
        _ev("$set", "u1", {"a": 1}, minutes=0),
    ])
    out = seq.aggregate_by_entity_ordered([], lambda acc, e: acc + [e.properties["a"]])
    assert out["u1"] == [1, 2]


def test_datamap_aggregator_set_unset_delete():
    op = datamap_aggregator()
    p = op(None, _ev("$set", "u", {"a": 1, "b": 2}))
    p = op(p, _ev("$set", "u", {"b": 3, "c": 4}))
    assert p == {"a": 1, "b": 3, "c": 4}
    p = op(p, _ev("$unset", "u", {"a": 0}))
    assert p == {"b": 3, "c": 4}
    p = op(p, _ev("rate", "u", {"x": 9}))      # non-$ events don't touch props
    assert p == {"b": 3, "c": 4}
    assert op(p, _ev("$delete", "u")) is None
    assert op(None, _ev("$unset", "u", {"a": 0})) is None


def test_batch_view_aggregate_properties(memory_storage):
    app = memory_storage.apps().insert("viewapp")
    memory_storage.events().init(app.id)
    for e in [
        _ev("$set", "u1", {"plan": "free"}, minutes=0),
        _ev("$set", "u1", {"plan": "pro"}, minutes=5),
        _ev("$set", "u2", {"plan": "free"}, minutes=6),
        _ev("$delete", "u2", minutes=7),
        _ev("$set", "i1", {"cat": "a"}, minutes=1, etype="item"),
    ]:
        memory_storage.events().insert(e, app.id)
    view = BatchView("viewapp", storage=memory_storage)
    props = view.aggregate_properties(entity_type="user")
    assert props == {"u1": {"plan": "pro"}}       # u2 deleted
    assert view.aggregate_properties(entity_type="item") == {"i1": {"cat": "a"}}
    # unfiltered: both entity types
    assert set(view.aggregate_properties()) == {"u1", "i1"}
