"""Fleet-wide observability federation (obs/collect.py): merge math,
cross-process trace stitching, the span-query surface, and the
acceptance e2e — a query driven through the router against a 3-replica
fleet (hedging armed) yields ONE stitched tree containing router,
replica and storage-server spans, and ``GET /admin/fleet/metrics``
bucket counts equal the sum of the members'.
"""

from __future__ import annotations

import json
import re
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

import pytest

from predictionio_tpu.obs import collect, metrics, trace
from predictionio_tpu.resilience import chaos

from tests.test_health import get, get_json, train_const
from tests.test_fleet import post, running_fleet


# ---------------------------------------------------------------------------
# exposition parsing + merge math
# ---------------------------------------------------------------------------

M1 = """\
# HELP pio_x_total things
# TYPE pio_x_total counter
pio_x_total{kind="a"} 3
# TYPE pio_g gauge
pio_g{slot="z"} 7
# TYPE pio_serving_request_seconds histogram
pio_serving_request_seconds_bucket{engine="e",le="0.1"} 5
pio_serving_request_seconds_bucket{engine="e",le="+Inf"} 6
pio_serving_request_seconds_sum{engine="e"} 0.9
pio_serving_request_seconds_count{engine="e"} 6
"""

M2 = """\
# TYPE pio_x_total counter
pio_x_total{kind="a"} 4
pio_x_total{kind="b"} 1
# TYPE pio_g gauge
pio_g{slot="z"} 2
pio_g{other="y"} 5
# TYPE pio_serving_request_seconds histogram
pio_serving_request_seconds_bucket{engine="e",le="0.1"} 1
pio_serving_request_seconds_bucket{engine="e",le="+Inf"} 4
pio_serving_request_seconds_sum{engine="e"} 1.5
pio_serving_request_seconds_count{engine="e"} 4
"""


def merged_two_members():
    return collect.merge_families([
        ("r0", collect.parse_exposition(M1)),
        ("r1", collect.parse_exposition(M2)),
    ])


def test_parse_exposition_families_and_labels():
    fams = collect.parse_exposition(M1)
    assert fams["pio_x_total"]["kind"] == "counter"
    assert fams["pio_serving_request_seconds"]["kind"] == "histogram"
    samples = fams["pio_serving_request_seconds"]["samples"]
    key = ("pio_serving_request_seconds_bucket",
           (("engine", "e"), ("le", "0.1")))
    assert samples[key] == 5.0
    # exemplars and escapes survive
    fams = collect.parse_exposition(
        '# TYPE h histogram\nh_bucket{le="0.1"} 2 # {trace_id="ab"} '
        '0.05 123.0\nweird{msg="a\\"b"} 1\n')
    assert fams["h"]["samples"][("h_bucket", (("le", "0.1"),))] == 2.0
    assert fams["weird"]["samples"][("weird", (("msg", 'a"b'),))] == 1.0


def test_merge_counters_sum_and_histograms_sum_bucketwise():
    flat = collect.flat_samples(merged_two_members())
    assert flat['pio_x_total{kind="a"}'] == 7.0
    assert flat['pio_x_total{kind="b"}'] == 1.0  # disjoint label sets union
    assert flat['pio_serving_request_seconds_bucket'
                '{engine="e",le="0.1"}'] == 6.0
    assert flat['pio_serving_request_seconds_bucket'
                '{engine="e",le="+Inf"}'] == 10.0
    assert flat['pio_serving_request_seconds_count{engine="e"}'] == 10.0
    assert flat['pio_serving_request_seconds_sum{engine="e"}'] == 2.4


def test_merge_gauges_keep_member_label():
    flat = collect.flat_samples(merged_two_members())
    # a gauge is NEVER summed: one series per member, member visible
    assert flat['pio_g{member="r0",slot="z"}'] == 7.0
    assert flat['pio_g{member="r1",slot="z"}'] == 2.0
    assert flat['pio_g{member="r1",other="y"}'] == 5.0
    assert 'pio_g{slot="z"}' not in flat


def test_render_merged_is_reparseable():
    merged = merged_two_members()
    text = collect.render_merged(merged)
    assert "# TYPE pio_serving_request_seconds histogram" in text
    again = collect.parse_exposition(text)
    assert collect.flat_samples(
        collect.merge_families([])) == {}
    # counters re-parse to the same values (gauges re-parse with their
    # member label already attached)
    assert again["pio_x_total"]["samples"][
        ("pio_x_total", (("kind", "a"),))] == 7.0


def test_fleet_slo_burn_over_merged_histogram(monkeypatch):
    monkeypatch.setenv("PIO_SLO_LATENCY_MS", "100")
    monkeypatch.setenv("PIO_SLO_LATENCY_OBJECTIVE", "0.99")
    slo = collect.fleet_slo(merged_two_members())
    # good = merged counts in buckets covering 100ms: le=0.1 -> 6
    assert slo["good"] == 6.0 and slo["total"] == 10.0
    assert slo["error_rate"] == pytest.approx(0.4)
    assert slo["burn"] == pytest.approx(40.0)
    # no traffic -> no burn, distinguishable from burning at 0
    empty = collect.fleet_slo(collect.merge_families([]))
    assert empty["burn"] is None and empty["error_rate"] is None


def test_quantile_from_flat_interpolates():
    flat = collect.flat_samples(merged_two_members())
    q = collect.quantile_from_flat(
        flat, "pio_serving_request_seconds", 0.5)
    # rank 5 of 10 inside the first bucket [0, 0.1): interpolated
    assert 0.0 < q < 0.1
    assert collect.quantile_from_flat({}, "nope", 0.5) is None


def test_merge_degrades_on_dead_member():
    """A member answering 5xx (or nothing) must degrade the merge to
    the members that answered — never fail it."""
    import socket

    # a port with nothing listening: transport failure
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    members = [collect.Member("local", None),
               collect.Member("gone", f"http://127.0.0.1:{dead_port}")]
    report = collect.federate_metrics(members)
    by_name = {m["name"]: m for m in report["members"]}
    assert by_name["local"]["ok"] is True
    assert by_name["gone"]["ok"] is False and by_name["gone"]["error"]
    assert report["merged_from"] == ["local"]
    assert report["samples"]  # the local registry still merged


# ---------------------------------------------------------------------------
# tree assembly
# ---------------------------------------------------------------------------

def synthetic_spans():
    return [
        {"trace": "t", "span": "a", "parent": None, "name": "http.router",
         "server": "router", "start_unix": 1.0, "duration_ms": 50.0},
        {"trace": "t", "span": "b", "parent": "a", "name": "router.attempt",
         "replica": "r0", "start_unix": 1.001, "duration_ms": 49.0},
        {"trace": "t", "span": "c", "parent": "b",
         "name": "http.engineserver", "server": "engineserver",
         "start_unix": 1.002, "duration_ms": 47.0},
        {"trace": "t", "span": "h", "parent": "a", "name": "router.attempt",
         "replica": "r1", "hedge": True, "start_unix": 1.03,
         "duration_ms": 12.0},
        {"trace": "t", "span": "e", "parent": "zz",
         "name": "http.storageserver", "server": "storageserver",
         "start_unix": 1.01, "duration_ms": 3.0},
    ]


def test_build_tree_annotations_and_missing_parent():
    doc = collect.build_tree("t", synthetic_spans(),
                             members=[{"name": "local", "ok": True,
                                       "evicted_total": 9}])
    assert doc["span_count"] == 5
    assert set(doc["processes"]) == {"router", "engineserver",
                                     "storageserver"}
    # the evicted parent became an explicit placeholder root
    assert doc["complete"] is False and doc["missing_spans"] == ["zz"]
    roots = doc["roots"]
    assert len(roots) == 2
    real = next(r for r in roots if not r.get("missing"))
    placeholder = next(r for r in roots if r.get("missing"))
    assert "evicted" in placeholder["note"] and "9" in placeholder["note"]
    assert placeholder["children"][0]["name"] == "http.storageserver"
    # children sorted by start; process/replica inherit down the tree
    attempts = real["children"]
    assert [a["replica"] for a in attempts] == ["r0", "r1"]
    assert attempts[1]["hedge"] is True
    engine = attempts[0]["children"][0]
    assert engine["process"] == "engineserver"
    assert engine["replica"] == "r0"  # inherited from the attempt
    # parent-edge latency: child start minus parent start, in ms
    assert attempts[0]["edge_ms"] == pytest.approx(1.0)
    assert engine["edge_ms"] == pytest.approx(1.0)


def test_build_tree_dedupes_nothing_but_renders_complete():
    spans = [s for s in synthetic_spans() if s["span"] != "e"]
    doc = collect.build_tree("t", spans)
    assert doc["complete"] is True and len(doc["roots"]) == 1


def test_build_tree_breaks_parent_cycles():
    """A malformed member payload (self-parenting span, two spans
    parenting each other) must not hang or vanish: the cycle is broken
    at its earliest span, promoted to an annotated root, and the doc
    reports not-complete."""
    spans = [
        {"trace": "t", "span": "s", "parent": "s", "name": "self.loop",
         "start_unix": 1.0, "duration_ms": 1.0},
        {"trace": "t", "span": "x", "parent": "y", "name": "cyc.a",
         "start_unix": 2.0, "duration_ms": 1.0},
        {"trace": "t", "span": "y", "parent": "x", "name": "cyc.b",
         "start_unix": 3.0, "duration_ms": 1.0},
    ]
    doc = collect.build_tree("t", spans)
    assert doc["complete"] is False
    assert set(doc["cyclic_spans"]) == {"s", "x"}
    rendered = collect.format_trace_tree(doc)  # must terminate
    assert "cycle" in rendered
    names = {n.get("name") for n in _tree_nodes(doc)}
    assert names == {"self.loop", "cyc.a", "cyc.b"}  # nothing dropped


def test_format_trace_tree_renders_glyphs_and_partial():
    doc = collect.build_tree("t", synthetic_spans(),
                             members=[{"name": "local", "ok": True,
                                       "evicted_total": 9}])
    doc["members"] = [{"name": "local", "url": None, "role": "local",
                       "ok": True, "spans": 5, "evicted_total": 9},
                      {"name": "gone", "url": "http://x", "role": "replica",
                       "ok": False, "error": "HTTP 503"}]
    text = collect.format_trace_tree(doc)
    assert "PARTIAL" in text
    assert "└─" in text and "├─" in text
    assert "replica=r0" in text and "hedge" in text
    assert "missing span zz" in text
    assert "ERROR: HTTP 503" in text
    assert "<engineserver>" in text


# ---------------------------------------------------------------------------
# span ring: PIO_SPAN_RING + eviction counter
# ---------------------------------------------------------------------------

def test_span_ring_env_capacity_and_eviction_counter(monkeypatch):
    monkeypatch.setenv("PIO_SPAN_RING", "4")
    trace.clear_recent()
    before = trace.evicted_total()
    token = trace.activate(trace.new_trace_id())
    try:
        for _ in range(7):
            with trace.span("ring.unit"):
                pass
    finally:
        trace.deactivate(token)
    assert len(trace.recent_spans()) == 4
    assert trace.evicted_total() == before + 3
    # restoring the env restores the capacity on the next emit
    monkeypatch.setenv("PIO_SPAN_RING", "64")
    token = trace.activate(trace.new_trace_id())
    try:
        with trace.span("ring.unit"):
            pass
    finally:
        trace.deactivate(token)
    assert trace.recent_spans() and len(trace.recent_spans()) == 5


def test_traced_headers_carry_context_only_when_active():
    assert trace.traced_headers({"A": "b"}) == {"A": "b"}
    token = trace.activate("feedface" * 4)
    try:
        with trace.span("hdr.unit"):
            headers = trace.traced_headers({"A": "b"})
            assert headers["A"] == "b"
            assert headers[trace.TRACE_HEADER] == "feedface" * 4
            assert trace.valid_span_id(headers[trace.PARENT_HEADER])
    finally:
        trace.deactivate(token)


# ---------------------------------------------------------------------------
# span-query surface on a live server
# ---------------------------------------------------------------------------

def test_admin_spans_endpoint(memory_storage):
    from predictionio_tpu.serving.storage_server import StorageServer

    server = StorageServer(storage=memory_storage, host="127.0.0.1",
                           port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        tid = "ad0be" + trace.new_trace_id()[:27]
        token = trace.activate(tid)
        try:
            with trace.span("spanpage.unit", detail=1):
                pass
        finally:
            trace.deactivate(token)
        status, page = get_json(f"{base}/admin/spans?trace={tid}")
        assert status == 200
        assert page["server"] == "PIOStorageServer"
        assert page["ring_capacity"] == trace.ring_capacity()
        assert isinstance(page["evicted_total"], int)
        assert [s["name"] for s in page["spans"]] == ["spanpage.unit"]
        # a non-id-shaped trace filter is rejected, not echoed around
        status, _ = get_json(f"{base}/admin/spans?trace=zzz")
        assert status == 400
        status, _ = get_json(f"{base}/admin/spans?trace={tid}&n=x")
        assert status == 400
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# acceptance e2e: 3-replica fleet + storage server, hedging armed
# ---------------------------------------------------------------------------

class _Holder:
    client = None
    app_id = None


def _rest_client(port):
    from predictionio_tpu.data.storage import Storage

    return Storage.from_env({
        "PIO_STORAGE_SOURCES_CENTRAL_TYPE": "rest",
        "PIO_STORAGE_SOURCES_CENTRAL_HOSTS": "127.0.0.1",
        "PIO_STORAGE_SOURCES_CENTRAL_PORTS": str(port),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "CENTRAL",
    })


def _build_reading_engine():
    from predictionio_tpu.core import (Algorithm, DataSource, Engine,
                                       FirstServing, IdentityPreparator)
    from predictionio_tpu.core.params import Params

    @dataclass
    class NoParams(Params):
        pass

    class OneDataSource(DataSource):
        def read_training(self, ctx):
            return 1.0

    class StorageReadingAlgo(Algorithm):
        """predict() does a REST storage read — the cross-process hop
        the stitched trace must contain."""

        def train(self, ctx, pd):
            return pd

        def predict(self, model, query):
            events = _Holder.client.events().find(_Holder.app_id)
            return {"events": len(events), "model": model}

    return Engine(OneDataSource, IdentityPreparator,
                  {"reader": StorageReadingAlgo}, FirstServing), NoParams


def _tree_nodes(doc):
    out = []

    def walk(node):
        out.append(node)
        for child in node.get("children") or []:
            walk(child)

    for root in doc.get("roots") or []:
        walk(root)
    return out


def _canon_serving(samples):
    """Serving-histogram samples with canonically sorted labels, so a
    member's rendered text and the merged flat form compare equal."""
    out = {}
    for key, value in samples.items():
        if not key.startswith("pio_serving_request_seconds"):
            continue
        name, _, labels = key.partition("{")
        labels = labels.rstrip("}")
        pairs = sorted(re.findall(r'([a-zA-Z_]+)="([^"]*)"', labels))
        out[(name, tuple(pairs))] = out.get((name, tuple(pairs)), 0.0) + value
    return out


def test_acceptance_stitched_trace_and_fleet_metrics(memory_storage,
                                                     monkeypatch):
    """ISSUE acceptance: a query driven through the router against a
    3-replica fleet (hedging armed) yields a single stitched tree
    containing router, replica and storage-server spans, and
    ``GET /admin/fleet/metrics`` bucket counts equal the sum of the
    members' — zero non-429 errors under load."""
    from predictionio_tpu.core.params import EngineParams
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.serving.engine_server import EngineServer
    from predictionio_tpu.serving.fleet import (FleetSupervisor,
                                                threaded_fleet)
    from predictionio_tpu.serving.router import QueryRouter
    from predictionio_tpu.serving.storage_server import StorageServer
    from predictionio_tpu.tools import cli
    from predictionio_tpu.workflow.train import run_train

    storage_server = StorageServer(storage=memory_storage,
                                   host="127.0.0.1", port=0).start()
    fleet = router = None
    try:
        client = _rest_client(storage_server.port)
        app = client.apps().insert("fed-app")
        client.events().init(app.id)
        client.events().insert(
            Event(event="view", entity_type="user", entity_id="u1"),
            app.id)
        _Holder.client, _Holder.app_id = client, app.id
        engine, NoParams = _build_reading_engine()
        ep = EngineParams(
            data_source_params=("", NoParams()),
            preparator_params=("", None),
            algorithm_params_list=[("reader", NoParams())],
            serving_params=("", None),
        )
        run_train(engine, ep, engine_id="fed", storage=memory_storage)

        # the storage server joins the pane of glass as a configured
        # member (the "event/storage/stream addresses" knob)
        monkeypatch.setenv(
            "PIO_OBS_MEMBERS",
            f"storage=http://127.0.0.1:{storage_server.port}")

        def factory(name):
            return EngineServer(engine, "fed", host="127.0.0.1", port=0,
                                storage=memory_storage, chaos_tag=name)

        fleet = FleetSupervisor(threaded_fleet(3, factory),
                                probe_interval=0.1).start()
        assert fleet.wait_ready(timeout=60), fleet.snapshot()
        router = QueryRouter(fleet, host="127.0.0.1", port=0).start()
        base = f"http://127.0.0.1:{router.port}"

        trace.clear_recent()
        trace_ids = []
        for _ in range(30):  # past HedgeClock.min_samples: hedging arms
            status, body, headers = post(
                base + "/queries.json", body=b'{"q": 1}')
            assert status == 200, body  # zero non-429 (indeed, none)
            assert json.loads(body)["events"] == 1
            trace_ids.append(headers[trace.TRACE_HEADER])
        assert router.hedge.deadline() is not None  # hedging armed

        tid = trace_ids[-1]
        wanted = ("http.router", "router.attempt", "http.engineserver",
                  "storage.find", "http.storageserver")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            names = [s["name"] for s in trace.recent_spans(trace_id=tid)]
            if all(w in names for w in wanted):
                break
            time.sleep(0.02)
        assert all(w in names for w in wanted), names

        # -- the stitched tree off the router -------------------------------
        status, doc = get_json(base + f"/admin/trace?id={tid}")
        assert status == 200
        assert doc["complete"] is True, doc.get("missing_spans")
        assert len(doc["roots"]) == 1  # ONE tree, not a forest
        root = doc["roots"][0]
        assert root["name"] == "http.router"
        assert {"router", "engineserver", "storageserver"} <= set(
            doc["processes"])
        nodes = _tree_nodes(doc)
        by_name = {}
        for node in nodes:
            by_name.setdefault(node.get("name"), []).append(node)
        # the replica hop is a child of a router.attempt span, and the
        # storage-server edge sits under the rest client's storage span
        engine_edge = by_name["http.engineserver"][0]
        assert engine_edge["process"] == "engineserver"
        assert engine_edge["replica"] in {"r0", "r1", "r2"}
        storage_edge = by_name["http.storageserver"][0]
        assert storage_edge["process"] == "storageserver"
        assert isinstance(storage_edge.get("edge_ms"), (int, float))
        # every fleet member (and the configured storage) answered
        ok_members = {m["name"] for m in doc["members"] if m["ok"]}
        assert {"local", "r0", "r1", "r2", "storage"} <= ok_members

        # -- pio trace renders the same document ----------------------------
        rc = cli.main(["trace", tid, "--url", base])
        assert rc == 0
        rc = cli.main(["trace", "feedfacefeedface", "--url", base])
        assert rc == 1  # unknown trace: no spans

        # -- metric federation: merged == sum of the members ----------------
        status, report = get_json(base + "/admin/fleet/metrics")
        assert status == 200
        assert all(m["ok"] for m in report["members"]), report["members"]
        assert {m["name"] for m in report["members"]} == {
            "r0", "r1", "r2", "storage"}
        member_sums = {}
        for member in report["members"]:
            _, text, _ = get(member["url"] + "/metrics")
            for key, value in _canon_serving(
                    metrics.samples_dict(text)).items():
                member_sums[key] = member_sums.get(key, 0.0) + value
        merged = _canon_serving(report["samples"])
        bucket_keys = [k for k in member_sums
                       if k[0].endswith("_bucket")]
        assert bucket_keys
        for key in bucket_keys:
            assert merged[key] == member_sums[key], key
        # the merged serving histogram carries the fleet SLO burn
        assert report["slo"]["total"] >= 30
        assert report["slo"]["burn"] is not None
        # the text form re-parses
        status, text, _ = get(base + "/admin/fleet/metrics?format=prom")
        assert status == 200 and "# TYPE" in text
        assert collect.parse_exposition(text)

        # -- fleet-wide tail attribution ------------------------------------
        status, tail = get_json(base + "/admin/fleet/tail")
        assert status == 200
        assert tail["total_count"] >= 4
        assert tail["stages"], tail
        assert {m["name"] for m in tail["members"]} == {
            "r0", "r1", "r2", "storage"}
        assert set(tail["member_tail"]) <= {"r0", "r1", "r2", "storage"}
        assert sum(e["tail_count"] for e in
                   tail["member_tail"].values()) == tail["tail_count"]

        # -- pio top --fleet drives off the federated endpoint --------------
        rc = cli.main(["top", "--fleet", "--once", "--url", base])
        assert rc == 0
    finally:
        if router is not None:
            router.stop()
        if fleet is not None:
            fleet.stop()
        storage_server.stop()
        _Holder.client = None


def test_hedged_attempt_is_sibling_span(memory_storage, monkeypatch):
    """A hedged second attempt appears as a SIBLING ``router.attempt``
    span (marked hedge) under the same trace — the stitched tree shows
    the placement decision, not just its winner."""
    monkeypatch.setenv("PIO_HEDGE_MIN_MS", "40")
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2) as (fleet, router,
                                                        base):
        for _ in range(25):  # arm the hedge clock
            status, _, _ = post(base + "/queries.json")
            assert status == 200
        chaos.configure("batcher@r1:hang:2s")
        trace_ids = []
        for _ in range(8):
            status, body, headers = post(base + "/queries.json")
            assert status == 200, body
            trace_ids.append(headers[trace.TRACE_HEADER])
        chaos.clear()
        # the hung primary's attempt span seals when the hang releases:
        # poll for a trace carrying BOTH attempts
        hedged = None
        deadline = time.monotonic() + 6.0
        while hedged is None and time.monotonic() < deadline:
            for tid in trace_ids:
                spans = [s for s in trace.recent_spans(trace_id=tid)
                         if s["name"] == "router.attempt"]
                if len(spans) >= 2 and any(s.get("hedge") for s in spans):
                    hedged = tid
                    break
            time.sleep(0.05)
        assert hedged is not None, "no hedged trace found"
        doc = collect.stitch_trace(hedged,
                                   collect.default_members(router))
        attempts = [n for n in _tree_nodes(doc)
                    if n.get("name") == "router.attempt"]
        assert len(attempts) >= 2
        parents = {a.get("parent") for a in attempts}
        assert len(parents) == 1  # siblings under the one router span
        assert any(a.get("hedge") for a in attempts)
        replicas = {a.get("replica") for a in attempts}
        assert replicas == {"r0", "r1"}


def test_canary_shadow_span_rides_the_original_trace(memory_storage):
    """The router's canary shadow replays a query on the worker pool
    AFTER the client is answered — its ``router.shadow`` span must
    still join the ORIGINAL request's trace as a marked sibling."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2) as (fleet, router,
                                                        base):
        replica = fleet.ready_replicas()[0]
        tid = trace.new_trace_id()
        ctx = trace.SpanContext(trace_id=tid, span_id="feedfacecafe0001")
        router._canary_shadow(replica, b'{"mult": 2}', b'{"result": 6.0}',
                              ctx=ctx)
        deadline = time.monotonic() + 5.0
        shadow = None
        while shadow is None and time.monotonic() < deadline:
            for s in trace.recent_spans(trace_id=tid):
                if s["name"] == "router.shadow":
                    shadow = s
            time.sleep(0.02)
        assert shadow is not None
        assert shadow["parent"] == "feedfacecafe0001"
        assert shadow["shadow"] is True
        assert shadow["replica"] == replica.name


def test_fleet_tail_degrades_on_dead_member(memory_storage):
    """A member mid-restart degrades the fleet tail merge (reported,
    not fatal) — the surviving members still attribute."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    members = [collect.Member("local", None),
               collect.Member("gone", f"http://127.0.0.1:{dead_port}")]
    report = collect.federate_tail(members)
    by_name = {m["name"]: m for m in report["members"]}
    assert by_name["local"]["ok"] is True
    assert by_name["gone"]["ok"] is False


def test_dashboard_trace_view(memory_storage):
    from predictionio_tpu.tools.dashboard import DashboardServer

    server = DashboardServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status, text, _ = get(base + "/trace")
        assert status == 200 and "<form" in text
        tid = trace.new_trace_id()
        token = trace.activate(tid)
        try:
            with trace.span("dash.unit"):
                pass
        finally:
            trace.deactivate(token)
        status, text, _ = get(base + f"/trace?id={tid}")
        assert status == 200 and "dash.unit" in text
        status, text, _ = get(base + "/trace?id=%3Cscript%3E")
        assert status == 200 and "not an id-shaped" in text
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# bench + CI gate: federation keys are benchcmp-gated lower-better
# ---------------------------------------------------------------------------

def _bench_round(tmp_path, name, scrape_ms, stitch_ms):
    path = tmp_path / name
    path.write_text(json.dumps({"parsed": {
        "metric": "m", "value": 1.0,
        "key": {"fleet_scrape_ms": scrape_ms,
                "trace_stitch_ms": stitch_ms},
    }}))
    return str(path)


def test_benchcmp_gates_federation_keys(tmp_path, capsys):
    from predictionio_tpu.tools import benchcmp

    assert benchcmp.lower_is_better("key.fleet_scrape_ms")
    assert benchcmp.lower_is_better("key.trace_stitch_ms")
    base = _bench_round(tmp_path, "BENCH_r01.json", 10.0, 5.0)
    worse = _bench_round(tmp_path, "BENCH_r02.json", 25.0, 5.0)
    assert benchcmp.run([base, worse]) == 1  # regression -> exit 1
    out = capsys.readouterr().out
    assert "key.fleet_scrape_ms" in out and "REGRESSION" in out
    better = _bench_round(tmp_path, "BENCH_r03.json", 8.0, 2.0)
    assert benchcmp.run([base, better]) == 0


# ---------------------------------------------------------------------------
# ops-journal + anomaly federation
# ---------------------------------------------------------------------------

def _dead_member():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    return collect.Member("gone", f"http://127.0.0.1:{port}")


def test_federate_journal_merges_and_degrades():
    from predictionio_tpu.obs import journal

    journal.emit("reload", instance="i-1")
    journal.emit("breaker", target="t", state="open")
    members = [collect.Member("local", None), _dead_member()]
    report = collect.federate_journal(members, n=50)
    by_name = {m["name"]: m for m in report["members"]}
    assert by_name["local"]["ok"] is True
    assert by_name["local"]["events"] == 2
    assert by_name["gone"]["ok"] is False and by_name["gone"]["error"]
    assert report["merged_from"] == ["local"]
    kinds = [e["kind"] for e in report["events"]]
    assert kinds == ["reload", "breaker"]  # wall-clock ordered
    assert all(e["fleet_member"] == "local" for e in report["events"])


def test_federate_journal_dedupes_shared_process_journal():
    """Threaded replicas share one process journal: the same event
    reported by two member views must appear once, stamped with the
    first member that reported it."""
    from predictionio_tpu.obs import journal

    journal.emit("swap", phase="start")
    members = [collect.Member("r0", None), collect.Member("r1", None)]
    report = collect.federate_journal(members, n=50)
    assert [m["events"] for m in report["members"]] == [1, 0]
    assert len(report["events"]) == 1
    assert report["events"][0]["fleet_member"] == "r0"


def test_federate_journal_kind_filter_passes_through():
    from predictionio_tpu.obs import journal

    journal.emit("reload", instance="i-1")
    journal.emit("patch", outcome="ok")
    report = collect.federate_journal(
        [collect.Member("local", None)], n=50, kind="patch")
    assert [e["kind"] for e in report["events"]] == ["patch"]


def test_federate_anomaly_unions_active_and_degrades():
    from predictionio_tpu.obs import anomaly

    verdict = {"mode": "step", "direction": "up", "z": 9.0,
               "baseline": 10.0, "recent": 15.0, "onset_ts": 1450.0,
               "since": 1540.0}
    anomaly.SENTINEL._active["serve_p99_ms.e"] = dict(verdict)
    members = [collect.Member("local", None), _dead_member()]
    report = collect.federate_anomaly(members)
    by_name = {m["name"]: m for m in report["members"]}
    assert by_name["local"]["ok"] is True
    assert by_name["local"]["active"] == 1
    assert by_name["gone"]["ok"] is False and by_name["gone"]["error"]
    assert report["merged_from"] == ["local"]
    assert report["any_active"] is True
    row = report["active"][0]
    assert row["series"] == "serve_p99_ms.e"
    assert row["fleet_member"] == "local"
    assert row["mode"] == "step"


def test_federate_anomaly_all_quiet():
    report = collect.federate_anomaly([collect.Member("local", None)])
    assert report["any_active"] is False
    assert report["active"] == []
    assert report["members"][0]["active"] == 0


def test_benchcmp_gates_sentinel_keys():
    from predictionio_tpu.tools import benchcmp

    assert benchcmp.lower_is_better("key.journal_append_us")
    assert benchcmp.lower_is_better("key.anomaly_scan_ms")
