"""Binned-layout cache + transfer compression (VERDICT r3 item 2).

Retraining on unchanged events must not re-pay read->bin: the
compressed device layout persists under the bin cache keyed by the
event log's O(1) fingerprint, and the compressed wire form
(lo/hi-split indexes, uint8 value codes) must train to exactly the
same factors as the uncompressed one.
"""

import numpy as np
import pytest

from predictionio_tpu.ops import als as als_mod
from predictionio_tpu.ops.als import (
    ALSConfig,
    ALSTrainer,
    LayoutCacheMiss,
    SideLayout,
    compress_side,
)


def _coo(n=60_000, users=800, items=300, seed=3):
    rng = np.random.default_rng(seed)
    u = rng.integers(0, users, n)
    i = rng.integers(0, items, n)
    v = (1.0 + (rng.integers(0, 9, n) * 0.5)).astype(np.float64)  # 9 values
    return (u, i, v), users, items


CFG = ALSConfig(rank=8, iterations=3, block_size=512,
                compute_dtype="float32", cg_dtype="float32")


def test_compressed_layout_trains_identically(monkeypatch):
    """uint8 value codes + int16 indexes decode to the exact floats the
    uncompressed path streams — factors must match to float tolerance."""
    coo, users, items = _coo()
    f_coded = ALSTrainer(coo, users, items, CFG).run()

    def no_compress(sg, n_opposing):
        lo, hi = als_mod._split_idx(sg.idx)
        return SideLayout(
            idx_lo=lo, idx_hi=hi, val=sg.val,
            mask=sg.mask.astype(np.uint8),
            seg=sg.seg, counts=sg.counts, affine=None,
            row_block=sg.row_block, group_block=sg.group_block,
            groups_per_shard=sg.groups_per_shard, n_shards=sg.n_shards)

    monkeypatch.setattr(als_mod, "compress_side", no_compress)
    f_plain = ALSTrainer(coo, users, items, CFG).run()
    np.testing.assert_allclose(
        f_coded.user_factors, f_plain.user_factors, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        f_coded.item_factors, f_plain.item_factors, rtol=2e-5, atol=2e-5)


def test_compression_kicks_in_and_shrinks_the_wire():
    coo, users, items = _coo()
    (u, i, v) = coo
    from predictionio_tpu.ops.als import _build_side

    side = compress_side(_build_side(u, i, v, users, CFG, 1, None), items)
    assert side.val.dtype == np.uint8 and side.mask is None
    # 300-item vocab: the hi index byte is dropped from the wire
    assert side.idx_lo.dtype == np.uint16 and side.idx_hi is None
    # value ladder is 1.0..5.0 in 0.5 steps -> affine; the pads' 0.0
    # filler stays OUT of the codebook (it would break the ladder)
    assert side.affine == (1.0, 0.5)
    assert side.slot_bytes == 3  # vs 9 uncompressed (idx4+val4+mask1)

    # >255 distinct values: stays float32 + mask
    v_many = v + np.arange(len(v)) * 1e-6
    side2 = compress_side(_build_side(u, i, v_many, users, CFG, 1, None), items)
    assert side2.val.dtype == np.float32 and side2.mask is not None
    assert side2.affine is None

    # few distinct values but NOT an affine ladder: a table decode
    # would need a second gather per slot, so it stays float32 + mask
    v_nonaffine = np.where(v > 3.0, 7.25, v)
    side3 = compress_side(
        _build_side(u, i, v_nonaffine, users, CFG, 1, None), items)
    assert side3.affine is None and side3.val.dtype == np.float32




def test_layout_cache_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_BIN_CACHE_DIR", str(tmp_path))
    coo, users, items = _coo()

    t1 = ALSTrainer(coo, users, items, CFG, cache_key="fp-abc")
    assert t1.cache_hit is False
    f1 = t1.run()

    # second trainer: NO COO at all — everything from the cache
    t2 = ALSTrainer(None, None, None, CFG, cache_key="fp-abc")
    assert t2.cache_hit is True
    assert (t2.n_users, t2.n_items) == (users, items)
    assert t2.kept_user_entries == t1.kept_user_entries
    assert t2.transfer_bytes == t1.transfer_bytes
    f2 = t2.run()
    np.testing.assert_allclose(f1.user_factors, f2.user_factors,
                               rtol=1e-6, atol=1e-6)

    # a different data fingerprint is a MISS, loudly
    with pytest.raises(LayoutCacheMiss):
        ALSTrainer(None, None, None, CFG, cache_key="fp-other")

    # layout-affecting config changes the key too (a rank change alters
    # the auto seg_len planning)
    with pytest.raises(LayoutCacheMiss):
        ALSTrainer(None, None, None,
                   ALSConfig(rank=16, iterations=3, block_size=512),
                   cache_key="fp-abc")


def test_eventlog_fingerprint_tracks_data(tmp_path):
    from tests.test_eventlog_backend import _mk, ev

    st = _mk(tmp_path)
    st.events().init(1)
    fp0 = st.events().data_fingerprint(1)
    st.events().insert_batch([ev("u1")], 1)
    fp1 = st.events().data_fingerprint(1)
    assert fp0 != fp1
    # unchanged data -> unchanged fingerprint (the warm-retrain key)
    assert st.events().data_fingerprint(1) == fp1
    ids = st.events().insert_batch([ev("u2", 1)], 1)
    fp2 = st.events().data_fingerprint(1)
    assert fp2 != fp1
    st.events().delete(ids[0], 1)
    assert st.events().data_fingerprint(1) != fp2
    st.events().close()

def test_index_wire_split_round_trips_past_16_bits():
    """lo-uint16 (+ hi-uint8 when the vocab crosses 2^16) must
    recombine to the exact int32 indexes, and a >65535-vocab side must
    train to the same factors as the uncompressed layout."""
    from predictionio_tpu.ops.als import _split_idx

    idx = np.array([[0, 1, 65_535, 65_536, 70_001, (1 << 24) - 1]],
                   dtype=np.int32)
    lo, hi = _split_idx(idx)
    assert lo.dtype == np.uint16 and hi.dtype == np.uint8
    np.testing.assert_array_equal(
        lo.astype(np.int32) | (hi.astype(np.int32) << 16), idx)
    # small vocab: no hi stream
    lo2, hi2 = _split_idx(np.array([[3, 65_535]], np.int32))
    assert hi2 is None
    # 24-bit overflow is a loud error, never silent truncation (a real
    # ValueError: asserts vanish under -O)
    with pytest.raises(ValueError):
        _split_idx(np.array([[1 << 24]], np.int32))


def test_wide_vocab_trains_identically(monkeypatch):
    """A >2^16 opposing vocab engages the hi byte; decoded gathers must
    match the uncompressed path bit-for-bit (same solves)."""
    rng = np.random.default_rng(5)
    n, users, items = 20_000, 300, 70_000
    u = rng.integers(0, users, n)
    i = rng.integers(0, items, n)
    v = (1.0 + (rng.integers(0, 9, n) * 0.5)).astype(np.float64)
    cfg = ALSConfig(rank=4, iterations=1, block_size=512,
                    compute_dtype="float32", cg_dtype="float32")
    f_coded = ALSTrainer((u, i, v), users, items, cfg).run()

    def no_compress(sg, n_opposing):
        lo, hi = als_mod._split_idx(sg.idx)
        return SideLayout(
            idx_lo=lo, idx_hi=hi, val=sg.val,
            mask=sg.mask.astype(np.uint8),
            seg=sg.seg, counts=sg.counts, affine=None,
            row_block=sg.row_block, group_block=sg.group_block,
            groups_per_shard=sg.groups_per_shard, n_shards=sg.n_shards)

    monkeypatch.setattr(als_mod, "compress_side", no_compress)
    f_plain = ALSTrainer((u, i, v), users, items, cfg).run()
    np.testing.assert_allclose(
        f_coded.user_factors, f_plain.user_factors, rtol=2e-5, atol=2e-5)
