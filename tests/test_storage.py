"""Event store + metadata DAO behavior across backends
(ref specs: LEventsSpec.scala:21, PEventsSpec.scala:25 — but runnable
in-process, no HBase needed)."""

import datetime as dt

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import AccessKey, EngineInstance, Model
from predictionio_tpu.data.storage import UNSET, Storage, StorageError, set_storage
from predictionio_tpu.data import store

UTC = dt.timezone.utc


def make_storage(kind, tmp_path):
    if kind == "eventlog":
        from predictionio_tpu.native import native_available

        if not native_available("eventlog"):
            pytest.skip("C++ toolchain unavailable for the native eventlog backend")
    if kind == "memory":
        env = {"PIO_STORAGE_SOURCES_S_TYPE": "memory"}
    else:
        env = {
            "PIO_STORAGE_SOURCES_S_TYPE": kind,
            "PIO_STORAGE_SOURCES_S_PATH": str(tmp_path / "store"),
        }
    env.update(
        {
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S",
        }
    )
    return Storage.from_env(env)


@pytest.fixture(params=["memory", "localfs", "sqlite", "eventlog"])
def storage(request, tmp_path):
    return make_storage(request.param, tmp_path)


def ev(name="rate", uid="u1", iid="i1", minute=0, props=None):
    return Event(
        event=name,
        entity_type="user",
        entity_id=uid,
        target_entity_type="item" if iid else None,
        target_entity_id=iid,
        properties=props or {},
        event_time=dt.datetime(2026, 1, 1, 0, minute, tzinfo=UTC),
    )


def test_event_crud(storage):
    es = storage.events()
    es.init(1)
    eid = es.insert(ev(props={"rating": 5}), 1)
    got = es.get(eid, 1)
    assert got.event == "rate"
    assert got.properties.get("rating", int) == 5
    assert es.delete(eid, 1) is True
    assert es.get(eid, 1) is None
    assert es.delete(eid, 1) is False


def test_find_filters(storage):
    es = storage.events()
    es.init(1)
    es.insert(ev("rate", "u1", "i1", 0), 1)
    es.insert(ev("rate", "u2", "i2", 1), 1)
    es.insert(ev("buy", "u1", "i2", 2), 1)
    es.insert(ev("$set", "u1", None, 3, {"a": 1}), 1)

    assert len(es.find(1)) == 4
    assert [e.entity_id for e in es.find(1, event_names=["rate"])] == ["u1", "u2"]
    assert len(es.find(1, entity_id="u1")) == 3
    assert len(es.find(1, target_entity_id="i2")) == 2
    # target_entity_type=None means "no target entity" (UNSET = don't care)
    assert [e.event for e in es.find(1, target_entity_type=None)] == ["$set"]
    # time window is half-open [start, until)
    t1 = dt.datetime(2026, 1, 1, 0, 1, tzinfo=UTC)
    t2 = dt.datetime(2026, 1, 1, 0, 2, tzinfo=UTC)
    window = es.find(1, start_time=t1, until_time=t2)
    assert [e.event for e in window] == ["rate"]
    # limit + reversed
    newest = es.find(1, limit=1, reversed=True)
    assert newest[0].event == "$set"


def test_channel_isolation(storage):
    es = storage.events()
    es.init(1)
    es.init(1, channel_id=2)
    es.insert(ev("rate", "u1"), 1)
    es.insert(ev("buy", "u2"), 1, channel_id=2)
    assert [e.event for e in es.find(1)] == ["rate"]
    assert [e.event for e in es.find(1, channel_id=2)] == ["buy"]
    es.remove(1, channel_id=2)
    es.init(1, channel_id=2)
    assert es.find(1, channel_id=2) == []


def test_aggregate_properties_via_store(storage):
    es = storage.events()
    es.init(1)
    es.insert(ev("$set", "u1", None, 0, {"a": 1, "b": 2}), 1)
    es.insert(ev("$unset", "u1", None, 1, {"b": None}), 1)
    es.insert(ev("$set", "u2", None, 0, {"a": 9}), 1)
    es.insert(ev("$delete", "u2", None, 1), 1)
    props = es.aggregate_properties(1, "user")
    assert set(props) == {"u1"}
    assert props["u1"].to_dict() == {"a": 1}


def test_metadata_repos(storage):
    apps = storage.apps()
    app = apps.insert("myapp", "desc")
    assert app.id >= 1
    assert apps.get_by_name("myapp").id == app.id
    with pytest.raises(StorageError):
        apps.insert("myapp")

    keys = storage.access_keys()
    k = AccessKey.generate(app.id, events=["rate"])
    keys.insert(k)
    assert keys.get(k.key).appid == app.id
    assert len(k.key) == 64

    channels = storage.channels()
    ch = channels.insert("live", app.id)
    assert channels.get_by_app_id(app.id)[0].name == "live"
    with pytest.raises(StorageError):
        channels.insert("bad name!", app.id)


def test_engine_instances_latest_completed(storage):
    repo = storage.engine_instances()
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)

    def mk(i, status):
        return EngineInstance(
            id=f"id{i}", status=status,
            start_time=t0 + dt.timedelta(hours=i), end_time=t0 + dt.timedelta(hours=i + 1),
            engine_id="e", engine_version="1", engine_variant="v", engine_factory="f",
        )

    repo.insert(mk(0, "COMPLETED"))
    repo.insert(mk(1, "FAILED"))
    repo.insert(mk(2, "COMPLETED"))
    latest = repo.get_latest_completed("e", "1", "v")
    assert latest.id == "id2"
    assert repo.get_latest_completed("other", "1", "v") is None


def test_models_blob_roundtrip(storage):
    models = storage.models()
    models.insert(Model(id="m1", models=b"\x00\x01binary"))
    assert models.get("m1").models == b"\x00\x01binary"
    models.delete("m1")
    assert models.get("m1") is None


def test_localfs_survives_restart(tmp_path):
    s1 = make_storage("localfs", tmp_path)
    app = s1.apps().insert("persisted")
    s1.events().init(app.id)
    eid = s1.events().insert(ev(props={"x": 1}), app.id)
    deleted = s1.events().insert(ev("buy", "u9"), app.id)
    s1.events().delete(deleted, app.id)
    s1.models().insert(Model(id="m", models=b"blob"))

    # fresh client over the same directory replays to identical state
    s2 = make_storage("localfs", tmp_path)
    assert s2.apps().get_by_name("persisted").id == app.id
    events = s2.events().find(app.id)
    assert [e.event_id for e in events] == [eid]
    assert s2.models().get("m").models == b"blob"
    # sequence counter continues, no id reuse
    assert s2.apps().insert("second").id == app.id + 1


def test_public_store_api(memory_storage):
    app = memory_storage.apps().insert("shop")
    memory_storage.events().init(app.id)
    memory_storage.events().insert(ev("$set", "u1", None, 0, {"vip": True}), app.id)
    memory_storage.events().insert(ev("rate", "u1", "i1", 1, {"rating": 5}), app.id)

    assert len(store.find("shop")) == 2
    assert store.aggregate_properties("shop", "user")["u1"].get("vip", bool) is True
    latest = store.find_by_entity("shop", "user", "u1", event_names=["rate"], limit=1)
    assert latest[0].properties.get("rating", int) == 5
    with pytest.raises(StorageError):
        store.find("no-such-app")
    with pytest.raises(StorageError):
        store.find("shop", channel_name="nope")


def test_verify_all_data_objects(storage):
    assert storage.verify_all_data_objects() == {
        "METADATA": True, "EVENTDATA": True, "MODELDATA": True,
    }


def test_uninitialized_table_read_raises(storage):
    es = storage.events()
    with pytest.raises(StorageError, match="not initialized"):
        es.find(999)
    with pytest.raises(StorageError, match="not initialized"):
        es.insert(ev(), 999)


def test_repo_boundary_copies(storage):
    """Mutating a record after insert must not bypass update()."""
    repo = storage.engine_instances()
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    inst = EngineInstance(
        id="x", status="INIT", start_time=t0, end_time=t0,
        engine_id="e", engine_version="1", engine_variant="v", engine_factory="f",
    )
    repo.insert(inst)
    inst.status = "COMPLETED"  # not saved via update()
    assert repo.get("x").status == "INIT"
    repo.update(inst)
    assert repo.get("x").status == "COMPLETED"


def test_localfs_torn_final_line_recovered(tmp_path):
    s1 = make_storage("localfs", tmp_path)
    app = s1.apps().insert("torn")
    s1.events().init(app.id)
    s1.events().insert(ev(), app.id)
    # simulate a crash mid-append
    log_path = tmp_path / "store" / "events" / f"events_{app.id}.jsonl"
    with open(log_path, "a") as f:
        f.write('{"event": "rate", "entityTy')
    s2 = make_storage("localfs", tmp_path)
    assert len(s2.events().find(app.id)) == 1  # torn line dropped, rest intact


def test_localfs_cross_process_metadata_sync(tmp_path):
    """Two clients over one basedir: writes through one are visible to the
    other, and neither clobbers the other's records."""
    s1 = make_storage("localfs", tmp_path)
    s2 = make_storage("localfs", tmp_path)
    a1 = s1.apps().insert("from-one")
    a2 = s2.apps().insert("from-two")  # s2 must sync before allocating an id
    assert a2.id != a1.id
    assert s1.apps().get_by_name("from-two") is not None
    assert s2.apps().get_by_name("from-one") is not None
    s3 = make_storage("localfs", tmp_path)
    assert {a.name for a in s3.apps().get_all()} == {"from-one", "from-two"}


def test_find_columnar_matches_find(storage):
    """Dict-encoded columnar scans must agree with find() row-by-row on
    every backend (the native eventlog overrides the generic fallback)."""
    import numpy as np

    app = storage.apps().insert("columnar")
    storage.events().init(app.id)
    events = [
        ev("rate", "u1", "i1", 0, {"rating": 4.5}),
        ev("buy", "u2", "i2", 1),
        ev("rate", "u1", "i2", 2, {"rating": 2.0}),
        ev("view", "u3", "i1", 3),
        ev("rate", "u2", "i3", 4, {"rating": 1.0, "extra": {"nested": 1}}),
    ]
    storage.events().insert_batch(events, app.id)

    kwargs = dict(
        entity_type="user",
        event_names=["rate", "buy"],
        target_entity_type="item",
    )
    rows = storage.events().find(app.id, **kwargs)
    cols = storage.events().find_columnar(
        app.id, value_property="rating", **kwargs
    )
    assert len(cols) == len(rows) == 4
    for i, e in enumerate(rows):
        assert cols.entity_vocab[cols.entity_codes[i]] == e.entity_id
        assert cols.target_vocab[cols.target_codes[i]] == e.target_entity_id
        assert cols.names[cols.name_codes[i]] == e.event
        expected = e.properties.get_opt("rating")
        if expected is None:
            assert np.isnan(cols.values[i])
        else:
            assert cols.values[i] == expected
        epoch = dt.datetime(1970, 1, 1, tzinfo=UTC)
        assert cols.times_us[i] == (e.event_time - epoch) // dt.timedelta(
            microseconds=1
        )
    # time-window + value-less scans also agree
    t0 = events[0].event_time
    windowed = storage.events().find_columnar(
        app.id, start_time=t0, until_time=t0 + dt.timedelta(minutes=2),
        **kwargs,
    )
    assert len(windowed) == 2
    assert np.isnan(windowed.values).all()  # no value_property requested


def test_find_columnar_no_target(storage):
    """Events without a target id get code -1 in every backend."""
    app = storage.apps().insert("columnar2")
    storage.events().init(app.id)
    storage.events().insert_batch(
        [
            Event(event="$set", entity_type="user", entity_id="u9",
                  properties={"a": 1},
                  event_time=dt.datetime(2026, 3, 1, tzinfo=UTC)),
            ev("rate", "u9", "i1", 1, {"rating": 3.0}),
        ],
        app.id,
    )
    cols = storage.events().find_columnar(app.id, entity_type="user")
    no_target = [i for i in range(len(cols)) if cols.target_codes[i] < 0]
    assert len(no_target) == 1
    assert cols.names[cols.name_codes[no_target[0]]] == "$set"


def test_insert_columnar_roundtrip(storage):
    """Columnar bulk ingest (the PEvents.write role) must produce events
    the row-level API reads back identically, on every backend (native
    C++ packer for eventlog, Event-object fallback elsewhere)."""
    import numpy as np
    from predictionio_tpu.data.storage import EventColumns

    app = storage.apps().insert("bulkingest")
    storage.events().init(app.id)
    cols = EventColumns(
        entity_codes=np.array([0, 1, 0, 2], np.int32),
        target_codes=np.array([0, 1, -1, 0], np.int32),   # row 2: no target
        name_codes=np.array([0, 0, 1, 0], np.int32),
        values=np.array([4.5, 2.0, np.nan, np.nan], np.float64),
        times_us=np.array([1_000_000, 2_000_000, 3_000_000, 4_000_000], np.int64),
        entity_vocab=["alice", "bob", "carol"],
        target_vocab=["iphone", "droid"],
        names=["rate", "$set"],
    )
    n = storage.events().insert_columnar(
        cols, app.id, entity_type="user", target_entity_type="item",
        value_property="rating",
    )
    assert n == 4
    got = storage.events().find(app.id)
    assert len(got) == 4
    assert [e.entity_id for e in got] == ["alice", "bob", "alice", "carol"]
    assert got[0].target_entity_id == "iphone"
    assert got[0].properties.get("rating") == 4.5
    assert got[1].properties.get("rating") == 2.0
    assert got[2].target_entity_id is None and got[2].target_entity_type is None
    assert len(got[2].properties) == 0      # NaN value -> no property
    assert got[2].event == "$set"
    assert got[0].event_time == dt.datetime(1970, 1, 1, 0, 0, 1, tzinfo=UTC)
    # ids are fresh and unique; get() resolves them
    ids = {e.event_id for e in got}
    assert len(ids) == 4
    e = storage.events().get(got[3].event_id, app.id)
    assert e.entity_id == "carol"
    # and the columnar reader round-trips the bulk write
    back = storage.events().find_columnar(
        app.id, value_property="rating", event_names=["rate"]
    )
    assert len(back) == 3
    assert sorted(
        back.entity_vocab[c] for c in back.entity_codes
    ) == ["alice", "bob", "carol"]
