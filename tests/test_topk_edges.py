"""ops/topk edge-case pins (index-subsystem satellite).

The brute-force scorer is the equivalence REFERENCE for the whole
``predictionio_tpu/index`` subsystem (the exact Pallas backend and the
IVF recall gate are both judged against it), so its edges — ``k >=
n_items``, exclusion lists longer than ``max_exclude``, empty tables,
empty batches — are pinned here on BOTH placement routes. The two
routes must behave identically: the index falls back between them
freely.
"""

import numpy as np
import pytest

from predictionio_tpu.ops.topk import NEG_INF, TopKScorer

RNG = np.random.default_rng(7)
FACTORS = RNG.normal(size=(7, 4)).astype(np.float32)
USER = RNG.normal(size=(4,)).astype(np.float32)

PLACEMENTS = ("host", "device")


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_k_beyond_n_items_clamps(placement):
    sc = TopKScorer(FACTORS, placement=placement)
    scores, idx = sc.score(USER, 50)
    assert scores.shape == (1, 7) and idx.shape == (1, 7)
    # all 7 items present, ranked descending
    assert sorted(idx[0].tolist()) == list(range(7))
    assert np.all(np.diff(scores[0]) <= 1e-6)


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_k_zero_and_empty_batch(placement):
    sc = TopKScorer(FACTORS, placement=placement)
    scores, idx = sc.score(USER, 0)
    assert scores.shape == (1, 0) and idx.shape == (1, 0)
    scores, idx = sc.score(np.zeros((0, 4), np.float32), 5)
    assert scores.shape == (0, 5) and idx.shape == (0, 5)


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_exclude_longer_than_max_drops_oldest_first(placement):
    """The documented cap semantics: entries beyond ``max_exclude``
    drop OLDEST first — the newest (rightmost) ids stay excluded."""
    sc = TopKScorer(FACTORS, max_exclude=2, placement=placement)
    excl = np.array([0, 1, 2, 3], np.int32)   # only 2, 3 survive the cap
    scores, idx = sc.score(USER, 7, excl)
    # with k == n_items every slot fills: excluded ids may appear, but
    # only at NEG_INF — live candidates are the score-filtered set
    kept = {int(i) for s, i in zip(scores[0], idx[0]) if s > float(NEG_INF)}
    assert 2 not in kept and 3 not in kept
    assert {0, 1} <= kept   # dropped-oldest ids are back in play


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_out_of_range_and_negative_excludes_dropped(placement):
    """Stale blacklists (catalog shrank) and -1 padding must be
    silently dropped — identically on both routes."""
    sc = TopKScorer(FACTORS, placement=placement)
    base_s, base_i = sc.score(USER, 3)
    s, i = sc.score(USER, 3, np.array([99, -5, -1], np.int32))
    np.testing.assert_allclose(s, base_s, rtol=1e-6)
    assert np.array_equal(i, base_i)


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_empty_item_table(placement):
    sc = TopKScorer(np.zeros((0, 4), np.float32), placement=placement)
    scores, idx = sc.score(USER, 5)
    assert scores.shape == (1, 0) and idx.shape == (1, 0)
    # exclusions against an empty table must not crash either
    scores, idx = sc.score(USER, 5, np.array([0, 3], np.int32))
    assert scores.shape == (1, 0)


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_single_item_table(placement):
    sc = TopKScorer(FACTORS[:1], placement=placement)
    scores, idx = sc.score(USER, 5)
    assert scores.shape == (1, 1) and int(idx[0, 0]) == 0


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_masked_fewer_candidates_than_k(placement):
    """Unfillable slots come back at NEG_INF — the contract callers
    (and the index subsystem) filter by."""
    sc = TopKScorer(FACTORS, placement=placement)
    mask = np.zeros(7, bool)
    mask[2] = True
    scores, idx = sc.score_masked(USER, 3, mask)
    assert int(idx[0, 0]) == 2 and scores[0, 0] > float(NEG_INF)
    assert np.all(scores[0, 1:] <= float(NEG_INF))


def test_host_tie_order_is_deterministic_and_matches_device():
    """Exact ties rank by LOWEST item index on both routes (lax.top_k's
    documented preference; the host route canonicalizes the partition
    before its stable sort) — ties away from the k-th boundary, where
    membership itself is determined."""
    dominant = (USER / np.linalg.norm(USER)).astype(np.float32)
    table = 0.01 * FACTORS
    table = np.vstack([table[:2], 5.0 * dominant[None, :], table[2:],
                       5.0 * dominant[None, :]])   # rows 2 and 8 tie on top
    host_s, host_i = TopKScorer(table, placement="host").score(USER, 4)
    dev_s, dev_i = TopKScorer(table, placement="device").score(USER, 4)
    assert host_i[0, 0] == dev_i[0, 0] == 2   # lowest tied index first
    assert host_i[0, 1] == dev_i[0, 1] == 8
    np.testing.assert_allclose(host_s, dev_s, rtol=1e-5, atol=1e-6)


def test_host_and_device_routes_agree():
    """No-ties random data: both routes return identical rankings (the
    index backend falls back between them freely, so they must be
    interchangeable)."""
    users = RNG.normal(size=(5, 4)).astype(np.float32)
    excl = np.array([[1, 4], [-1, -1], [0, 2], [6, -1], [3, 3]], np.int32)
    host = TopKScorer(FACTORS, placement="host")
    dev = TopKScorer(FACTORS, placement="device")
    hs, hi = host.score(users, 4, excl)
    ds, di = dev.score(users, 4, excl)
    np.testing.assert_allclose(hs, ds, rtol=1e-5, atol=1e-6)
    assert np.array_equal(hi, di)
