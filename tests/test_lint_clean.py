"""Tier-1 gate: the committed tree carries zero unsuppressed graftlint
findings.

This is the CI wiring for graftlint (mirrors `bin/lint`): any JT01-JT06
finding — or an unjustified suppression (GL00) — fails the tier-1 run
with the exact file:line so the offending change is one click away.
Uses the in-process API (no subprocess) to stay cheap; graftlint never
imports jax, so this collects and runs in milliseconds.
"""

from __future__ import annotations

from pathlib import Path

from predictionio_tpu.tools.lint import lint_paths

PACKAGE = Path(__file__).resolve().parents[1] / "predictionio_tpu"


def test_tree_has_no_unsuppressed_findings():
    findings = lint_paths([str(PACKAGE)])
    assert not findings, (
        f"{len(findings)} graftlint finding(s) — fix them or suppress "
        "with a justified `# graftlint: disable=RULE — why` comment:\n"
        + "\n".join(str(f) for f in findings)
    )
