"""Tier-1 gate: the committed tree carries zero unsuppressed graftlint
findings.

This is the CI wiring for graftlint (mirrors `bin/lint`): any JT01-JT20
finding — or an unjustified suppression (GL00) — fails the tier-1 run
with the exact file:line so the offending change is one click away.
Uses the in-process API (no subprocess) to stay cheap; graftlint never
imports jax. The project pass (JT18-JT20) shares the per-file pass's
AST cache, so the two gates together parse each module once.
"""

from __future__ import annotations

import time
from pathlib import Path

from predictionio_tpu.tools.lint import lint_paths, lint_project

PACKAGE = Path(__file__).resolve().parents[1] / "predictionio_tpu"

#: generous multiple of the observed ~7 s dev-container wall clock —
#: the ISSUE-16 budget: a super-linear regression in the cross-module
#: analysis must fail loudly, not silently tax every commit
PROJECT_PASS_BUDGET_SEC = 10.0


def test_tree_has_no_unsuppressed_findings():
    findings = lint_paths([str(PACKAGE)])
    assert not findings, (
        f"{len(findings)} graftlint finding(s) — fix them or suppress "
        "with a justified `# graftlint: disable=RULE — why` comment:\n"
        + "\n".join(str(f) for f in findings)
    )


def test_tree_is_clean_under_project_mode():
    """The whole-program concurrency pass (JT18-JT20: unguarded shared
    mutation, lock-order cycles, check-then-act splits) over the whole
    package: any future unguarded mutation of a lock-disciplined
    attribute fails tier-1 here, with the race's file:line."""
    t0 = time.perf_counter()
    findings, files = lint_project([str(PACKAGE)])
    elapsed = time.perf_counter() - t0
    assert not findings, (
        f"{len(findings)} graftlint --project finding(s) — fix the "
        "race/deadlock or justify the lock-free design with a "
        "`# graftlint: disable=RULE — why` comment:\n"
        + "\n".join(str(f) for f in findings)
    )
    assert files > 0
    assert elapsed < PROJECT_PASS_BUDGET_SEC, (
        f"project lint took {elapsed:.1f}s over {files} files — the "
        f"< {PROJECT_PASS_BUDGET_SEC:.0f}s budget protects every "
        "commit's tier-1 wall clock; profile the cross-module pass"
    )
