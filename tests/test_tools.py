"""Tools layer tests: commands, CLI, import/export, admin API, dashboard.

Reference coverage model: tools/src/test/.../admin/AdminAPISpec.scala
(route-level) plus console behaviors asserted in App.scala/AccessKey.scala
docstrings (SURVEY.md §2.7).
"""

import datetime as dt
import json
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import EvaluationInstance
from predictionio_tpu.tools import commands, eventdata
from predictionio_tpu.tools.admin import AdminServer
from predictionio_tpu.tools.cli import main as cli_main
from predictionio_tpu.tools.commands import CommandError
from predictionio_tpu.tools.dashboard import DashboardServer

UTC = dt.timezone.utc


def http(method, url, body=None):
    req = urllib.request.Request(
        url, method=method,
        data=None if body is None else json.dumps(body).encode(),
    )
    try:
        with urllib.request.urlopen(req) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw.startswith(b"{") or raw.startswith(b"[") else raw
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else {}


class TestCommands:
    def test_app_lifecycle(self, memory_storage):
        info = commands.app_new("myapp", "desc", memory_storage)
        assert info.app.name == "myapp"
        assert len(info.access_keys) == 1
        assert len(info.access_keys[0].key) == 64
        # duplicate name rejected (ref: App.scala:37)
        with pytest.raises(CommandError):
            commands.app_new("myapp", storage=memory_storage)
        assert [i.app.name for i in commands.app_list(memory_storage)] == ["myapp"]
        # event store was initialized: inserts work
        memory_storage.events().insert(
            Event(event="e", entity_type="user", entity_id="u"), info.app.id)
        commands.app_delete("myapp", memory_storage)
        assert commands.app_list(memory_storage) == []
        with pytest.raises(CommandError):
            commands.app_show("myapp", memory_storage)

    def test_app_data_delete(self, memory_storage):
        info = commands.app_new("a1", storage=memory_storage)
        memory_storage.events().insert(
            Event(event="e", entity_type="user", entity_id="u"), info.app.id)
        assert len(memory_storage.events().find(info.app.id)) == 1
        commands.app_data_delete("a1", storage=memory_storage)
        assert memory_storage.events().find(info.app.id) == []

    def test_channels(self, memory_storage):
        info = commands.app_new("capp", storage=memory_storage)
        ch = commands.channel_new("capp", "mobile", memory_storage)
        assert ch.name == "mobile"
        with pytest.raises(CommandError):
            commands.channel_new("capp", "mobile", memory_storage)
        memory_storage.events().insert(
            Event(event="e", entity_type="user", entity_id="u"), info.app.id, ch.id)
        assert len(memory_storage.events().find(info.app.id, channel_id=ch.id)) == 1
        commands.app_data_delete("capp", "mobile", memory_storage)
        assert memory_storage.events().find(info.app.id, channel_id=ch.id) == []
        commands.channel_delete("capp", "mobile", memory_storage)
        assert commands.app_show("capp", memory_storage).channels == []

    def test_accesskeys(self, memory_storage):
        commands.app_new("kapp", storage=memory_storage)
        key = commands.accesskey_new("kapp", ["rate", "buy"], memory_storage)
        assert sorted(key.events) == ["buy", "rate"]
        keys = commands.accesskey_list("kapp", memory_storage)
        assert len(keys) == 2  # default + new
        commands.accesskey_delete(key.key, memory_storage)
        assert len(commands.accesskey_list("kapp", memory_storage)) == 1
        with pytest.raises(CommandError):
            commands.accesskey_delete("nope", memory_storage)

    def test_status(self, memory_storage):
        assert commands.status(memory_storage) == {
            "METADATA": True, "EVENTDATA": True, "MODELDATA": True}


class TestImportExport:
    def test_round_trip(self, memory_storage, tmp_path):
        info = commands.app_new("ioapp", storage=memory_storage)
        for n in range(5):
            memory_storage.events().insert(
                Event(event="rate", entity_type="user", entity_id=f"u{n}",
                      target_entity_type="item", target_entity_id="i1",
                      properties={"rating": n},
                      event_time=dt.datetime(2026, 1, 1, 0, n, tzinfo=UTC)),
                info.app.id)
        out = tmp_path / "events.jsonl"
        assert eventdata.export_events("ioapp", str(out), storage=memory_storage) == 5
        assert len(out.read_text().strip().splitlines()) == 5

        commands.app_new("ioapp2", storage=memory_storage)
        assert eventdata.import_events("ioapp2", str(out), storage=memory_storage) == 5
        app2 = memory_storage.apps().get_by_name("ioapp2")
        events = memory_storage.events().find(app2.id)
        assert {e.entity_id for e in events} == {f"u{n}" for n in range(5)}

    def test_parquet_round_trip(self, memory_storage, tmp_path):
        info = commands.app_new("pqapp", storage=memory_storage)
        for n in range(4):
            memory_storage.events().insert(
                Event(event="rate", entity_type="user", entity_id=f"u{n}",
                      target_entity_type="item", target_entity_id="i1",
                      properties={"rating": float(n), "tags_test": ["a", "b"]},
                      tags=("t1", "t2"),
                      event_time=dt.datetime(2026, 1, 1, 0, n, tzinfo=UTC)),
                info.app.id)
        # no target / no properties event too
        memory_storage.events().insert(
            Event(event="$set", entity_type="user", entity_id="u9",
                  properties={"plan": "pro"},
                  event_time=dt.datetime(2026, 1, 2, tzinfo=UTC)),
            info.app.id)
        out = tmp_path / "events.parquet"
        assert eventdata.export_events("pqapp", str(out), storage=memory_storage) == 5

        commands.app_new("pqapp2", storage=memory_storage)
        assert eventdata.import_events("pqapp2", str(out), storage=memory_storage) == 5
        app2 = memory_storage.apps().get_by_name("pqapp2")
        events = {e.entity_id: e for e in memory_storage.events().find(app2.id)}
        assert events["u2"].properties.get("rating") == 2.0
        assert events["u2"].properties.get("tags_test") == ["a", "b"]
        assert events["u2"].tags == ("t1", "t2")
        assert events["u9"].event == "$set"
        assert events["u9"].target_entity_type is None
        assert events["u9"].event_time == dt.datetime(2026, 1, 2, tzinfo=UTC)

    def test_import_invalid_line(self, memory_storage, tmp_path):
        commands.app_new("bad", storage=memory_storage)
        f = tmp_path / "bad.jsonl"
        f.write_text('{"event": "e", "entityType": "user", "entityId": "u"}\n'
                     '{"event": "$set"}\n')
        with pytest.raises(ValueError, match="bad.jsonl:2"):
            eventdata.import_events("bad", str(f), storage=memory_storage)


_RUN_ARGS = None


def _run_target(argv):
    global _RUN_ARGS
    _RUN_ARGS = list(argv)
    return 0


class TestCLI:
    def test_app_and_template_commands(self, memory_storage, tmp_path, capsys):
        assert cli_main(["app", "new", "cliapp"]) == 0
        out = capsys.readouterr().out
        assert "Access Key:" in out
        assert cli_main(["app", "list"]) == 0
        assert cli_main(["status"]) == 0
        # duplicate app -> exit 1 with error message
        assert cli_main(["app", "new", "cliapp"]) == 1
        assert "already exists" in capsys.readouterr().err
        # template scaffold
        assert cli_main(["template", "list"]) == 0
        tdir = str(tmp_path / "eng")
        assert cli_main(["template", "get", "vanilla", tdir]) == 0
        variant = json.load(open(f"{tdir}/engine.json"))
        assert variant["engineFactory"].endswith("vanilla_engine")

    def test_lint_command(self, tmp_path, capsys):
        # clean file -> exit 0 with the summary line
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert cli_main(["lint", str(clean)]) == 0
        assert "clean" in capsys.readouterr().out
        # a jit'd host sync -> exit 1, finding on stdout
        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "import jax\n\n@jax.jit\ndef f(x):\n    return float(x)\n"
        )
        assert cli_main(["lint", str(dirty)]) == 1
        out = capsys.readouterr().out
        assert "JT01" in out and "dirty.py" in out
        assert cli_main(["lint", "--list-rules"]) == 0
        assert "JT06" in capsys.readouterr().out
        # bad path -> exit 2, distinguishable from "findings found" (1)
        assert cli_main(["lint", str(tmp_path / "missing")]) == 2
        # no args -> lints the installed package from any cwd
        import os
        old = os.getcwd()
        os.chdir(str(tmp_path))
        try:
            assert cli_main(["lint"]) == 0
        finally:
            os.chdir(old)
        assert "clean" in capsys.readouterr().out

    def test_run_command(self, memory_storage, tmp_path, capsys):
        # dotted callable: gets passthrough argv, return value is exit code
        import tests.test_tools as me
        assert cli_main(["run", "tests.test_tools._run_target", "a", "b"]) == 0
        assert me._RUN_ARGS == ["a", "b"]
        # bare module executed as __main__ (prints the platform string)
        assert cli_main(["run", "platform"]) == 0
        assert capsys.readouterr().out.strip()

    def test_build_train_via_cli(self, memory_storage, tmp_path, capsys):
        tdir = str(tmp_path / "eng")
        cli_main(["template", "get", "vanilla", tdir])
        ej = f"{tdir}/engine.json"
        assert cli_main(["build", "--engine-json", ej]) == 0
        assert cli_main(["train", "--engine-json", ej]) == 0
        assert "COMPLETED" in capsys.readouterr().out
        manifests = memory_storage.engine_manifests().get_all()
        assert len(manifests) == 1
        instances = memory_storage.engine_instances().get_all()
        assert instances and instances[0].status == "COMPLETED"


class TestAdminServer:
    @pytest.fixture()
    def admin(self, memory_storage):
        server = AdminServer(storage=memory_storage, host="127.0.0.1", port=0)
        server.start()
        yield f"http://127.0.0.1:{server.port}"
        server.stop()

    def test_routes(self, admin, memory_storage):
        assert http("GET", f"{admin}/")[1] == {"status": "alive"}
        status, body = http("POST", f"{admin}/cmd/app", {"name": "adminapp"})
        assert status == 200 and body["name"] == "adminapp"
        assert body["accessKeys"]
        # duplicate -> 409
        assert http("POST", f"{admin}/cmd/app", {"name": "adminapp"})[0] == 409
        status, body = http("GET", f"{admin}/cmd/app")
        assert [a["name"] for a in body["apps"]] == ["adminapp"]
        # wipe data then delete
        assert http("DELETE", f"{admin}/cmd/app/adminapp/data")[0] == 200
        assert http("DELETE", f"{admin}/cmd/app/adminapp")[0] == 200
        assert http("GET", f"{admin}/cmd/app")[1]["apps"] == []
        assert http("DELETE", f"{admin}/cmd/app/ghost")[0] == 404
        assert http("POST", f"{admin}/cmd/app", {"nope": 1})[0] == 400


class TestDashboard:
    def test_listing_and_results(self, memory_storage):
        memory_storage.evaluation_instances().insert(EvaluationInstance(
            id="ev1", status="EVALCOMPLETED",
            start_time=dt.datetime(2026, 1, 1, tzinfo=UTC),
            end_time=dt.datetime(2026, 1, 1, 1, tzinfo=UTC),
            evaluation_class="my.Eval", batch="b1",
            evaluator_results="best: x",
            evaluator_results_html="<html>r</html>",
            evaluator_results_json='{"best": "x"}',
        ))
        server = DashboardServer(storage=memory_storage, host="127.0.0.1", port=0)
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            status, body = http("GET", f"{base}/")
            assert status == 200 and b"ev1" in body
            assert http("GET", f"{base}/engine_instances/ev1/evaluator_results.txt")[1] == b"best: x"
            assert http("GET", f"{base}/engine_instances/ev1/evaluator_results.json")[1] == {"best": "x"}
            assert http("GET", f"{base}/engine_instances/ev1/evaluator_results.html")[1] == b"<html>r</html>"
            assert http("GET", f"{base}/engine_instances/ghost/evaluator_results.txt")[0] == 404
        finally:
            server.stop()


class TestTemplateScaffold:
    def test_get_materializes_editable_source(self, memory_storage, tmp_path,
                                              capsys):
        """`pio template get` must produce a WORKING project whose source
        the user can edit before training (ref: Template.scala:226-415
        materializes a renamed source tree)."""
        import sys

        from predictionio_tpu.data.event import Event

        app = memory_storage.apps().insert("scaffold")
        memory_storage.events().init(app.id)
        events = [
            Event(event="buy", entity_type="user", entity_id=f"u{k % 6}",
                  target_entity_type="item", target_entity_id=f"i{k % 4}")
            for k in range(40)
        ]
        memory_storage.events().insert_batch(events, app.id)

        tdir = tmp_path / "myreco"
        assert cli_main(["template", "get", "recommendation", str(tdir)]) == 0
        src_path = tdir / "recommendation_engine.py"
        assert src_path.exists() and (tdir / "README.md").exists()

        # the user EDITS the scaffolded source: different buy rating
        src = src_path.read_text()
        assert "buy_rating: float = 4.0" in src
        src_path.write_text(
            src.replace("buy_rating: float = 4.0", "buy_rating: float = 2.5")
        )
        # and fills the variant params
        ej = tdir / "engine.json"
        variant = json.load(open(ej))
        assert variant["engineFactory"] == "recommendation_engine.recommendation_engine"
        variant["datasource"] = {"params": {"app_name": "scaffold"}}
        variant["algorithms"] = [
            {"name": "als", "params": {"rank": 4, "num_iterations": 2,
                                       "block_size": 8}}
        ]
        json.dump(variant, open(ej, "w"))

        assert cli_main(["train", "--engine-json", str(ej)]) == 0
        assert "COMPLETED" in capsys.readouterr().out
        # the edited project-local module was loaded (path-keyed, never
        # the installed package nor another project's same-named file)
        mod = next(
            m for k, m in sys.modules.items()
            if k.startswith("_pio_project_")
            and getattr(m, "__file__", None) == str(src_path)
        )
        assert mod.RecoDataSourceParams().buy_rating == 2.5
        inst = memory_storage.engine_instances().get_all()[0]
        assert inst.engine_factory.startswith("recommendation_engine.")

        # a SECOND project with the same module name must not collide
        tdir2 = tmp_path / "other"
        assert cli_main(["template", "get", "recommendation", str(tdir2)]) == 0
        from predictionio_tpu.workflow.variant import EngineVariant

        v2 = EngineVariant.load(str(tdir2 / "engine.json"))
        engine2 = v2.create_engine()
        ds_cls = next(iter(engine2.data_source_classes.values()))
        # unedited copy keeps the 4.0 default even though project 1's
        # edited 2.5 version is already loaded in this process
        assert ds_cls.__module__ != mod.__name__
        import inspect as _inspect

        assert _inspect.getmodule(ds_cls).RecoDataSourceParams().buy_rating == 4.0


class TestColumnarImport:
    """Parquet files with a pure interaction shape bulk-load through the
    columnar path; anything richer falls back to the row path — both
    must land identical events."""

    def _write_ratings_parquet(self, path, n=50):
        import numpy as np

        from predictionio_tpu.tools.eventdata import _write_parquet

        rng = np.random.default_rng(4)
        dicts = [
            {
                "event": "rate" if k % 3 else "buy",
                "entityType": "user",
                "entityId": f"u{rng.integers(8)}",
                "targetEntityType": "item",
                "targetEntityId": f"i{rng.integers(5)}",
                "properties": {"rating": float(k % 5) + 0.5} if k % 3 else None,
                "eventTime": f"2026-01-01T00:{k % 60:02d}:00+00:00",
            }
            for k in range(n)
        ]
        for d in dicts:
            if d["properties"] is None:
                del d["properties"]
        _write_parquet(path, dicts)
        return dicts

    def test_interaction_parquet_takes_columnar_path(self, memory_storage,
                                                     tmp_path, monkeypatch):
        from predictionio_tpu.tools import eventdata

        app = memory_storage.apps().insert("colimp")
        memory_storage.events().init(app.id)
        path = str(tmp_path / "ratings.parquet")
        dicts = self._write_ratings_parquet(path)

        # prove the fast path ran (row path would call insert_batch with
        # Event objects built from dicts)
        spy = {"columnar": 0}
        real = memory_storage.events().insert_columnar

        def counting(*a, **kw):
            spy["columnar"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(memory_storage.events(), "insert_columnar", counting)
        n = eventdata.import_events("colimp", path, storage=memory_storage)
        assert n == len(dicts) and spy["columnar"] == 1

        got = memory_storage.events().find(app.id)
        assert len(got) == len(dicts)
        want = {
            (d["event"], d["entityId"], d["targetEntityId"],
             d.get("properties", {}).get("rating"))
            for d in dicts
        }
        have = {
            (e.event, e.entity_id, e.target_entity_id,
             e.properties.get_opt("rating"))
            for e in got
        }
        assert have == want

    def test_rich_properties_fall_back_to_row_path(self, memory_storage,
                                                   tmp_path):
        from predictionio_tpu.tools import eventdata
        from predictionio_tpu.tools.eventdata import _write_parquet

        app = memory_storage.apps().insert("rowimp")
        memory_storage.events().init(app.id)
        path = str(tmp_path / "rich.parquet")
        _write_parquet(path, [
            {
                "event": "$set", "entityType": "item", "entityId": "i1",
                "properties": {"categories": ["a", "b"], "price": 9.5},
                "eventTime": "2026-01-01T00:00:00+00:00",
            },
            {
                "event": "view", "entityType": "user", "entityId": "u1",
                "targetEntityType": "item", "targetEntityId": "i1",
                "eventTime": "2026-01-01T00:01:00+00:00",
            },
        ])
        n = eventdata.import_events("rowimp", path, storage=memory_storage)
        assert n == 2
        got = memory_storage.events().find(app.id)
        assert got[0].properties.get_opt("categories") == ["a", "b"]
        assert got[1].event == "view"

    def test_columnar_rejects_invalid_events_via_row_path(self, memory_storage,
                                                          tmp_path):
        """A shape-conforming file with INVALID events must not bulk-load:
        the fast path declines and the row path raises with position."""
        from predictionio_tpu.tools import eventdata
        from predictionio_tpu.tools.eventdata import _write_parquet

        commands.app_new("badimp", storage=memory_storage)
        path = str(tmp_path / "bad.parquet")
        _write_parquet(path, [
            {   # reserved event WITH a target: validation must reject
                "event": "$set", "entityType": "user", "entityId": "u1",
                "targetEntityType": "item", "targetEntityId": "i1",
                "eventTime": "2026-01-01T00:00:00+00:00",
            },
        ])
        with pytest.raises(ValueError, match="bad.parquet:1"):
            eventdata.import_events("badimp", path, storage=memory_storage)

    def test_columnar_handles_mixed_no_target_rows(self, memory_storage,
                                                   tmp_path):
        from predictionio_tpu.tools import eventdata
        from predictionio_tpu.tools.eventdata import _write_parquet

        app = commands.app_new("miximp", storage=memory_storage).app
        path = str(tmp_path / "mix.parquet")
        _write_parquet(path, [
            {"event": "view", "entityType": "user", "entityId": "u1",
             "targetEntityType": "item", "targetEntityId": "i1",
             "eventTime": "2026-01-01T00:00:00+00:00"},
            {"event": "login", "entityType": "user", "entityId": "u2",
             "eventTime": "2026-01-01T00:01:00+00:00"},
        ])
        assert eventdata.import_events("miximp", path, storage=memory_storage) == 2
        got = {e.entity_id: e for e in memory_storage.events().find(app.id)}
        assert got["u1"].target_entity_id == "i1"
        assert got["u2"].target_entity_id is None
        assert got["u2"].target_entity_type is None


class TestBenchCompare:
    """`pio bench-compare` over the checked-in fixture trajectory
    (tests/data/bench): per-metric deltas, direction-aware verdicts,
    exit codes."""

    FIXTURES = sorted(
        str(p) for p in
        (Path(__file__).parent / "data" / "bench").glob("BENCH_r*.json"))

    def test_load_metrics_extracts_headline_and_detail(self):
        from predictionio_tpu.tools import benchcmp

        got = benchcmp.load_metrics(self.FIXTURES[0])
        assert got["als_ml20m_rating_updates_per_sec_per_chip"] == 60000000.0
        assert got["detail.serve_p50_ms"] == 1.0
        assert got["detail.n_users"] == 138000

    def test_direction_inference(self):
        from predictionio_tpu.tools import benchcmp

        assert benchcmp.lower_is_better("detail.serve_p50_ms")
        assert benchcmp.lower_is_better("detail.elapsed_sec")
        assert not benchcmp.lower_is_better("detail.serve_qps")
        assert not benchcmp.lower_is_better(
            "als_ml20m_rating_updates_per_sec_per_chip")

    def test_within_tolerance_passes(self, capsys):
        from predictionio_tpu.tools import benchcmp

        # r01 -> r02: every delta is under 10%
        rc = benchcmp.run(self.FIXTURES[:2], tolerance_pct=10.0)
        assert rc == 0
        out = capsys.readouterr().out
        assert "no regressions beyond tolerance" in out

    def test_regression_beyond_tolerance_fails(self, capsys):
        from predictionio_tpu.tools import benchcmp

        # r02 -> r03: throughput -28%, latency +47%/+40%
        rc = benchcmp.run(self.FIXTURES, tolerance_pct=10.0)
        assert rc == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out
        assert "als_ml20m_rating_updates_per_sec_per_chip" in out
        assert "detail.serve_p50_ms" in out
        # qps went UP 4%: within tolerance, not printed as a verdict
        assert "detail.serve_qps:" not in out

    def test_improvement_is_reported_not_failed(self, capsys):
        from predictionio_tpu.tools import benchcmp

        # reversed trajectory: r03 -> r02 is an improvement
        rc = benchcmp.run([self.FIXTURES[2], self.FIXTURES[1]],
                          tolerance_pct=10.0)
        assert rc == 0
        assert "IMPROVED" in capsys.readouterr().out

    def test_config_change_is_flagged_but_not_a_regression(self, tmp_path,
                                                           capsys):
        import json as _json

        from predictionio_tpu.tools import benchcmp

        doc = _json.loads(Path(self.FIXTURES[1]).read_text())
        doc["parsed"]["detail"]["rank"] = 128
        changed = tmp_path / "BENCH_r99.json"
        changed.write_text(_json.dumps(doc))
        rc = benchcmp.run([self.FIXTURES[0], str(changed)],
                          tolerance_pct=10.0)
        assert rc == 0
        assert "CONFIG-CHANGED" in capsys.readouterr().out

    def test_needs_two_files(self, capsys):
        from predictionio_tpu.tools import benchcmp

        assert benchcmp.run(self.FIXTURES[:1]) == 2

    def test_cli_entrypoint(self, capsys):
        from predictionio_tpu.tools.cli import main

        rc = main(["bench-compare", "--tolerance", "10",
                   *self.FIXTURES[1:]])
        assert rc == 1
        assert "bench-compare:" in capsys.readouterr().out

    def test_rounds_without_metrics_are_skipped(self, tmp_path, capsys):
        # a round whose headline failed to parse (empty `parsed`, like
        # the real BENCH_r04.json) must not become the baseline
        import json as _json

        from predictionio_tpu.tools import benchcmp

        empty = tmp_path / "BENCH_r98.json"
        empty.write_text(_json.dumps({"n": 98, "parsed": {}}))
        rc = benchcmp.run([self.FIXTURES[0], str(empty),
                           self.FIXTURES[1]], tolerance_pct=10.0)
        assert rc == 0
        out = capsys.readouterr().out
        assert "BENCH_r98.json has no extractable metrics" in out
        assert "BENCH_r02.json vs BENCH_r01.json" in out

    @staticmethod
    def _sentinel_round(tmp_path, name, append_us, scan_ms):
        import json as _json

        doc = {"n": 1, "cmd": "python bench.py", "rc": 0,
               "tail": "(fixture)",
               "parsed": {"metric": "m", "value": 1.0,
                          "key": {"journal_append_us": append_us,
                                  "anomaly_scan_ms": scan_ms}}}
        path = tmp_path / name
        path.write_text(_json.dumps(doc))
        return str(path)

    def test_sentinel_keys_gated_lower_better(self):
        from predictionio_tpu.tools import benchcmp

        assert benchcmp.lower_is_better("key.journal_append_us")
        assert benchcmp.lower_is_better("key.anomaly_scan_ms")

    def test_journal_append_regression_exits_1(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        base = self._sentinel_round(tmp_path, "BENCH_r01.json", 8.0, 14.0)
        slow = self._sentinel_round(tmp_path, "BENCH_r02.json", 20.0, 14.5)
        rc = benchcmp.run([base, slow], tolerance_pct=10.0)
        assert rc == 1
        out = capsys.readouterr().out
        assert "key.journal_append_us" in out
        assert "REGRESSION" in out
        # scan drifted +3.6%: inside tolerance, no verdict printed
        assert "key.anomaly_scan_ms:" not in out

    def test_anomaly_scan_regression_exits_1(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        base = self._sentinel_round(tmp_path, "BENCH_r01.json", 8.0, 14.0)
        slow = self._sentinel_round(tmp_path, "BENCH_r02.json", 8.1, 40.0)
        rc = benchcmp.run([base, slow], tolerance_pct=10.0)
        assert rc == 1
        out = capsys.readouterr().out
        assert "key.anomaly_scan_ms" in out
        assert "REGRESSION" in out

    def test_sentinel_keys_dropping_is_improvement(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        base = self._sentinel_round(tmp_path, "BENCH_r01.json", 8.0, 14.0)
        fast = self._sentinel_round(tmp_path, "BENCH_r02.json", 4.0, 7.0)
        rc = benchcmp.run([base, fast], tolerance_pct=10.0)
        assert rc == 0
        assert "IMPROVED" in capsys.readouterr().out


class TestJournalCLI:
    """`pio journal` over this process's ring (no --url)."""

    def test_empty_journal(self, capsys):
        assert cli_main(["journal"]) == 0
        assert "(journal is empty)" in capsys.readouterr().out

    def test_human_lines_and_kind_filter(self, capsys):
        from predictionio_tpu.obs import journal

        journal.emit("reload", instance="i-7")
        journal.emit("breaker", target="svc", state="open", failures=3)
        assert cli_main(["journal"]) == 0
        out = capsys.readouterr().out
        assert "reload" in out and "instance=i-7" in out
        assert "breaker" in out and "state=open" in out
        assert cli_main(["journal", "--kind", "breaker"]) == 0
        out = capsys.readouterr().out
        assert "breaker" in out and "reload" not in out

    def test_json_page_shape(self, capsys):
        from predictionio_tpu.obs import journal

        journal.emit("swap", phase="start")
        assert cli_main(["journal", "--json"]) == 0
        page = json.loads(capsys.readouterr().out)
        assert set(page) == {"capacity", "path", "dropped_total",
                             "events"}
        assert page["events"][-1]["kind"] == "swap"

    def test_fleet_without_url_is_an_error(self, capsys):
        assert cli_main(["journal", "--fleet"]) == 1
        assert "--fleet needs --url" in capsys.readouterr().err

    def test_format_event_renders_member_and_trace(self):
        from predictionio_tpu.tools.cli import format_journal_event

        line = format_journal_event(
            {"ts": 1754500000.0, "mono": 1.0, "kind": "reload",
             "fleet_member": "r1", "trace": "a" * 32,
             "instance": "i-1"})
        assert "[r1]" in line
        assert "trace=" + "a" * 8 in line and "a" * 9 not in line
        assert "instance=i-1" in line


class TestAnomaliesCLI:
    """`pio anomalies`: exit 1 while anything is active, 0 when quiet;
    --json is the pinned machine contract."""

    def _arm(self, cause=True):
        from predictionio_tpu.obs import anomaly

        verdict = {"mode": "step", "direction": "up", "baseline": 10.0,
                   "sigma": 0.3, "recent": 15.0, "delta": 5.0,
                   "z": 16.9, "cusum": 45.0, "onset_ts": 1450.0,
                   "since": 1540.0}
        if cause:
            verdict["cause"] = {"kind": "reload", "ts": 1445.0,
                                "instance": "i-9", "gap_sec": 5.0}
        anomaly.SENTINEL._active["serve_p99_ms.e"] = verdict

    def test_quiet_exits_0(self, capsys):
        assert cli_main(["anomalies"]) == 0
        assert "no active anomalies" in capsys.readouterr().out

    def test_active_exits_1_with_attribution(self, capsys):
        self._arm()
        assert cli_main(["anomalies"]) == 1
        out = capsys.readouterr().out
        assert "1 ACTIVE anomaly" in out
        assert "serve_p99_ms.e" in out
        assert "step/up" in out
        assert "z=16.9" in out
        assert "<- reload" in out and "instance=i-9" in out

    def test_json_shape_pin(self, capsys):
        """The machine contract CI scripts consume: top-level keys,
        the active block keyed by series, exit code semantics."""
        self._arm()
        assert cli_main(["anomalies", "--json"]) == 1
        report = json.loads(capsys.readouterr().out)
        assert set(report) == {"window_sec", "active",
                               "recent_resolved", "scan_ms"}
        entry = report["active"]["serve_p99_ms.e"]
        assert {"mode", "direction", "baseline", "recent", "z",
                "onset_ts", "since", "cause"} <= set(entry)
        assert entry["cause"]["kind"] == "reload"
        # quiet process -> same shape, exit 0
        from predictionio_tpu.obs import anomaly

        anomaly.SENTINEL.reset()
        assert cli_main(["anomalies", "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["active"] == {}

    def test_fleet_without_url_is_an_error(self, capsys):
        assert cli_main(["anomalies", "--fleet"]) == 1
        assert "--fleet needs --url" in capsys.readouterr().err
