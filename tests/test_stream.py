"""Streaming events→model (ROADMAP item C): delta-tailer exactness,
ALS fold-in equivalence against a full retrain, freshness accounting,
the engine-server model-patch lane, the router worker pool, and the
hedge-rescue SLO credit."""

import datetime as _dt
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import set_storage
from predictionio_tpu.obs import perfacct

from tests.test_storage import make_storage

UTC = _dt.timezone.utc


def _rate(user, item, rating, event="rate"):
    return Event(
        event=event, entity_type="user", entity_id=user,
        target_entity_type="item", target_entity_id=item,
        properties={"rating": float(rating)} if event == "rate" else {},
        event_time=_dt.datetime.now(tz=UTC))


def _seed_world(storage, app_id, n_users=40, n_items=25, n_events=1200,
                seed=3):
    """Structured synthetic ratings (planted rank-4 signal) so a fold-in
    vs full-retrain comparison measures solve quality, not noise."""
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_users, 4)).astype(np.float32)
    V = rng.normal(size=(n_items, 4)).astype(np.float32)
    events = []
    for _ in range(n_events):
        u = int(rng.integers(0, n_users))
        i = int(rng.integers(0, n_items))
        z = float(U[u] @ V[i]) / 2.0
        r = float(np.clip(np.round((3.0 + z) * 2) / 2, 0.5, 5.0))
        events.append(_rate(f"u{u}", f"i{i}", r))
    storage.events().insert_batch(events, app_id)
    return U, V


# ---------------------------------------------------------------------------
# native delta reads
# ---------------------------------------------------------------------------

class TestDeltaReads:
    def _store(self, tmp_path):
        storage = make_storage("eventlog", tmp_path)
        app = storage.apps().insert("delta")
        storage.events().init(app.id)
        return storage, app.id

    def test_exactly_the_rows_since_the_cursor(self, tmp_path):
        storage, app_id = self._store(tmp_path)
        ev = storage.events()
        ev.insert_batch([_rate("a", "x", 1.0), _rate("b", "y", 2.0)], app_id)
        cursor = ev.delta_cursor(app_id)
        ev.insert_batch([_rate("c", "x", 3.0), _rate("a", "z", 4.5)], app_id)
        cols, cursor2, rebased = ev.find_columnar_since(
            app_id, cursor=cursor, value_property="rating",
            entity_type="user", event_names=["rate", "buy"],
            target_entity_type="item")
        assert not rebased
        assert [cols.entity_vocab[c] for c in cols.entity_codes] == ["c", "a"]
        assert [cols.target_vocab[c] for c in cols.target_codes] == ["x", "z"]
        assert list(cols.values) == [3.0, 4.5]
        # the advanced cursor yields an empty delta
        cols2, cursor3, rebased2 = ev.find_columnar_since(
            app_id, cursor=cursor2, value_property="rating")
        assert len(cols2) == 0 and not rebased2 and cursor3 == cursor2

    def test_cursor_survives_process_restart(self, tmp_path):
        storage, app_id = self._store(tmp_path)
        ev = storage.events()
        ev.insert_batch([_rate("a", "x", 1.0)], app_id)
        cursor = ev.delta_cursor(app_id)
        ev.insert_batch([_rate("b", "y", 2.0)], app_id)
        ev.close()  # releases the flock; a fresh handle replays/loads
        cols, cursor2, rebased = ev.find_columnar_since(
            app_id, cursor=cursor, value_property="rating")
        assert not rebased
        assert [cols.entity_vocab[c] for c in cols.entity_codes] == ["b"]
        ev.insert_batch([_rate("c", "z", 3.0)], app_id)
        cols2, _, rebased2 = ev.find_columnar_since(
            app_id, cursor=cursor2, value_property="rating")
        assert not rebased2
        assert [cols2.entity_vocab[c] for c in cols2.entity_codes] == ["c"]

    def test_compaction_rebases_the_cursor(self, tmp_path):
        storage, app_id = self._store(tmp_path)
        ev = storage.events()
        ids = ev.insert_batch([_rate("a", "x", 1.0), _rate("b", "y", 2.0)],
                              app_id)
        cursor = ev.delta_cursor(app_id)
        ev.delete(ids[0], app_id)
        ev.compact(app_id)
        cols, _, rebased = ev.find_columnar_since(
            app_id, cursor=cursor, value_property="rating")
        # the rescan returns the live set, flagged as NOT a delta
        assert rebased
        assert [cols.entity_vocab[c] for c in cols.entity_codes] == ["b"]

    def test_filters_and_deletes_apply_to_the_delta(self, tmp_path):
        storage, app_id = self._store(tmp_path)
        ev = storage.events()
        cursor = ev.delta_cursor(app_id)
        ids = ev.insert_batch(
            [_rate("a", "x", 1.0),
             Event(event="$set", entity_type="user", entity_id="a",
                   properties={"p": 1},
                   event_time=_dt.datetime.now(tz=UTC)),
             _rate("b", "y", 2.0)], app_id)
        ev.delete(ids[2], app_id)  # tombstoned before the read
        cols, _, rebased = ev.find_columnar_since(
            app_id, cursor=cursor, value_property="rating",
            entity_type="user", event_names=["rate", "buy"],
            target_entity_type="item")
        assert not rebased
        assert [cols.entity_vocab[c] for c in cols.entity_codes] == ["a"]

    def test_malformed_cursor_rejected(self, tmp_path):
        storage, app_id = self._store(tmp_path)
        with pytest.raises(ValueError, match="malformed delta cursor"):
            storage.events().find_columnar_since(app_id, cursor="nope")

    def test_unknown_filter_rejected(self, tmp_path):
        storage, app_id = self._store(tmp_path)
        ev = storage.events()
        cursor = ev.delta_cursor(app_id)
        with pytest.raises(TypeError, match="unexpected filters"):
            ev.find_columnar_since(app_id, cursor=cursor, limit=5)


# ---------------------------------------------------------------------------
# ALS fold-in equivalence + freshness
# ---------------------------------------------------------------------------

def _train_reco(storage, engine_id="stream_eq", iterations=15):
    from predictionio_tpu.templates.recommendation import (
        recommendation_engine)
    from predictionio_tpu.workflow.train import run_train

    engine = recommendation_engine()
    ep = engine.engine_params_from_variant({
        "datasource": {"params": {"app_name": "stream"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 8, "num_iterations": iterations, "lambda_": 0.1,
            "compute_dtype": "float32", "cg_dtype": "float32",
            "cg_iters": 12}}],
    })
    instance = run_train(engine, ep, engine_id=engine_id, storage=storage)
    assert instance.status == "COMPLETED"
    return engine, instance


def _load_model(engine, instance, storage):
    from predictionio_tpu.workflow.deploy import prepare_deploy

    return prepare_deploy(engine, instance, storage=storage).models[0]


class TestALSFoldIn:
    @pytest.fixture()
    def world(self, tmp_path):
        storage = make_storage("eventlog", tmp_path)
        set_storage(storage)
        app = storage.apps().insert("stream")
        storage.events().init(app.id)
        _seed_world(storage, app.id)
        yield storage, app.id
        set_storage(None)

    def test_foldin_matches_full_retrain_within_tolerance(self, world):
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        engine, instance = _train_reco(storage)
        updater = StreamUpdater(engine, "stream_eq", storage=storage,
                                instance=instance)
        rng = np.random.default_rng(9)
        # new users rating existing items, plus one existing user with
        # fresh ratings — both fold lanes (cold solve + warm re-solve)
        delta = []
        touched = []
        for k in range(4):
            uid = f"fresh{k}"
            touched.append(uid)
            for i in rng.integers(0, 25, size=6):
                delta.append(_rate(uid, f"i{int(i)}",
                                   float(rng.integers(2, 11)) / 2.0))
        touched.append("u3")
        for i in (1, 7, 19):
            delta.append(_rate("u3", f"i{i}", 4.5))
        storage.events().insert_batch(delta, app_id)
        stats = updater.poll_once()
        assert stats["events"] == len(delta) and stats["published"]
        folded = updater._folders[0].model

        # full retrain over base + delta: the ground truth
        engine2, instance2 = _train_reco(storage, engine_id="stream_eq2")
        retrained = _load_model(engine2, instance2, storage)

        for uid in touched:
            u_f = folded.user_factors[folded.user_ids[uid]]
            u_r = retrained.user_factors[retrained.user_ids[uid]]
            # compare PREDICTIONS (scores over the shared item set) —
            # factors themselves are only identified up to the data
            items = [f"i{i}" for i in range(25)]
            p_f = np.array([folded.item_factors[folded.item_ids[i]] @ u_f
                            for i in items])
            p_r = np.array([retrained.item_factors[retrained.item_ids[i]]
                            @ u_r for i in items])
            rmse = float(np.sqrt(np.mean((p_f - p_r) ** 2)))
            assert rmse < 0.12, (uid, rmse)
            assert float(np.max(np.abs(p_f - p_r))) < 0.35, uid

    def test_staleness_drops_to_zero_without_retrain(self, world):
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        engine, instance = _train_reco(storage, engine_id="stream_fresh")
        updater = StreamUpdater(engine, "stream_fresh", storage=storage,
                                instance=instance)
        perfacct.LEDGER.clear()
        storage.events().insert_batch(
            [_rate("newbie", "i1", 5.0), _rate("newbie", "i2", 3.0)],
            app_id)
        time.sleep(0.05)
        assert perfacct.LEDGER.staleness_seconds() >= 0.05
        trains_before = storage.engine_instances().get_latest_completed(
            "stream_fresh", "0", "default").id
        stats = updater.poll_once()
        assert stats["published"] and stats["events"] == 2
        # freshness restored by the FOLD — no new trained instance
        assert perfacct.LEDGER.staleness_seconds() < 0.05
        assert storage.engine_instances().get_latest_completed(
            "stream_fresh", "0", "default").id == trains_before
        perfacct.LEDGER.clear()

    def test_rebase_skips_fold_and_warns(self, world):
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        engine, instance = _train_reco(storage, engine_id="stream_rb")
        updater = StreamUpdater(engine, "stream_rb", storage=storage,
                                instance=instance)
        ev = storage.events()
        eid = ev.insert(_rate("gone", "i1", 1.0), app_id)
        ev.delete(eid, app_id)
        ev.compact(app_id)  # renumbers records -> cursor rebases
        stats = updater.poll_once()
        assert stats["rebased"] and stats["events"] == 0
        # after the reset the tail is clean again
        ev.insert_batch([_rate("after", "i2", 4.0)], app_id)
        stats2 = updater.poll_once()
        assert not stats2["rebased"] and stats2["events"] == 1

    def test_truncated_backlog_holds_staleness_debt(self, world,
                                                    monkeypatch):
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        engine, instance = _train_reco(storage, engine_id="stream_tr",
                                       iterations=4)
        updater = StreamUpdater(engine, "stream_tr", storage=storage,
                                instance=instance)
        monkeypatch.setenv("PIO_STREAM_MAX_DELTA", "3")
        perfacct.LEDGER.clear()
        storage.events().insert_batch(
            [_rate(f"tr{k}", "i1", 4.0) for k in range(8)], app_id)
        time.sleep(0.02)
        stats = updater.poll_once()
        assert stats["truncated"] and stats["published"]
        # the dropped backlog is unreflected work: NOT credited
        assert perfacct.LEDGER.staleness_seconds() >= 0.02
        # ...and a LATER clean fold must not silently credit it either
        storage.events().insert_batch([_rate("tr_late", "i2", 4.0)],
                                      app_id)
        stats2 = updater.poll_once()
        assert stats2["published"] and not stats2["truncated"]
        assert perfacct.LEDGER.staleness_seconds() >= 0.02
        # only a NEW trained instance (the retrain lane) clears the debt
        _, instance2 = _train_reco(storage, engine_id="stream_tr",
                                   iterations=4)
        updater.resync()
        assert updater.instance_id == instance2.id
        storage.events().insert_batch([_rate("tr_post", "i3", 4.0)],
                                      app_id)
        stats3 = updater.poll_once()
        assert stats3["published"]
        assert perfacct.LEDGER.staleness_seconds() < 0.02
        perfacct.LEDGER.clear()

    def test_fold_failure_rewinds_cursor_for_retry(self, world):
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        engine, instance = _train_reco(storage, engine_id="stream_err",
                                       iterations=4)
        updater = StreamUpdater(engine, "stream_err", storage=storage,
                                instance=instance)
        storage.events().insert_batch(
            [_rate("err_u", "i1", 4.0), _rate("err_u", "i2", 3.0)],
            app_id)
        folder = updater._folders[0]
        real_fold = folder.fold
        calls = {"n": 0}

        def flaky_fold(users, items, ratings):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient fold failure")
            return real_fold(users, items, ratings)

        folder.fold = flaky_fold
        before = updater.cursor
        with pytest.raises(RuntimeError, match="transient"):
            updater.poll_once()
        assert updater.cursor == before  # rewound: the delta survives
        stats = updater.poll_once()      # the next tick retries it
        assert stats["events"] == 2 and stats["published"]
        assert "err_u" in folder.model.user_ids

    def test_inprocess_stale_patch_triggers_resync(self, world):
        from predictionio_tpu.serving.engine_server import EngineServer
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        engine, instance = _train_reco(storage, engine_id="stream_sp",
                                       iterations=4)
        server = EngineServer(engine, "stream_sp", host="127.0.0.1",
                              port=0, storage=storage).start()
        try:
            updater = StreamUpdater(engine, "stream_sp", storage=storage,
                                    instance=instance,
                                    patch_servers=[server])
            # a retrain lands and the server rolls to it behind the
            # streamer's back
            _, instance2 = _train_reco(storage, engine_id="stream_sp",
                                       iterations=4)
            server.reload()
            storage.events().insert_batch([_rate("sp_u", "i1", 4.0)],
                                          app_id)
            stats = updater.poll_once()
            # the stale patch is a counted failure AND the streamer
            # rebinds to the served instance, like the HTTP 409 lane
            assert not stats["published"]
            assert updater.instance_id == instance2.id
            storage.events().insert_batch([_rate("sp_u2", "i2", 4.5)],
                                          app_id)
            stats2 = updater.poll_once()
            assert stats2["published"]
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# engine-server model-patch lane
# ---------------------------------------------------------------------------

class TestModelPatch:
    @pytest.fixture()
    def served(self, tmp_path):
        from predictionio_tpu.serving.engine_server import EngineServer

        storage = make_storage("eventlog", tmp_path)
        set_storage(storage)
        app = storage.apps().insert("stream")
        storage.events().init(app.id)
        _seed_world(storage, app.id, n_events=400)
        engine, instance = _train_reco(storage, engine_id="patch_e",
                                       iterations=4)
        server = EngineServer(engine, "patch_e", host="127.0.0.1", port=0,
                              storage=storage).start()
        yield server, instance
        server.stop()
        set_storage(None)

    @staticmethod
    def _post(port, payload, token=None):
        headers = {"Content-Type": "application/json"}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/model/patch",
            data=json.dumps(payload).encode(), headers=headers,
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    @staticmethod
    def _query(port, user):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=json.dumps({"user": user, "num": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    def test_patch_applies_new_user_row(self, served):
        server, instance = served
        assert self._query(server.port, "patched_u")["itemScores"] == []
        vec = [0.5] * 8
        status, body = self._post(server.port, {
            "instanceId": instance.id,
            "algorithms": [{"index": 0, "userRows": [["patched_u", vec]]}],
        })
        assert status == 200 and body["applied"] == 1
        assert self._query(server.port, "patched_u")["itemScores"]

    def test_stale_instance_answers_409(self, served):
        server, _ = served
        status, body = self._post(server.port, {
            "instanceId": "not_the_deployed_instance",
            "algorithms": [{"index": 0, "userRows": [["u", [0.0] * 8]]}],
        })
        assert status == 409
        assert "stale" in body["message"] or "instance" in body["message"]

    def test_malformed_patch_answers_400(self, served):
        server, instance = served
        for payload in (
                {"instanceId": instance.id, "algorithms": []},
                {"instanceId": instance.id,
                 "algorithms": [{"index": 99, "userRows": []}]},
                {"instanceId": instance.id,
                 "algorithms": [{"index": 0,
                                 "userRows": [["u", [0.0] * 3]]}]},
        ):
            status, _ = self._post(server.port, payload)
            assert status == 400, payload

    def test_patch_requires_bearer_token_when_set(self, served,
                                                  monkeypatch):
        server, instance = served
        monkeypatch.setenv("PIO_ADMIN_TOKEN", "s3cret")
        payload = {
            "instanceId": instance.id,
            "algorithms": [{"index": 0,
                            "userRows": [["tok_u", [0.1] * 8]]}],
        }
        status, _ = self._post(server.port, payload)
        assert status == 401
        status, _ = self._post(server.port, payload, token="s3cret")
        assert status == 200

    def test_unsupported_algorithm_answers_400(self, tmp_path):
        from predictionio_tpu.core import Engine
        from predictionio_tpu.core.params import EngineParams
        from predictionio_tpu.serving.engine_server import EngineServer
        from predictionio_tpu.workflow.train import run_train
        from tests.test_servers import (ConstAlgo, ConstDataSource,
                                        ConstParams, FirstServing,
                                        IdentityPreparator)

        storage = make_storage("memory", tmp_path)
        set_storage(storage)
        try:
            engine = Engine(ConstDataSource, IdentityPreparator,
                            {"c": ConstAlgo}, FirstServing)
            ep = EngineParams(
                data_source_params=("", ConstParams(value=1.0)),
                preparator_params=("", None),
                algorithm_params_list=[("c", ConstParams(value=2.0))],
                serving_params=("", None),
            )
            instance = run_train(engine, ep, engine_id="const",
                                 storage=storage)
            server = EngineServer(engine, "const", host="127.0.0.1",
                                  port=0, storage=storage,
                                  micro_batch=False).start()
            try:
                status, body = self._post(server.port, {
                    "instanceId": instance.id,
                    "algorithms": [{"index": 0, "userRows": []}],
                })
                assert status == 400
                assert "does not support" in body["message"]
            finally:
                server.stop()
        finally:
            set_storage(None)


# ---------------------------------------------------------------------------
# two-tower online delta steps
# ---------------------------------------------------------------------------

class TestTwoTowerOnline:
    def test_updates_only_touched_rows_and_reduces_delta_loss(self):
        from predictionio_tpu.ops.twotower import online_delta_step

        rng = np.random.default_rng(5)

        def unit_rows(n, d):
            v = rng.normal(size=(n, d)).astype(np.float32)
            return v / np.linalg.norm(v, axis=1, keepdims=True)

        U = unit_rows(20, 16)
        V = unit_rows(30, 16)
        u_rows = np.array([1, 1, 4, 7], np.int32)
        i_rows = np.array([2, 9, 9, 11], np.int32)
        uu, new_u, ii, new_v, losses = online_delta_step(
            U, V, u_rows, i_rows, lr=0.1, steps=6)
        assert list(uu) == [1, 4, 7] and list(ii) == [2, 9, 11]
        # bounded steps actually descend the delta-batch objective
        assert losses[-1] < losses[0]
        # updated rows stay unit-norm (the serving manifold)
        assert np.allclose(np.linalg.norm(new_u, axis=1), 1.0, atol=1e-4)
        assert np.allclose(np.linalg.norm(new_v, axis=1), 1.0, atol=1e-4)
        # untouched source tables are never mutated
        assert np.allclose(np.linalg.norm(U, axis=1), 1.0, atol=1e-5)

    def test_empty_delta_is_a_noop(self):
        from predictionio_tpu.ops.twotower import online_delta_step

        uu, new_u, ii, new_v, losses = online_delta_step(
            np.zeros((4, 8), np.float32), np.zeros((4, 8), np.float32),
            np.zeros(0, np.int32), np.zeros(0, np.int32))
        assert len(uu) == 0 and len(ii) == 0 and losses == []


# ---------------------------------------------------------------------------
# router worker pool (ROADMAP item B follow-up)
# ---------------------------------------------------------------------------

class TestRouterWorkerPool:
    def test_reuses_workers_and_counts_saturation(self):
        from predictionio_tpu.serving.router import (_POOL_SATURATED,
                                                     _WorkerPool)

        pool = _WorkerPool(2)
        gate = threading.Event()
        started = []
        done = []

        def blocker(k):
            started.append(k)
            gate.wait(5)
            done.append(k)

        base = _POOL_SATURATED.value
        pool.submit(blocker, 0)
        pool.submit(blocker, 1)
        deadline = time.monotonic() + 5
        while len(started) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.outstanding() == 2
        assert _POOL_SATURATED.value == base
        # third task: both workers busy -> overflow thread + counter
        pool.submit(blocker, 2)
        deadline = time.monotonic() + 5
        while len(started) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(started) == 3, "overflow task must run, not queue"
        assert _POOL_SATURATED.value == base + 1
        gate.set()
        deadline = time.monotonic() + 5
        while len(done) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sorted(done) == [0, 1, 2]
        # pool workers drained their outstanding accounting
        deadline = time.monotonic() + 5
        while pool.outstanding() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.outstanding() == 0
        pool.stop()

    def test_task_error_does_not_kill_the_worker(self):
        from predictionio_tpu.serving.router import _WorkerPool

        pool = _WorkerPool(1)
        results = []
        pool.submit(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        pool.submit(results.append, "alive")
        deadline = time.monotonic() + 5
        while not results and time.monotonic() < deadline:
            time.sleep(0.01)
        assert results == ["alive"]
        pool.stop()


# ---------------------------------------------------------------------------
# hedge-rescue SLO credit (ROADMAP item B remaining)
# ---------------------------------------------------------------------------

class TestHedgeRescueCredit:
    def test_rescued_requests_do_not_burn_latency_budget(self):
        import predictionio_tpu.serving.engine_server  # registers the hist
        from predictionio_tpu.obs import metrics, slo

        hist = metrics.REGISTRY.get("pio_serving_request_seconds")
        assert hist is not None
        child = hist.labels("credit_test")
        # a dedicated credit counter isolates this test from real
        # router traffic elsewhere in the suite; the real wiring (the
        # default SLO naming pio_router_hedge_rescues_total) is pinned
        # in the companion test below
        credit = metrics.counter(
            "pio_test_hedge_credit_total", "test credit counter")
        measured = slo.SLO(
            name="serving-latency", kind="latency",
            metric="pio_serving_request_seconds", objective=0.99,
            threshold_ms=100.0,
            good_credit_metric="pio_test_hedge_credit_total",
        )
        # 100 requests; 4 over the 100 ms threshold
        for _ in range(96):
            child.observe(0.005)
        for _ in range(4):
            child.observe(0.5)
        good0, total0 = measured.measure()
        # every slow primary was actually rescued by a hedge in time
        credit.inc(4)
        good1, total1 = measured.measure()
        assert total1 == total0
        assert good1 == pytest.approx(good0 + 4)
        # credit clamps at total — it can never manufacture good > total
        credit.inc(10_000)
        good2, total2 = measured.measure()
        assert good2 == total2

    def test_default_serving_slo_carries_the_credit_metric(self):
        from predictionio_tpu.obs import slo

        latency = [s for s in slo.default_slos()
                   if s.name == "serving-latency"][0]
        assert latency.good_credit_metric == "pio_router_hedge_rescues_total"


# ---------------------------------------------------------------------------
# bench-compare: streaming keys are direction-aware
# ---------------------------------------------------------------------------

class TestStreamBenchKeys:
    @staticmethod
    def _round(tmp_path, name, e2s_ms, foldin_eps):
        doc = {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "(fx)",
               "parsed": {
                   "metric": "als_ml20m_rating_updates_per_sec_per_chip",
                   "value": 6.0e7, "unit": "ratings*iters/sec",
                   "key": {"event_to_servable_ms": e2s_ms,
                           "foldin_events_per_sec": foldin_eps}}}
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_direction_inference(self):
        from predictionio_tpu.tools import benchcmp

        assert benchcmp.lower_is_better("key.event_to_servable_ms")
        assert not benchcmp.lower_is_better("key.foldin_events_per_sec")

    def test_freshness_regression_fails_compare(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 420.0, 5000.0),
                 self._round(tmp_path, "BENCH_r02.json", 900.0, 5100.0)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 1
        assert "key.event_to_servable_ms" in capsys.readouterr().out

    def test_foldin_throughput_drop_fails_compare(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 420.0, 5000.0),
                 self._round(tmp_path, "BENCH_r02.json", 410.0, 2000.0)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 1
        assert "key.foldin_events_per_sec" in capsys.readouterr().out

    def test_improvement_passes(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 900.0, 2000.0),
                 self._round(tmp_path, "BENCH_r02.json", 420.0, 5000.0)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 0
        assert "IMPROVED" in capsys.readouterr().out
