"""Unified telemetry subsystem (obs/): metrics core + Prometheus
exposition on every server, request tracing with X-PIO-Trace-Id
propagation engine server -> rest storage client -> storage server,
JAX runtime instrumentation, and the satellite fixes that ride along
(Stats.report pruning, ServingStats on the shared histogram)."""

import datetime as _dt
import json
import re
import urllib.request

import pytest

from predictionio_tpu.obs import jaxmon, metrics, trace
from predictionio_tpu.obs.metrics import Registry

UTC = _dt.timezone.utc


def http_get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, dict(resp.headers), resp.read().decode()


def http_post(url, body, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, dict(resp.headers), resp.read().decode()


# ---------------------------------------------------------------------------
# metrics core
# ---------------------------------------------------------------------------

def test_counter_labels_and_monotonicity():
    reg = Registry()
    c = reg.counter("t_requests_total", "help", ("route", "status"))
    c.labels("/a", "200").inc()
    c.labels("/a", "200").inc(2)
    c.labels(route="/b", status="500").inc()
    assert c.labels("/a", "200").value == 3
    assert c.labels("/b", "500").value == 1
    with pytest.raises(ValueError):
        c.labels("/a", "200").inc(-1)
    with pytest.raises(ValueError):
        c.labels("/only-one")


def test_gauge_set_inc_dec():
    reg = Registry()
    g = reg.gauge("t_inflight", "help")
    g.inc()
    g.inc()
    g.dec()
    assert g.value == 1
    g.set(42.5)
    assert g.value == 42.5


def test_histogram_bucket_math_and_quantiles():
    reg = Registry()
    h = reg.histogram("t_latency_seconds", "help",
                      buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.02, 0.02, 0.5, 3.0):
        h.observe(v)
    child = h.labels()
    assert child.count == 5
    assert child.sum == pytest.approx(3.545)
    # cumulative: le=0.01 -> 1, le=0.1 -> 3, le=1.0 -> 4, +Inf -> 5
    cum = dict(
        (bound, count) for bound, count in child.cumulative()
    )
    assert cum[0.01] == 1 and cum[0.1] == 3 and cum[1.0] == 4
    assert cum[float("inf")] == 5
    # quantiles interpolate inside the crossing bucket
    assert 0.01 <= child.quantile(0.5) <= 0.1
    assert child.quantile(0.0) == 0.0
    # the open-ended tail answers the last finite bound
    assert child.quantile(1.0) == 1.0


def test_histogram_boundary_values_are_inclusive():
    reg = Registry()
    h = reg.histogram("t_edges", "help", buckets=(1.0, 2.0))
    h.observe(1.0)   # le="1" is inclusive, Prometheus semantics
    h.observe(2.0)
    cum = dict(h.labels().cumulative())
    assert cum[1.0] == 1 and cum[2.0] == 2


def test_registry_dedup_and_type_conflict():
    reg = Registry()
    a = reg.counter("t_dup", "help", ("x",))
    assert reg.counter("t_dup", "help", ("x",)) is a
    with pytest.raises(ValueError):
        reg.gauge("t_dup", "help", ("x",))
    with pytest.raises(ValueError):
        reg.counter("t_dup", "help", ("y",))
    h = reg.histogram("t_dup_h", "help", buckets=(0.1, 1.0))
    assert reg.histogram("t_dup_h", "help", buckets=(0.1, 1.0)) is h
    with pytest.raises(ValueError):  # silently-different buckets misbucket
        reg.histogram("t_dup_h", "help", buckets=(0.5, 2.0))
    # atomic (count, sum) pair for average computations
    h.observe(0.3)
    assert h.labels().snapshot() == (1, pytest.approx(0.3))


def test_label_escaping_in_exposition():
    reg = Registry()
    c = reg.counter("t_esc", "help", ("msg",))
    c.labels('say "hi"\nback\\slash').inc()
    text = reg.render()
    assert r'msg="say \"hi\"\nback\\slash"' in text


_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [0-9.eE+-]+(Inf)?$"
)


def assert_valid_prometheus(text: str) -> dict:
    """Validate the text-format document shape; return {name: value}
    for unlabeled samples and histogram invariants for labeled ones."""
    samples = {}
    by_series = {}
    for line in text.strip().splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
        name_part, value = line.rsplit(" ", 1)
        samples[name_part] = float(value)
        by_series.setdefault(name_part, float(value))
    # histogram invariant: the +Inf bucket equals the series count
    for key, value in samples.items():
        m = re.match(r"^(.*)_bucket\{(.*)le=\"\+Inf\"\}$", key)
        if m:
            base, labels = m.group(1), m.group(2).rstrip(",")
            count_key = f"{base}_count{{{labels}}}" if labels else (
                f"{base}_count")
            count_key = count_key.replace("{}", "")
            assert samples[count_key] == value, key
    return samples


def test_render_is_valid_prometheus_text():
    reg = Registry()
    reg.counter("t_total", "help", ("k",)).labels("v").inc(3)
    reg.gauge("t_gauge", "plain gauge").set(1.5)
    h = reg.histogram("t_h", "hist", ("k",), buckets=(0.1, 1.0))
    h.labels("v").observe(0.05)
    h.labels("v").observe(5.0)
    samples = assert_valid_prometheus(reg.render())
    assert samples['t_total{k="v"}'] == 3
    assert samples['t_h_bucket{k="v",le="+Inf"}'] == 2
    assert samples['t_h_count{k="v"}'] == 2


def test_metrics_route_collapses_ids():
    from predictionio_tpu.serving.http import metrics_route

    assert metrics_route("/") == "/"
    assert metrics_route("/events.json") == "/events.json"
    eid = "0123456789abcdef0123456789abcdef"
    assert metrics_route(f"/events/{eid}.json") == "/events/:id.json"
    assert metrics_route(f"/storage/models/{eid}") == "/storage/models/:id"
    assert metrics_route(f"/storage/events/scan/{eid}") == (
        "/storage/events/scan/:id")
    assert metrics_route("/queries.json") == "/queries.json"


def test_metrics_route_cardinality_is_capped(monkeypatch):
    from predictionio_tpu.serving import http

    monkeypatch.setattr(http, "_routes_seen", set())
    monkeypatch.setattr(http, "_MAX_ROUTES", 4)
    assert [http.metrics_route(f"/probe{i}") for i in range(4)] == [
        f"/probe{i}" for i in range(4)]
    # a scanner's 5th+ distinct path collapses instead of growing labels
    assert http.metrics_route("/probe4") == ":other"
    assert http.metrics_route("/probe0") == "/probe0"  # known stays known


def test_invalid_trace_header_is_reminted(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}"
    bad = "not-a-trace-id!{}"
    _, headers, _ = http_get(f"{base}/", headers={trace.TRACE_HEADER: bad})
    echoed = headers[trace.TRACE_HEADER]
    assert echoed != bad
    assert trace.valid_trace_id(echoed)
    assert not trace.valid_trace_id("x" * 65)
    assert not trace.valid_trace_id("")
    assert trace.valid_trace_id("deadbeef-0123-4567")


# ---------------------------------------------------------------------------
# /metrics exposition on the servers
# ---------------------------------------------------------------------------

@pytest.fixture()
def event_server(memory_storage):
    from predictionio_tpu.data.metadata import AccessKey
    from predictionio_tpu.serving.event_server import EventServer

    app = memory_storage.apps().insert("obs-app")
    memory_storage.events().init(app.id)
    key = AccessKey.generate(app.id)
    memory_storage.access_keys().insert(key)
    server = EventServer(storage=memory_storage, host="127.0.0.1", port=0).start()
    yield server, app, key
    server.stop()


def test_event_server_metrics_endpoint(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}"
    http_post(f"{base}/events.json?accessKey={key.key}",
              {"event": "view", "entityType": "user", "entityId": "u1"})
    status, headers, text = http_get(f"{base}/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    samples = assert_valid_prometheus(text)
    key_ = ('pio_http_requests_total{server="PIOEventServer",'
            'method="POST",route="/events.json",status="201"}')
    assert samples[key_] >= 1
    # the duration histogram and in-flight gauge ride along
    assert any(k.startswith("pio_http_request_duration_seconds_bucket"
                            '{server="PIOEventServer"') for k in samples)
    assert 'pio_http_requests_in_flight{server="PIOEventServer"}' in samples


def test_storage_server_metrics_endpoint_without_auth_key(memory_storage):
    from predictionio_tpu.serving.storage_server import StorageServer

    server = StorageServer(storage=memory_storage, host="127.0.0.1",
                           port=0, auth_key="sekrit").start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # /metrics is a scrape endpoint: served before storage auth
        status, _, text = http_get(f"{base}/metrics")
        assert status == 200
        assert_valid_prometheus(text)
        # compile-cache and trace counters are part of the document
        assert "pio_jax_compile_cache_total" in text
        assert "pio_trace_spans_total" in text
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# engine server + end-to-end trace propagation over REST storage
# ---------------------------------------------------------------------------

def _rest_client(port):
    from predictionio_tpu.data.storage import Storage

    return Storage.from_env({
        "PIO_STORAGE_SOURCES_CENTRAL_TYPE": "rest",
        "PIO_STORAGE_SOURCES_CENTRAL_HOSTS": "127.0.0.1",
        "PIO_STORAGE_SOURCES_CENTRAL_PORTS": str(port),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "CENTRAL",
    })


class _TraceAlgoHolder:
    """Serve-time storage client for StorageReadingAlgo (set per test)."""

    client = None
    app_id = None


def _build_reading_engine():
    from dataclasses import dataclass

    from predictionio_tpu.core import (
        Algorithm,
        DataSource,
        Engine,
        FirstServing,
        IdentityPreparator,
    )
    from predictionio_tpu.core.params import Params

    @dataclass
    class NoParams(Params):
        pass

    class OneDataSource(DataSource):
        def __init__(self, params):
            super().__init__(params)

        def read_training(self, ctx):
            return 1.0

    class StorageReadingAlgo(Algorithm):
        """predict() does a REST storage read — the serve-time storage
        round-trip the trace must decompose."""

        def __init__(self, params):
            super().__init__(params)

        def train(self, ctx, pd):
            return pd

        def predict(self, model, query):
            events = _TraceAlgoHolder.client.events().find(
                _TraceAlgoHolder.app_id)
            return {"events": len(events), "model": model}

    return Engine(OneDataSource, IdentityPreparator,
                  {"reader": StorageReadingAlgo}, FirstServing), NoParams


def test_trace_chain_engine_to_storage_server(memory_storage):
    """Acceptance: one served query produces a span chain sharing one
    trace id from the engine server through the REST storage backend to
    the storage server, and /metrics shows serving + span counts."""
    from predictionio_tpu.core.params import EngineParams
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.serving.engine_server import EngineServer
    from predictionio_tpu.serving.storage_server import StorageServer
    from predictionio_tpu.workflow.train import run_train

    storage_server = StorageServer(storage=memory_storage, host="127.0.0.1",
                                   port=0).start()
    engine_server = None
    try:
        client = _rest_client(storage_server.port)
        app = client.apps().insert("traced-app")
        client.events().init(app.id)
        client.events().insert(
            Event(event="view", entity_type="user", entity_id="u1"), app.id)
        _TraceAlgoHolder.client = client
        _TraceAlgoHolder.app_id = app.id

        engine, NoParams = _build_reading_engine()
        ep = EngineParams(
            data_source_params=("", NoParams()),
            preparator_params=("", None),
            algorithm_params_list=[("reader", NoParams())],
            serving_params=("", None),
        )
        run_train(engine, ep, engine_id="traced", storage=memory_storage)
        engine_server = EngineServer(
            engine, "traced", host="127.0.0.1", port=0,
            storage=memory_storage).start()

        trace.clear_recent()
        trace_id = "feedfacecafebeef" * 2
        base = f"http://127.0.0.1:{engine_server.port}"
        status, headers, body = http_post(
            f"{base}/queries.json", {"q": 1},
            headers={trace.TRACE_HEADER: trace_id})
        assert status == 200
        assert json.loads(body)["events"] == 1
        # the trace id round-trips in the response
        assert headers[trace.TRACE_HEADER] == trace_id

        # each server's outer http span is emitted by ITS handler
        # thread as the instrument wrapper unwinds — AFTER the response
        # bytes already reached the caller, so the full chain lands
        # asynchronously with the client's return: poll briefly
        import time as _time

        wanted = ("http.engineserver", "serve.query", "serve.dispatch",
                  "storage.find", "http.storageserver")
        deadline = _time.monotonic() + 5.0
        while True:
            spans = trace.recent_spans(trace_id=trace_id)
            names = [s["name"] for s in spans]
            if all(e in names for e in wanted) or (
                    _time.monotonic() >= deadline):
                break
            _time.sleep(0.02)
        for expected in wanted:
            assert expected in names, (expected, names)
        assert {s["trace"] for s in spans} == {trace_id}
        # parenthood: serve.query is a child of the engine-server span
        by_name = {s["name"]: s for s in spans}
        assert by_name["serve.query"]["parent"] is not None
        assert all("duration_ms" in s and s["duration_ms"] >= 0
                   for s in spans)

        # /metrics on the engine server: serving histogram + span counts
        _, _, text = http_get(f"{base}/metrics")
        samples = assert_valid_prometheus(text)
        assert samples['pio_serving_request_seconds_count{engine="traced"}'] >= 1
        assert samples['pio_trace_spans_total{name="serve.query"}'] >= 1
    finally:
        if engine_server is not None:
            engine_server.stop()
        storage_server.stop()
        _TraceAlgoHolder.client = None


def test_span_records_nothing_without_active_trace():
    trace.clear_recent()
    with trace.span("orphan.work"):
        pass
    assert trace.recent_spans() == []


def test_span_records_error_and_nesting(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_TRACE_LOG", str(tmp_path / "trace.jsonl"))
    trace.clear_recent()
    token = trace.activate("t" * 32)
    try:
        with trace.span("outer"):
            with pytest.raises(ValueError):
                with trace.span("inner", detail="x"):
                    raise ValueError("boom")
    finally:
        trace.deactivate(token)
    spans = trace.recent_spans(trace_id="t" * 32)
    inner = next(s for s in spans if s["name"] == "inner")
    outer = next(s for s in spans if s["name"] == "outer")
    assert inner["parent"] == outer["span"]
    assert inner["error"].startswith("ValueError")
    assert inner["detail"] == "x"
    # mirrored as JSON lines to PIO_TRACE_LOG
    lines = [json.loads(l) for l in
             (tmp_path / "trace.jsonl").read_text().splitlines()]
    assert {l["name"] for l in lines} == {"outer", "inner"}


# ---------------------------------------------------------------------------
# JAX runtime instrumentation
# ---------------------------------------------------------------------------

def test_compile_cache_counters_via_jax_monitoring():
    """The jaxmon bridge is registered by enable_persistent_cache and
    counts the real jax.monitoring events."""
    from jax import monitoring

    from predictionio_tpu.parallel.compile_cache import enable_persistent_cache

    enable_persistent_cache()  # installs the bridge (idempotent)
    assert jaxmon.install()    # second call: already installed

    hits0 = jaxmon.COMPILE_CACHE_TOTAL.labels("hit").value
    miss0 = jaxmon.COMPILE_CACHE_TOTAL.labels("miss").value
    monitoring.record_event("/jax/compilation_cache/cache_hits")
    monitoring.record_event("/jax/compilation_cache/cache_misses")
    monitoring.record_event("/jax/compilation_cache/cache_misses")
    assert jaxmon.COMPILE_CACHE_TOTAL.labels("hit").value == hits0 + 1
    assert jaxmon.COMPILE_CACHE_TOTAL.labels("miss").value == miss0 + 2

    child = jaxmon.COMPILE_SECONDS.labels("backend_compile")
    c0, s0 = child.count, child.sum
    monitoring.record_event_duration_secs(
        "/jax/core/compile/backend_compile_duration", 0.5)
    assert child.count == c0 + 1
    assert child.sum == pytest.approx(s0 + 0.5)
    # unknown events are ignored, not errors
    monitoring.record_event("/jax/some/new/event")
    monitoring.record_event_duration_secs("/jax/some/new/duration", 1.0)


def test_real_compile_feeds_compile_histogram():
    """Jitting fresh code emits backend-compile durations through the
    installed listener — the integration proof without depending on
    persistent-cache behavior."""
    import jax
    import jax.numpy as jnp

    assert jaxmon.install()
    before = sum(
        jaxmon.COMPILE_SECONDS.labels(p).count
        for p in ("trace", "lower", "backend_compile")
    )

    @jax.jit
    def fresh(x):
        return (x * 3 + 1).sum()

    fresh(jnp.arange(7)).block_until_ready()
    after = sum(
        jaxmon.COMPILE_SECONDS.labels(p).count
        for p in ("trace", "lower", "backend_compile")
    )
    assert after > before


def test_transfer_and_train_step_instruments():
    d0 = jaxmon.TRANSFER_BYTES.labels("h2d").value
    jaxmon.record_transfer(1024, "h2d")
    jaxmon.record_transfer(None, "h2d")  # no-op, never raises
    assert jaxmon.TRANSFER_BYTES.labels("h2d").value == d0 + 1024

    c0 = jaxmon.TRAIN_STEP_SECONDS.labels().count
    jaxmon.observe_train_step(0.01)
    assert jaxmon.TRAIN_STEP_SECONDS.labels().count == c0 + 1

    # device gauges: CPU may report nothing — must not raise either way
    assert jaxmon.update_device_memory_gauges() >= 0


def test_batch_predict_dense_counts_transfers():
    import numpy as np

    from predictionio_tpu.models import batch_predict_dense

    class Model:
        def predict_batch(self, feats):
            return np.asarray([f.sum() for f in feats])

    h0 = jaxmon.TRANSFER_BYTES.labels("h2d").value
    d0 = jaxmon.TRANSFER_BYTES.labels("d2h").value
    out = batch_predict_dense(Model(), [(0, {"features": [1.0, 2.0]}),
                                        (1, {"features": [3.0, 4.0]})])
    assert [v for _, v in out] == [3.0, 7.0]
    assert jaxmon.TRANSFER_BYTES.labels("h2d").value == h0 + 16  # 2x2 f32
    assert jaxmon.TRANSFER_BYTES.labels("d2h").value > d0


# ---------------------------------------------------------------------------
# satellites: Stats.report pruning, ServingStats on the shared histogram
# ---------------------------------------------------------------------------

def test_stats_report_prunes_stale_buckets_without_update():
    from predictionio_tpu.serving.stats import Stats, _hour_bucket

    s = Stats()
    stale = _hour_bucket() - _dt.timedelta(hours=5)
    s._buckets[stale][7][(201, "old", "user")] = 3
    s._buckets[_hour_bucket()][7][(201, "new", "user")] = 1
    report = s.report(7)
    hours = [b["hour"] for b in report["buckets"]]
    assert stale.isoformat() not in hours
    assert len(hours) == 1
    # pruned from memory too, not just filtered out of the report
    assert stale not in s._buckets


def test_serving_stats_reports_from_shared_histogram():
    from predictionio_tpu.serving.engine_server import (
        _SERVING_SECONDS,
        ServingStats,
    )

    st = ServingStats("obs-hist-engine")
    for v in [0.001] * 50 + [0.2] * 50:
        st.record(v)
    assert st.request_count == 100
    assert st.total_serving_sec == pytest.approx(0.05 + 10.0)
    snap = st.snapshot()
    assert snap["requestCount"] == 100
    assert snap["lastServingSec"] == 0.2
    # bucket-interpolated percentiles from the SAME series /metrics shows
    assert 0.0005 <= snap["p50ServingSec"] <= 0.0025
    assert 0.1 <= snap["p99ServingSec"] <= 0.25
    assert st.recent(3) == [0.2, 0.2, 0.2]
    child = _SERVING_SECONDS.labels("obs-hist-engine")
    assert child.count == 100
    # a second ServingStats for the same engine (a fleet replica, or a
    # restarted in-process server) starts ITS OWN counts from zero but
    # keeps recording into the SAME engine-wide registry series — the
    # SLO burn rate and shedding must see every replica's traffic
    fresh = ServingStats("obs-hist-engine")
    assert fresh.request_count == 0
    assert _SERVING_SECONDS.labels("obs-hist-engine").count == 100
    fresh.record(0.2)
    assert fresh.request_count == 1
    assert st.request_count == 100  # the older server's view is per-server
    assert _SERVING_SECONDS.labels("obs-hist-engine").count == 101


# ---------------------------------------------------------------------------
# pio metrics CLI
# ---------------------------------------------------------------------------

def test_pio_metrics_cli_remote_and_local(event_server, capsys):
    from predictionio_tpu.tools.cli import main

    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}"
    assert main(["metrics", "--url", base]) == 0
    out = capsys.readouterr().out
    assert "# TYPE pio_http_requests_total counter" in out
    assert_valid_prometheus(out)

    assert main(["metrics"]) == 0
    out = capsys.readouterr().out
    assert "pio_jax_compile_cache_total" in out
