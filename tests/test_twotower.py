"""Two-tower retrieval: compute core + DASE template + hybrid serving."""

import numpy as np
import pytest

from predictionio_tpu.models.twotower import TwoTowerAlgorithm, TwoTowerParams
from predictionio_tpu.ops.twotower import (
    TwoTowerConfig,
    TwoTowerTrainer,
    twotower_train,
)
from predictionio_tpu.parallel.mesh import MeshContext, create_mesh
from predictionio_tpu.templates.twotower import (
    ItemScoreAverageServing,
    twotower_engine,
    twotower_hybrid_engine,
)
from predictionio_tpu.workflow.deploy import prepare_deploy
from predictionio_tpu.workflow.train import run_train

from tests.test_als import _seed_events


def _block_positives(n_users=40, n_items=16, per_user=6, seed=0):
    """Users 0..n/2 interact with items 0..n/2, rest with the other half."""
    rng = np.random.default_rng(seed)
    u, i = [], []
    half_u, half_i = n_users // 2, n_items // 2
    for user in range(n_users):
        lo, hi = (0, half_i) if user < half_u else (half_i, n_items)
        for item in rng.integers(lo, hi, size=per_user):
            u.append(user)
            i.append(item)
    return np.array(u), np.array(i), n_users, n_items


def test_twotower_loss_decreases_and_learns_blocks():
    u, i, n_users, n_items = _block_positives()
    cfg = TwoTowerConfig(dim=8, epochs=30, batch_size=64, learning_rate=1e-2, seed=1)
    emb = twotower_train((u, i, None), n_users, n_items, cfg)
    assert emb.losses[-1] < emb.losses[0]
    # vectors are L2-normalized
    norms = np.linalg.norm(emb.item_vecs, axis=1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-4)
    # block structure: user 0's best items should be in the first half
    scores = emb.item_vecs @ emb.user_vecs[0]
    top4 = np.argsort(-scores)[:4]
    assert sum(1 for t in top4 if t < n_items // 2) >= 3


def test_twotower_on_mesh_dp():
    u, i, n_users, n_items = _block_positives(n_users=24, n_items=8, per_user=4)
    mesh = create_mesh({"data": 8})
    cfg = TwoTowerConfig(dim=4, epochs=3, batch_size=32, seed=2)
    emb = twotower_train((u, i, None), n_users, n_items, cfg, mesh=mesh)
    assert emb.user_vecs.shape == (n_users, 4)
    assert np.all(np.isfinite(emb.user_vecs))


def test_twotower_sharded_embeddings_tp():
    """Row-sharding embedding tables over the model axis (TP) must
    produce finite, normalized embeddings identical in shape."""
    u, i, n_users, n_items = _block_positives(n_users=16, n_items=8, per_user=4)
    mesh = create_mesh({"data": 4, "model": 2})
    cfg = TwoTowerConfig(dim=4, epochs=2, batch_size=16, seed=3, shard_embeddings=True)
    trainer = TwoTowerTrainer((u, i, None), n_users, n_items, cfg, mesh=mesh)
    losses = trainer.run()
    emb = trainer.embeddings(losses)
    assert emb.item_vecs.shape == (n_items, 4)
    assert np.all(np.isfinite(emb.item_vecs))


def test_twotower_template_end_to_end(memory_storage):
    _seed_events(memory_storage, "tt-app")
    engine = twotower_engine()
    ep = engine.engine_params_from_variant({
        "engineFactory": "predictionio_tpu.templates.twotower.twotower_engine",
        "datasource": {"name": "", "params": {"app_name": "tt-app"}},
        "algorithms": [{"name": "twotower", "params": {
            "dim": 8, "epochs": 25, "batch_size": 64, "learning_rate": 1e-2,
            "min_rating": 3.0}}],
    })
    ctx = MeshContext(mesh=create_mesh({"data": 8}))
    instance = run_train(engine, ep, engine_id="tt", storage=memory_storage, ctx=ctx)
    assert instance.status == "COMPLETED"

    deployment = prepare_deploy(engine, instance, ctx, memory_storage)
    result = deployment.query({"user": "u3", "num": 4})
    items = [r["item"] for r in result["itemScores"]]
    assert len(items) == 4
    # u3 rates block-0 items 5.0 and block-1 items 1.0; min_rating=3 keeps
    # only the positives, so recommendations should be block-0 heavy
    assert sum(1 for i in items if int(i[1:]) < 6) >= 3
    assert deployment.query({"user": "nobody", "num": 3}) == {"itemScores": []}


def test_twotower_batch_predict_matches_predict(memory_storage):
    _seed_events(memory_storage, "tt-bp")
    engine = twotower_engine()
    ep = engine.engine_params_from_variant({
        "engineFactory": "x",
        "datasource": {"name": "", "params": {"app_name": "tt-bp"}},
        "algorithms": [{"name": "twotower", "params": {
            "dim": 4, "epochs": 4, "batch_size": 32}}],
    })
    result = engine.train(MeshContext(), ep)
    algo = engine.make_algorithms(ep)[0]
    model = result.models[0]
    queries = [(0, {"user": "u1", "num": 3}), (1, {"user": "nobody", "num": 3})]
    batch = dict(algo.batch_predict(model, queries))
    assert [r["item"] for r in batch[0]["itemScores"]] == \
        [r["item"] for r in algo.predict(model, {"user": "u1", "num": 3})["itemScores"]]
    assert batch[1] == {"itemScores": []}


def test_hybrid_engine_averages_scores(memory_storage):
    _seed_events(memory_storage, "tt-hybrid")
    engine = twotower_hybrid_engine()
    ep = engine.engine_params_from_variant({
        "engineFactory": "predictionio_tpu.templates.twotower.twotower_hybrid_engine",
        "datasource": {"name": "", "params": {"app_name": "tt-hybrid"}},
        "algorithms": [
            {"name": "als", "params": {"rank": 4, "num_iterations": 4, "block_size": 32}},
            {"name": "twotower", "params": {"dim": 4, "epochs": 4, "batch_size": 32}},
        ],
    })
    ctx = MeshContext()
    instance = run_train(engine, ep, engine_id="tt-h", storage=memory_storage, ctx=ctx)
    assert instance.status == "COMPLETED"
    deployment = prepare_deploy(engine, instance, ctx, memory_storage)
    result = deployment.query({"user": "u1", "num": 3})
    assert len(result["itemScores"]) == 3
    scores = [r["score"] for r in result["itemScores"]]
    assert scores == sorted(scores, reverse=True)


def test_item_score_average_serving_merges():
    serving = ItemScoreAverageServing()
    out = serving.serve(
        {"num": 2},
        [
            {"itemScores": [{"item": "a", "score": 1.0}, {"item": "b", "score": 0.5}]},
            {"itemScores": [{"item": "a", "score": 0.0}, {"item": "c", "score": 0.9}]},
        ],
    )
    assert out["itemScores"][0] == {"item": "a", "score": 0.5}
    # c only appears in one algorithm: (0 + 0.9) / 2 — and it outranks b
    assert out["itemScores"][1] == {"item": "c", "score": 0.45}


def test_min_rating_filters_all_raises():
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.als import PreparedRatings

    pd = PreparedRatings(
        user_ids=BiMap.string_int(["u"]), item_ids=BiMap.string_int(["i"]),
        user_idx=np.array([0]), item_idx=np.array([0]),
        ratings=np.array([1.0], dtype=np.float32),
    )
    algo = TwoTowerAlgorithm(TwoTowerParams(min_rating=3.0))
    with pytest.raises(ValueError, match="nothing to train"):
        algo.train(MeshContext(), pd)


import pytest


@pytest.mark.parametrize("cdt_name,l_rtol,g_rtol,g_atol", [
    ("float32", 1e-5, 1e-4, 1e-6),
    # the production default: bf16 tile logits quantize all three
    # forms identically in fwd (and the VJP recomputes logits with the
    # SAME rounding in bwd), so they still track each other closely
    # measured deltas: grads differ by <=~1.1e-3 absolute at 0.067
    # scale (bf16 logit quantization under different summation orders;
    # the VJP and autodiff losses agree bit-exactly with each other)
    ("bfloat16", 5e-3, 1e-1, 2e-3),
])
def test_blockwise_ce_matches_dense(cdt_name, l_rtol, g_rtol, g_atol):
    """The flash-style blockwise in-batch CE must agree with the dense
    reference — loss AND gradients — including duplicate users/items
    in-batch and zero-weight padding rows, in BOTH compute dtypes."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops.twotower import (
        _blockwise_softmax_ce,
        _blockwise_softmax_ce_autodiff,
        _dense_softmax_ce,
    )

    rng = np.random.default_rng(9)
    B, D = 256, 16
    u = rng.normal(size=(B, D)).astype(np.float32)
    v = rng.normal(size=(B, D)).astype(np.float32)
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    u_idx = rng.integers(0, 60, B).astype(np.int32)   # many duplicates
    i_idx = rng.integers(0, 40, B).astype(np.int32)
    w = np.ones(B, np.float32)
    w[-17:] = 0.0                                     # padding rows
    args = (jnp.asarray(u_idx), jnp.asarray(i_idx), jnp.asarray(w))

    cdt = jnp.dtype(cdt_name)

    def dense(u_, v_):
        return _dense_softmax_ce(u_, v_, *args, 0.07, cdt)

    def block(u_, v_):
        return _blockwise_softmax_ce(u_, v_, *args, 0.07, 64, cdt)

    def block_ad(u_, v_):
        return _blockwise_softmax_ce_autodiff(u_, v_, *args, 0.07, 64, cdt)

    ld, (gdu, gdv) = jax.value_and_grad(dense, argnums=(0, 1))(
        jnp.asarray(u), jnp.asarray(v))
    lb, (gbu, gbv) = jax.value_and_grad(block, argnums=(0, 1))(
        jnp.asarray(u), jnp.asarray(v))
    np.testing.assert_allclose(float(lb), float(ld), rtol=l_rtol)
    np.testing.assert_allclose(np.asarray(gbu), np.asarray(gdu),
                               rtol=g_rtol, atol=g_atol)
    np.testing.assert_allclose(np.asarray(gbv), np.asarray(gdv),
                               rtol=g_rtol, atol=g_atol)
    # the checkpoint-autodiff formulation agrees too (it is the
    # reference the hand-written VJP replaced)
    la, (gau, gav) = jax.value_and_grad(block_ad, argnums=(0, 1))(
        jnp.asarray(u), jnp.asarray(v))
    np.testing.assert_allclose(float(la), float(ld), rtol=l_rtol)
    np.testing.assert_allclose(np.asarray(gbu), np.asarray(gau),
                               rtol=g_rtol, atol=g_atol)
    np.testing.assert_allclose(np.asarray(gbv), np.asarray(gav),
                               rtol=g_rtol, atol=g_atol)


def test_blockwise_ce_trains_end_to_end():
    """A trainer configured to engage the blockwise loss must still
    learn (loss decreases over epochs)."""
    rng = np.random.default_rng(4)
    n_users, n_items, n = 300, 200, 4000
    block = rng.integers(0, 4, n)
    u = (block * 75 + rng.integers(0, 75, n)).astype(np.int64)
    i = (block * 50 + rng.integers(0, 50, n)).astype(np.int64)
    cfg = TwoTowerConfig(dim=8, epochs=12, batch_size=256, loss_chunk=64,
                         learning_rate=1e-2, seed=1)
    emb = twotower_train((u, i, None), n_users, n_items, cfg)
    assert emb.losses[-1] < emb.losses[0]
