"""Multi-host runtime helpers (single-process degenerate paths + the
pieces that are testable without real multi-process: stable hashing,
slice balance, global array assembly on the 8-device CPU mesh)."""

import numpy as np

from predictionio_tpu.parallel.mesh import create_mesh
from predictionio_tpu.parallel import multihost as mh


def test_initialize_without_env_is_single_process(monkeypatch):
    monkeypatch.delenv("PIO_COORDINATOR_ADDRESS", raising=False)
    assert mh.initialize_from_env() is False
    assert mh.process_count() == 1
    assert mh.process_index() == 0


def test_stable_hash_is_process_independent():
    # regression pin: must never fall back to the salted builtin hash
    assert mh._stable_hash("u1") == mh._stable_hash("u1")
    assert mh._stable_hash("u1") != mh._stable_hash("u2")


def test_host_shard_by_entity_partitions_completely():
    events = [{"eid": f"u{n}"} for n in range(100)]
    shards = [
        mh.host_shard_by_entity(events, lambda e: e["eid"], n_hosts=4, host=h)
        for h in range(4)
    ]
    total = [e["eid"] for s in shards for e in s]
    assert sorted(total) == sorted(e["eid"] for e in events)
    # same entity always lands on the same host
    again = mh.host_shard_by_entity(events, lambda e: e["eid"], n_hosts=4, host=2)
    assert [e["eid"] for e in again] == [e["eid"] for e in shards[2]]
    # single host keeps everything
    assert len(mh.host_shard_by_entity(events, lambda e: e["eid"],
                                       n_hosts=1, host=0)) == 100


def test_host_shard_slice_covers_and_balances():
    for n_total in (0, 1, 7, 8, 100):
        slices = [mh.host_shard_slice(n_total, n_hosts=3, host=h) for h in range(3)]
        covered = []
        for s in slices:
            covered.extend(range(s.start, s.stop))
        assert covered == list(range(n_total))
        sizes = [s.stop - s.start for s in slices]
        assert max(sizes) - min(sizes) <= 1


def test_global_array_single_host_shards_over_mesh():
    mesh = create_mesh({"data": 8})
    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    arr = mh.global_array(x, mesh, "data", None)
    assert arr.shape == (16, 4)
    np.testing.assert_array_equal(np.asarray(arr), x)
    # actually device-sharded: each of the 8 devices owns 2 rows
    assert len(arr.sharding.device_set) == 8


def test_all_hosts_sum_single_host_identity():
    mesh = create_mesh({"data": 8})
    x = np.array([3.0, 4.0])
    np.testing.assert_array_equal(mh.all_hosts_sum(x, mesh), x)
