"""DataMap / PropertyMap behavior (ref spec: data/.../storage/DataMapSpec.scala)."""

import datetime as dt

import pytest

from predictionio_tpu.data.datamap import DataMap, DataMapError, PropertyMap

UTC = dt.timezone.utc


def test_typed_get():
    d = DataMap({"a": 1, "b": "x", "c": 2.5, "d": True, "arr": [1, 2], "obj": {"k": 1}})
    assert d.get("a", int) == 1
    assert d.get("b", str) == "x"
    assert d.get("c", float) == 2.5
    assert d.get("a", float) == 1.0  # int widens to float
    assert d.get("d", bool) is True
    assert d.get("arr", list) == [1, 2]
    assert d.get("obj", dict) == {"k": 1}


def test_get_missing_raises():
    d = DataMap({"a": 1})
    with pytest.raises(DataMapError):
        d.get("nope")


def test_get_opt_and_or_else():
    d = DataMap({"a": 1})
    assert d.get_opt("a", int) == 1
    assert d.get_opt("missing") is None
    assert d.get_opt("missing", default=7) == 7
    assert d.get_or_else("missing", "x") == "x"


def test_type_mismatch():
    d = DataMap({"a": "str"})
    with pytest.raises(TypeError):
        d.get("a", int)


def test_merge_right_biased():
    a = DataMap({"x": 1, "y": 2})
    b = DataMap({"y": 3, "z": 4})
    assert a.merge(b).to_dict() == {"x": 1, "y": 3, "z": 4}


def test_remove_and_keyset():
    d = DataMap({"x": 1, "y": 2, "z": 3})
    assert d.remove(["y"]).keyset() == {"x", "z"}
    assert d.keyset() == {"x", "y", "z"}  # immutable


def test_json_roundtrip():
    d = DataMap({"a": 1, "b": [1, "two"], "c": {"n": None}})
    assert DataMap.from_json(d.to_json()) == d


def test_property_map_carries_times():
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    t1 = dt.datetime(2026, 1, 2, tzinfo=UTC)
    pm = PropertyMap({"a": 1}, first_updated=t0, last_updated=t1)
    assert pm.get("a", int) == 1
    assert pm.first_updated == t0
    assert pm.last_updated == t1
