"""Serving fleet: replica supervisor, health-routed query router and
the rolling zero-downtime hot-swap (serving/fleet.py,
serving/router.py), plus the shared SIGTERM drain handler
(serving/http.py) and the fleet keys' bench-compare gating.

Chaos comes through the PR-6 seams: ``ThreadedReplica.kill()`` dies
like a crashed process (listening socket closed abruptly), and the
``batcher@<replica>:hang`` tagged chaos rule hangs exactly one
replica's dispatch loop while its peers keep answering.
"""

from __future__ import annotations

import contextlib
import json
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.obs import metrics
from predictionio_tpu.resilience import chaos
from predictionio_tpu.resilience.admission import ShedDecision
from predictionio_tpu.serving import fleet as fleet_mod
from predictionio_tpu.serving.engine_server import EngineServer
from predictionio_tpu.serving.fleet import (DEAD, READY, FleetSupervisor,
                                            threaded_fleet)
from predictionio_tpu.serving.http import install_drain_handler
from predictionio_tpu.serving.router import QueryRouter

from tests.test_health import get, get_json, train_const


def post(url, body=b'{"mult": 2}', headers=None, timeout=15):
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


@contextlib.contextmanager
def running_fleet(storage, engine, n=3, probe_interval=0.05,
                  backoff=None, engine_name="const", **engine_kw):
    """N threaded const-engine replicas behind a router on an
    ephemeral port; yields (fleet, router, base_url). ``engine_name``
    labels the serving metrics — tests that assert on cumulative
    histograms pass a private name so earlier tests' observations
    (chaos hangs especially) don't sit in their tail."""
    def factory(name):
        return EngineServer(engine, engine_name, host="127.0.0.1",
                            port=0, storage=storage, max_batch=8,
                            chaos_tag=name, **engine_kw)

    fleet = FleetSupervisor(threaded_fleet(n, factory),
                            probe_interval=probe_interval,
                            backoff=backoff).start()
    router = None
    try:
        assert fleet.wait_ready(timeout=60), fleet.snapshot()
        router = QueryRouter(fleet, host="127.0.0.1", port=0).start()
        yield fleet, router, f"http://127.0.0.1:{router.port}"
    finally:
        chaos.clear()
        if router is not None:
            router.stop()
        fleet.stop()


def counter_value(name, *labels):
    family = metrics.REGISTRY.get(name)
    if family is None:
        return 0.0
    return family.labels(*labels).value if labels else family.value


# -- routing basics ------------------------------------------------------------

def test_fleet_starts_routes_and_balances(memory_storage, monkeypatch):
    """3 replicas come up READY, the router answers queries with the
    serving replica stamped, and placement spreads across replicas.
    Hedging is off: the per-replica counts must sum exactly to the
    queries sent, and a scheduling hiccup past the hedge floor would
    legitimately add a duplicate."""
    monkeypatch.setenv("PIO_HEDGE_QUANTILE", "0")
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine) as (fleet, router, base):
        served = set()
        for _ in range(24):
            status, body, headers = post(base + "/queries.json")
            assert status == 200, body
            assert json.loads(body) == {"result": 6.0}
            served.add(headers["X-PIO-Replica"])
        assert len(served) >= 2, served  # p2c spreads the load
        # per-replica request counts agree traffic reached >1 replica
        counts = {r.name: r.server.stats.request_count
                  for r in fleet.replicas}
        assert sum(counts.values()) == 24, counts
        # the operator surface sees the same fleet
        status, snap = get_json(base + "/admin/fleet")
        assert status == 200
        assert snap["ready"] == 3 and snap["size"] == 3
        assert {r["state"] for r in snap["replicas"]} == {READY}
        # router readiness mirrors the rotation
        status, ready = get_json(base + "/readyz")
        assert status == 200
        assert ready["probes"]["storage"]["status"] == "ok"


def test_router_503_when_nothing_in_rotation(memory_storage):
    """Admin drain empties the rotation: the router answers 503 +
    Retry-After (and readyz FAILED) instead of hanging; readmit
    restores service."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=1) as (fleet, router,
                                                        base):
        status, body, _ = post(
            base + "/admin/fleet", body=json.dumps({"drain": "r0"}).encode())
        assert status == 200, body
        status, body, headers = post(base + "/queries.json")
        assert status == 503, body
        assert headers["Retry-After"] == "1"
        status, _ = get_json(base + "/readyz")
        assert status == 503  # a router with no rotation is NOT ready
        status, body, _ = post(
            base + "/admin/fleet",
            body=json.dumps({"readmit": "r0"}).encode())
        assert status == 200, body
        assert fleet.wait_ready(timeout=10)
        status, _, _ = post(base + "/queries.json")
        assert status == 200


# -- satellite: shed/degraded passthrough --------------------------------------

def test_router_passes_through_shed_and_degraded(memory_storage,
                                                 monkeypatch):
    """A replica's 429 Retry-After travels to the client UN-retried
    (retrying shed traffic amplifies the overload), and the degraded
    stamp survives the router hop — both counted in
    pio_router_passthrough_total{reason}."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine) as (fleet, router, base):
        calls = {"n": 0}

        def always_shed():
            calls["n"] += 1
            return ShedDecision("queue_depth", 7, "test shed")

        for r in fleet.replicas:
            monkeypatch.setattr(r.server.admission, "check", always_shed)
        shed_before = counter_value("pio_router_passthrough_total", "shed")
        status, body, headers = post(base + "/queries.json")
        assert status == 429, body
        assert headers["Retry-After"] == "7"
        assert json.loads(body)["reason"] == "queue_depth"
        # exactly ONE replica was consulted: the shed was not retried
        assert calls["n"] == 1
        assert counter_value("pio_router_passthrough_total",
                             "shed") == shed_before + 1

        for r in fleet.replicas:
            monkeypatch.undo()
        # degraded mode: open every replica's storage circuit; the
        # query still answers, stamped, through the router
        for r in fleet.replicas:
            r.server._storage_breaker.record_failure()
            r.server._storage_breaker.record_failure()
        deg_before = counter_value("pio_router_passthrough_total",
                                   "degraded")
        status, body, headers = post(base + "/queries.json")
        assert status == 200, body
        assert "last-loaded instance" in headers["X-PIO-Degraded"]
        assert counter_value("pio_router_passthrough_total",
                             "degraded") == deg_before + 1


# -- satellite: hedging pins the tail ------------------------------------------

def test_hedge_rescues_hung_replica(memory_storage, monkeypatch):
    """A chaos-hung replica no longer sets the measured p99: once the
    reply exceeds the trailing-quantile hedge deadline, a second
    request races on the healthy replica and answers in milliseconds
    instead of the hang's seconds."""
    monkeypatch.setenv("PIO_HEDGE_MIN_MS", "40")
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2) as (fleet, router,
                                                        base):
        # warm the trailing window past HedgeClock.min_samples
        for _ in range(25):
            status, _, _ = post(base + "/queries.json")
            assert status == 200
        assert router.hedge.deadline() is not None
        hedges_before = counter_value("pio_router_hedges_total")
        chaos.configure("batcher@r1:hang:2s")
        latencies = []
        for _ in range(12):
            t0 = time.perf_counter()
            status, body, _ = post(base + "/queries.json")
            latencies.append(time.perf_counter() - t0)
            assert status == 200, body
        chaos.clear()
        # the hang is 2s; every answer must have beaten it by far
        assert sorted(latencies)[-1] < 1.5, latencies
        assert counter_value("pio_router_hedges_total") > hedges_before


def test_hedged_shed_answer_defers_to_primary_success(memory_storage,
                                                      monkeypatch):
    """A hedge that lands on a shedding replica answers 429 in
    sub-milliseconds — long before the slow primary it was meant to
    rescue. That racer answer must NOT win the race: the router holds
    it and returns the primary's eventual 200 (hedging exists to cut
    the tail, not to convert would-be successes into client-visible
    errors)."""
    monkeypatch.setenv("PIO_HEDGE_MIN_MS", "40")
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2) as (fleet, router,
                                                        base):
        for _ in range(25):  # warm the trailing window
            status, _, _ = post(base + "/queries.json")
            assert status == 200
        assert router.hedge.deadline() is not None
        shedder = next(r for r in fleet.replicas if r.name == "r1")
        monkeypatch.setattr(
            shedder.server.admission, "check",
            lambda: ShedDecision("queue_depth", 1, "test shed"))
        chaos.configure("batcher@r0:hang:2s")
        hedged = False
        # p2c places ~half the queries on the hung r0; the first 2s
        # success then trains the hedge clock past the hang, so only
        # the earliest r0 placements hedge — stop at the first one
        for _ in range(12):
            before = counter_value("pio_router_hedges_total")
            status, body, headers = post(base + "/queries.json")
            assert status in (200, 429), body
            if counter_value("pio_router_hedges_total") > before:
                # the hedge raced r1's instant 429 and lost on purpose:
                # the hung primary's 200 is the client's answer
                assert status == 200, body
                assert headers["X-PIO-Replica"] == "r0"
                hedged = True
                break
        chaos.clear()
        assert hedged, "no query ever hedged"


# -- acceptance: chaos kill + hang + rolling swap ------------------------------

def test_fleet_chaos_acceptance(memory_storage, monkeypatch):
    """The tier-1 acceptance story: 3 replicas under chaos — one
    killed, one hung — serve a continuous query load with ZERO
    non-429 errors; the supervisor restarts the dead replica under
    backoff; a rolling hot-swap onto a freshly trained instance
    completes while queries keep answering and the fleet never drops
    below 2 ready replicas."""
    monkeypatch.setenv("PIO_HEDGE_MIN_MS", "50")
    monkeypatch.setenv("PIO_DRAIN_TIMEOUT", "5")
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine) as (fleet, router, base):
        for _ in range(30):  # arm the hedge clock
            status, _, _ = post(base + "/queries.json")
            assert status == 200

        results = []
        failures = []
        stop_evt = threading.Event()

        def loader():
            while not stop_evt.is_set():
                try:
                    status, body, _ = post(base + "/queries.json")
                    results.append(status)
                    if status not in (200, 429):
                        failures.append((status, body[:200]))
                except Exception as e:  # noqa: BLE001 — a transport
                    # error IS the outage the fleet must prevent
                    failures.append(("transport", repr(e)))

        threads = [threading.Thread(target=loader) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            # chaos: hang r1's dispatch loop, crash r0 outright
            chaos.configure("batcher@r1:hang:2s")
            victim = fleet.replicas[0]
            victim.kill()
            time.sleep(1.0)
            # the supervisor restarts the dead replica
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and victim.state != READY:
                time.sleep(0.05)
            assert victim.state == READY, fleet.snapshot()
            assert victim.restarts >= 1
            assert counter_value("pio_fleet_restarts_total", "r0") >= 1
            chaos.clear()

            # rolling hot-swap to a NEW trained instance, sampling the
            # ready floor throughout
            _, new_instance = train_const(memory_storage)
            min_ready = [fleet.size()]
            swap_done = threading.Event()

            def sampler():
                while not swap_done.is_set():
                    min_ready.append(fleet.ready_count())
                    time.sleep(0.01)

            sample_thread = threading.Thread(target=sampler)
            sample_thread.start()
            try:
                result = fleet.rolling_reload()
            finally:
                swap_done.set()
                sample_thread.join(timeout=5)
            assert result["outcome"] == "ok", result
            assert sorted(result["swapped"]) == ["r0", "r1", "r2"]
            assert min(min_ready) >= 2, min(min_ready)
            assert fleet.version() == new_instance.id
            for r in fleet.replicas:
                assert r.version == new_instance.id
        finally:
            stop_evt.set()
            for t in threads:
                t.join(timeout=30)
        assert not failures, failures[:5]
        assert results.count(200) > 50, len(results)
        # queries answered THROUGH the swap window, not just before it
        status, _, _ = post(base + "/queries.json")
        assert status == 200


@pytest.mark.slow
def test_fleet_kill_swap_soak(memory_storage, monkeypatch):
    """Soak: 3 replica kills and 2 rolling swaps under continuous
    load, zero non-429 errors end to end."""
    monkeypatch.setenv("PIO_HEDGE_MIN_MS", "50")
    monkeypatch.setenv("PIO_DRAIN_TIMEOUT", "5")
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine) as (fleet, router, base):
        failures = []
        answered = []
        stop_evt = threading.Event()

        def loader():
            while not stop_evt.is_set():
                try:
                    status, body, _ = post(base + "/queries.json")
                    answered.append(status)
                    if status not in (200, 429):
                        failures.append((status, body[:200]))
                except Exception as e:  # noqa: BLE001
                    failures.append(("transport", repr(e)))

        threads = [threading.Thread(target=loader) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for round_no in range(3):
                victim = fleet.replicas[round_no % fleet.size()]
                restarts_before = victim.restarts
                victim.kill()
                # right after kill() the state is STILL READY (the
                # supervisor needs consecutive probe failures to
                # notice): wait for the restart, THEN for readiness
                deadline = time.monotonic() + 60
                while (time.monotonic() < deadline
                       and not (victim.restarts > restarts_before
                                and victim.state == READY)):
                    time.sleep(0.05)
                assert victim.restarts > restarts_before, fleet.snapshot()
                assert victim.state == READY, fleet.snapshot()
                if round_no < 2:
                    train_const(memory_storage)
                    result = fleet.rolling_reload()
                    assert result["outcome"] == "ok", result
        finally:
            stop_evt.set()
            for t in threads:
                t.join(timeout=30)
        assert not failures, failures[:5]
        assert answered.count(200) > 100


# -- supervisor: restart backoff -----------------------------------------------

def test_supervisor_restart_backoff_schedule(memory_storage):
    """Crash-looping replicas back off: the supervisor consults the
    backoff schedule with an INCREASING attempt number (reset only
    after a stable period), and each restart lands in
    pio_fleet_restarts_total."""
    engine, _ = train_const(memory_storage)
    attempts = []

    def recording_backoff(attempt):
        attempts.append(attempt)
        return 0.05

    with running_fleet(memory_storage, engine, n=2,
                       backoff=recording_backoff) as (fleet, _, base):
        victim = fleet.replicas[0]
        # the counter is process-global and replica names recur across
        # fleets (tests included): assert the delta, not the absolute
        restarts_before = counter_value("pio_fleet_restarts_total", "r0")
        for expected_restarts in (1, 2):
            victim.kill()
            deadline = time.monotonic() + 30
            while (time.monotonic() < deadline
                   and victim.restarts < expected_restarts):
                time.sleep(0.02)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and victim.state != READY:
                time.sleep(0.02)
            assert victim.state == READY, fleet.snapshot()
        assert victim.restarts == 2
        # second crash inside the stable window -> attempt number grew
        assert attempts[:2] == [0, 1], attempts
        assert counter_value("pio_fleet_restarts_total",
                             "r0") == restarts_before + 2.0


def test_drained_replica_crash_is_detected(memory_storage):
    """A drain parks a replica out of rotation, but the supervisor
    still notices when its process dies while parked: the replica goes
    DEAD and restarts instead of reading "draining" (with a
    live-looking port) forever."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2,
                       backoff=lambda attempt: 0.05) as (fleet, _, base):
        status, body, _ = post(
            base + "/admin/fleet", body=json.dumps({"drain": "r0"}).encode())
        assert status == 200, body
        victim = fleet.replicas[0]
        # die like a crashed process: the listening socket closes but
        # the server object stays in place (process_alive must see
        # through it — a bare object-presence check reads "draining"
        # forever here)
        victim.kill()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and victim.restarts < 1:
            time.sleep(0.02)
        assert victim.restarts >= 1, fleet.snapshot()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and victim.state != READY:
            time.sleep(0.02)
        assert victim.state == READY, fleet.snapshot()


def test_probe_verdict_cannot_overwrite_a_concurrent_drain(memory_storage):
    """The residual probe-vs-drain race, BOTH probe outcomes: a state
    write landing after probe_and_update's re-check must lose to a
    concurrent DRAINING. A green probe readmitting straight to READY
    was already guarded; a failed probe flipping the drained replica
    to EVICTED is the same bug one hop removed — the next green probe
    readmits from EVICTED. Deliberate transitions (the swap's and the
    admin readmit) still pass."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2,
                       probe_interval=1.0) as (fleet, _, _base):
        replica = fleet.replicas[0]
        fleet._set_state(replica, fleet_mod.DRAINING, deliberate=True)
        # probe-driven writes (the racy post-re-check ones) lose
        fleet._set_state(replica, fleet_mod.EVICTED)
        assert replica.state == fleet_mod.DRAINING, fleet.snapshot()
        fleet._set_state(replica, READY)
        assert replica.state == fleet_mod.DRAINING, fleet.snapshot()
        # the operator's / the swap's readmit is deliberate and wins
        fleet._set_state(replica, fleet_mod.EVICTED, deliberate=True)
        assert replica.state == fleet_mod.EVICTED, fleet.snapshot()


def test_stop_fences_straggling_swap_writes(memory_storage):
    """A rolling-swap thread can outlive stop() (it checks the stop
    event only between replicas, and one replica's reload can block
    for minutes): its late writes must not flip a STOPPED replica back
    or re-mint the per-replica gauge children stop() retired — a later
    fleet in the same process would inherit phantom replica series."""
    engine, _ = train_const(memory_storage)

    def factory(name):
        return EngineServer(engine, "const", host="127.0.0.1", port=0,
                            storage=memory_storage, max_batch=8,
                            chaos_tag=name)

    fleet = FleetSupervisor(threaded_fleet(2, factory),
                            probe_interval=0.05).start()
    try:
        assert fleet.wait_ready(timeout=60), fleet.snapshot()
    finally:
        fleet.stop()
    r0 = fleet.replicas[0]
    assert r0.state == fleet_mod.STOPPED
    # exactly what a straggling swap thread would do next:
    fleet._set_state(r0, fleet_mod.DRAINING, deliberate=True)
    fleet._set_state(r0, fleet_mod.EVICTED, deliberate=True)
    fleet._refresh_version(r0)
    assert r0.state == fleet_mod.STOPPED, fleet.snapshot()
    up = metrics.REGISTRY.get("pio_fleet_replica_up")
    names = {vals[0] for vals, _ in (up.children() if up else [])}
    assert r0.name not in names, names
    # and no NEW swap can start against a stopped fleet
    assert not fleet.start_rolling_reload()


# -- admin surface -------------------------------------------------------------

def test_admin_fleet_auth_and_reload_control(memory_storage, monkeypatch):
    """/admin/fleet honors the PIO_ADMIN_TOKEN bearer gate like every
    admin route; POST {"reload": true} answers 202 and runs a swap."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2) as (fleet, router,
                                                        base):
        monkeypatch.setenv("PIO_ADMIN_TOKEN", "s3cret")
        status, _, _ = get(base + "/admin/fleet")
        assert status == 401
        # GET /reload triggers the same fleet-wide swap as the gated
        # admin route — it must sit behind the same bearer token
        status, _, _ = get(base + "/reload")
        assert status == 401
        # the public status page must not leak the byte-identical
        # fleet snapshot (ports, instance ids, probe verdicts) that
        # the token just gated one route over — aggregates only
        status, body, _ = get(base + "/")
        assert status == 200
        fleet_view = json.loads(body)["fleet"]
        assert fleet_view == {"size": 2, "ready": 2}
        auth = {"Authorization": "Bearer s3cret"}
        status, body, _ = get(base + "/admin/fleet", headers=auth)
        assert status == 200 and json.loads(body)["size"] == 2
        monkeypatch.delenv("PIO_ADMIN_TOKEN")

        train_const(memory_storage)
        status, body, _ = post(base + "/admin/fleet",
                               body=json.dumps({"reload": True}).encode())
        assert status == 202, body
        # while that swap runs, a second reload request answers 409 on
        # this route exactly like the router's GET /reload does
        status, body, _ = post(base + "/admin/fleet",
                               body=json.dumps({"reload": True}).encode())
        assert status == 409, body
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            _, snap = get_json(base + "/admin/fleet")
            if (not snap["swap"]["active"]
                    and snap["swap"]["last"] is not None):
                break
            time.sleep(0.05)
        assert snap["swap"]["last"]["outcome"] == "ok", snap
        # a no-fleet server 404s the route (negative case)
        status, _, _ = get(
            f"http://127.0.0.1:{fleet.replicas[0].port}/admin/fleet")
        assert status == 404


def test_admin_fleet_rejects_multiple_actions(memory_storage):
    """apply_admin runs exactly one action; a body carrying two (e.g.
    `pio fleet --drain r0 --readmit r1`) must answer 400 rather than
    run the first by precedence and silently drop the second."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2) as (fleet, _, base):
        status, body, _ = post(
            base + "/admin/fleet",
            body=json.dumps({"drain": "r0", "readmit": "r1"}).encode())
        assert status == 400, body
        assert "one action per call" in body
        # and neither action ran
        assert fleet.replicas[0].state == READY, fleet.snapshot()


# -- satellite: graceful SIGTERM drain -----------------------------------------

def test_drain_handler_finishes_inflight_requests(memory_storage):
    """The shared SIGTERM handler stops accepting, lets the in-flight
    query finish (it used to be dropped mid-response), then frees the
    port."""
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    base = f"http://127.0.0.1:{server.port}"
    handler = install_drain_handler(server)
    try:
        chaos.configure("batcher:latency:0.4")
        outcome = {}

        def slow_query():
            outcome["result"] = post(base + "/queries.json",
                                     b'{"mult": 3}')

        t = threading.Thread(target=slow_query)
        t.start()
        time.sleep(0.15)  # the query is inside the slowed dispatch
        handler()         # what SIGTERM would run
        t.join(timeout=10)
        status, body, _ = outcome["result"]
        assert status == 200 and json.loads(body) == {"result": 9.0}
        # drained and stopped: the port no longer accepts
        deadline = time.monotonic() + 5
        refused = False
        while time.monotonic() < deadline and not refused:
            try:
                post(base + "/queries.json", timeout=2)
            except (urllib.error.URLError, ConnectionError, OSError):
                refused = True
        assert refused
    finally:
        chaos.clear()
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        server.stop()


# -- dashboard + bench-compare satellites --------------------------------------

def test_dashboard_fleet_panel(memory_storage):
    from predictionio_tpu.tools.dashboard import DashboardServer

    dash = DashboardServer(storage=memory_storage, host="127.0.0.1",
                           port=0).start()
    base = f"http://127.0.0.1:{dash.port}"
    try:
        status, body, _ = get(base + "/fleet")
        assert status == 200 and "No fleet supervised" in body
        engine, _ = train_const(memory_storage)
        with running_fleet(memory_storage, engine, n=2) as (fleet, _, _b):
            status, body, _ = get(base + "/fleet")
            assert status == 200
            assert "r0" in body and "r1" in body and "2/2 ready" in body
        status, body, _ = get(base + "/")
        assert 'href="/fleet"' in body
    finally:
        dash.stop()


def test_benchcmp_gates_serve_and_fleet_keys(tmp_path):
    """key.serve_p99_ms and the fleet sweep keys are direction-aware:
    a p99 increase is a REGRESSION (exit 1), qps is higher-better."""
    import io

    from predictionio_tpu.tools import benchcmp

    assert benchcmp.lower_is_better("key.serve_p99_ms")
    assert benchcmp.lower_is_better("key.fleet_srv_p99_ms_128conn")
    assert not benchcmp.lower_is_better("key.fleet_qps_128conn")

    for n, p99 in ((1, 10.0), (2, 20.0)):
        (tmp_path / f"BENCH_r0{n}.json").write_text(json.dumps(
            {"parsed": {"metric": "m", "value": 1.0,
                        "key": {"serve_p99_ms": p99}}}))
    out = io.StringIO()
    rc = benchcmp.run([str(tmp_path / "BENCH_r01.json"),
                       str(tmp_path / "BENCH_r02.json")],
                      tolerance_pct=10.0, out=out)
    assert rc == 1
    assert "key.serve_p99_ms" in out.getvalue()
    assert "REGRESSION" in out.getvalue()


# -- tagged chaos --------------------------------------------------------------

def test_chaos_tag_scopes_rule_to_one_replica():
    """`batcher@r1` rules fire only for the tagged instance; untagged
    rules fire for everyone."""
    chaos.configure("batcher@r1:error:1")
    with pytest.raises(chaos.ChaosError):
        chaos.inject("batcher", tag="r1")
    chaos.inject("batcher", tag="r0")   # other tag: silent
    chaos.inject("batcher")             # untagged seam: silent
    chaos.configure("batcher:error:1")
    with pytest.raises(chaos.ChaosError):
        chaos.inject("batcher", tag="r0")  # untagged rule hits all tags
    chaos.clear()


# -- review regressions --------------------------------------------------------

def test_subprocess_argv_forces_single_server_children():
    """PIO_REPLICAS in the environment must not recurse into subprocess
    replicas (each child re-entering the fleet path is a fork bomb):
    the child argv pins --replicas 1 and the child env overrides the
    inherited variable."""
    from predictionio_tpu.serving.fleet import (SubprocessReplica,
                                                deploy_fleet_argv)

    argv = deploy_fleet_argv("engine.json")
    joined = " ".join(argv)
    assert "--replicas 1" in joined
    replica = SubprocessReplica("r0", argv)
    assert replica._env.get("PIO_REPLICAS", "1") == "1"


def test_probe_never_readmits_drained_replica(memory_storage):
    """A green /readyz must not overrule a deliberate drain: the
    monitor's probes and the swap's convergence waits leave DRAINING
    replicas out of rotation until an explicit readmit."""
    engine, _ = train_const(memory_storage)
    with running_fleet(memory_storage, engine, n=2) as (fleet, _, base):
        replica = fleet.replicas[0]
        status, body, _ = post(
            base + "/admin/fleet", body=json.dumps({"drain": "r0"}).encode())
        assert status == 200, body
        # direct probe + a few monitor cadences: still draining
        fleet.probe_and_update(replica)
        time.sleep(0.3)
        assert replica.state == "draining"
        # a rolling swap skips (not readmits) the operator-held replica
        # — and with r0 held, r1 is the ONLY replica in rotation, so
        # the swap refuses to drain it too (reloading it would take
        # ready to zero for the whole warm window)
        train_const(memory_storage)
        result = fleet.rolling_reload()
        assert replica.state == "draining"
        assert "operator-drained" in ";".join(result["errors"])
        assert "refusing to drain the fleet to zero" in ";".join(
            result["errors"])
        assert result["swapped"] == []


def test_fleet_stop_removes_timeline_collector(memory_storage):
    """A stopped fleet must deregister its timeline collector, or the
    timeline pins the supervisor (replicas, models and all) forever
    while its dead 0-ready samples clobber a successor fleet's."""
    from predictionio_tpu.obs import timeline as timeline_mod

    engine, _ = train_const(memory_storage)
    before = len(timeline_mod.TIMELINE._collectors)
    with running_fleet(memory_storage, engine, n=1):
        assert len(timeline_mod.TIMELINE._collectors) == before + 1
    assert len(timeline_mod.TIMELINE._collectors) == before


def test_chaos_clear_site_drops_tagged_rules():
    """clear("batcher") clears the whole seam including batcher@r1 —
    an operator clearing a seam means the seam, not one spelling."""
    chaos.configure("batcher:latency:10ms,batcher@r1:hang:5s,"
                    "storage:error:0.5")
    chaos.clear("batcher")
    assert [r.site for r in chaos.active()] == ["storage"]
    # exact site@tag clears one instance only
    chaos.configure("batcher@r1:hang:5s,batcher@r2:hang:5s")
    chaos.clear("batcher@r1")
    assert [r.site for r in chaos.active()] == ["batcher@r2"]
    chaos.clear()


def test_stale_pooled_connection_retries_fresh_without_breaker_charge():
    """A pooled keep-alive that died while idle is retried once on a
    fresh connection inside the client — the caller (and therefore the
    replica's breaker) never sees the stale-socket failure."""
    import http.client
    import socket

    from predictionio_tpu.serving.router import _ReplicaClient

    # a tiny HTTP listener that answers every connection's first request
    listener = socket.create_server(("127.0.0.1", 0))
    port = listener.getsockname()[1]
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            try:
                listener.settimeout(0.2)
                conn, _ = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn.recv(65536)
                conn.sendall(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Type: application/json\r\n"
                             b"Content-Length: 2\r\n\r\n{}")
            finally:
                conn.close()  # server-side close: pooled conn goes stale

    server_thread = threading.Thread(target=serve, daemon=True)
    server_thread.start()
    try:
        client = _ReplicaClient("127.0.0.1", port)
        status, data, _ = client.request("POST", "/queries.json", b"{}",
                                         {"Content-Type":
                                          "application/json"}, 5.0)
        assert status == 200
        # plant a STALE pooled connection: connected, then killed
        stale = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        stale.connect()
        stale.sock.close()
        client._idle.append(stale)
        # the request must silently fail over to a fresh connection
        status, data, _ = client.request("POST", "/queries.json", b"{}",
                                         {"Content-Type":
                                          "application/json"}, 5.0)
        assert status == 200 and data == b"{}"
        client.close()
    finally:
        stop.set()
        listener.close()
        server_thread.join(timeout=5)


# -- acceptance: chaos -> anomaly attributed -> recovery -> durable journal ----

def test_fleet_chaos_anomaly_journal_e2e(memory_storage, monkeypatch,
                                         tmp_path, capsys):
    """The ops-journal + sentinel acceptance loop end to end: injected
    batcher latency on ONE replica of a 2-replica fleet raises the
    fleet-wide serve p99, the sentinel detects the shift and attributes
    it to the chaos journal event, ``pio anomalies`` gates 1 while
    active and 0 after the ring turns over post-recovery, and the
    journal file outlives the fleet (read back torn-tail-safely, the
    restart-durability contract)."""
    from predictionio_tpu.obs import anomaly, journal
    from predictionio_tpu.obs import timeline as timeline_mod
    from predictionio_tpu.tools.cli import main as cli_main

    monkeypatch.setenv("PIO_HEDGE_QUANTILE", "0")  # no hedge rescue:
    # the injected latency must land in the histogram tail
    sink = tmp_path / "journal.jsonl"
    monkeypatch.setenv("PIO_JOURNAL_PATH", str(sink))
    # a fresh timeline focused on the serving p99 (the rate/staleness
    # collectors would add unrelated series whose test-paced samples
    # could alarm on their own); capacity 24 so the post-recovery ring
    # turns over inside the test
    tl = timeline_mod.Timeline(
        interval=0.0, capacity=24,
        collectors=[timeline_mod.quantile_collector(
            "pio_serving_request_seconds", 0.99, "serve_p99_ms",
            scale=1e3)])
    monkeypatch.setattr(timeline_mod, "TIMELINE", tl)
    # a private engine name: the shared cumulative histogram for
    # "const" carries earlier tests' chaos hangs in its tail, which
    # would bury this test's 250 ms injections
    series = "serve_p99_ms.journal_e2e"

    from predictionio_tpu.core import (Engine, FirstServing,
                                       IdentityPreparator)
    from predictionio_tpu.core.params import EngineParams
    from predictionio_tpu.workflow.train import run_train

    from tests.test_health import (ConstAlgo, ConstDataSource,
                                   ConstParams)

    engine = Engine(ConstDataSource, IdentityPreparator,
                    {"const": ConstAlgo}, FirstServing)
    run_train(engine, EngineParams(
        data_source_params=("", ConstParams(value=1.0)),
        preparator_params=("", None),
        algorithm_params_list=[("const", ConstParams(value=2.0))],
        serving_params=("", None)),
        engine_id="journal_e2e", storage=memory_storage)
    with running_fleet(memory_storage, engine, n=2,
                       engine_name="journal_e2e") as (fleet, router,
                                                      base):
        for _ in range(16):
            status, body, _ = post(base + "/queries.json")
            assert status == 200, body
            tl.sample(now=time.time())
        report = anomaly.SENTINEL.scan(now=time.time())
        assert series not in report["active"], report  # calm baseline

        chaos.configure("batcher@r1:latency:250ms")  # journals "chaos"
        for _ in range(8):
            status, body, _ = post(base + "/queries.json", timeout=30)
            assert status == 200, body
            tl.sample(now=time.time())
        report = anomaly.SENTINEL.scan(now=time.time())
        assert series in report["active"], report
        verdict = report["active"][series]
        assert verdict["direction"] == "up"
        assert verdict["cause"]["kind"] == "chaos", verdict
        assert counter_value("pio_anomaly_active", series) == 1.0
        assert cli_main(["anomalies"]) == 1
        out = capsys.readouterr().out
        assert series in out and "chaos" in out

        chaos.clear()
        for _ in range(30):
            status, body, _ = post(base + "/queries.json")
            assert status == 200, body
            tl.sample(now=time.time())
        report = anomaly.SENTINEL.scan(now=time.time())
        assert series not in report["active"], report
        assert counter_value("pio_anomaly_active", series) == 0.0
        assert cli_main(["anomalies"]) == 0
        assert "no active anomalies" in capsys.readouterr().out

    assert journal.JOURNAL.flush(timeout=10.0)
    events, corrupt = journal.read_back(str(sink))
    assert corrupt == 0
    kinds = [e["kind"] for e in events]
    for expected in ("replica_state", "chaos", "anomaly",
                     "anomaly_resolved"):
        assert expected in kinds, kinds
    onset = next(e for e in events if e["kind"] == "anomaly")
    assert onset["series"] == series
    assert onset["cause_kind"] == "chaos"
    # a restarted process (fresh Journal over the same path) appends to
    # the same history
    fresh = journal.Journal()
    fresh.emit("reload", instance="post-restart")
    assert fresh.flush(timeout=10.0)
    events2, _ = journal.read_back(str(sink))
    assert len(events2) == len(events) + 1
    assert events2[-1]["instance"] == "post-restart"
    fresh.reset()
