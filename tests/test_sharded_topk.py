"""Sharded top-k serving: item factors row-sharded over the mesh,
per-shard top-k + all-gather merge (ops.topk.make_sharded_topk).
Runs on the 8-device CPU mesh; results must match the single-device
TopKScorer exactly."""

import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.topk import TopKScorer, make_sharded_topk
from predictionio_tpu.parallel.mesh import create_mesh, named_sharding

import jax


def _setup(n_items=256, rank=16, batch=4, seed=0):
    rng = np.random.default_rng(seed)
    items = rng.normal(size=(n_items, rank)).astype(np.float32)
    users = rng.normal(size=(batch, rank)).astype(np.float32)
    return users, items


def test_sharded_matches_single_device():
    users, items = _setup()
    mesh = create_mesh({"data": 8})
    k = 10
    fn = make_sharded_topk(mesh, "data", items.shape[0], k)
    sharded_items = jax.device_put(
        jnp.asarray(items), named_sharding(mesh, "data", None))
    excl = np.full((users.shape[0], 4), -1, dtype=np.int32)
    s_scores, s_idx = fn(jnp.asarray(users), sharded_items, jnp.asarray(excl))

    ref_scores, ref_idx = TopKScorer(items).score(users, k)
    np.testing.assert_array_equal(np.asarray(s_idx), ref_idx)
    np.testing.assert_allclose(np.asarray(s_scores), ref_scores, rtol=1e-5)


def test_sharded_respects_global_exclusions():
    users, items = _setup(batch=2)
    mesh = create_mesh({"data": 8})
    k = 5
    fn = make_sharded_topk(mesh, "data", items.shape[0], k)
    sharded_items = jax.device_put(
        jnp.asarray(items), named_sharding(mesh, "data", None))

    # exclude each row's unrestricted top-1 (global ids across shards)
    _, base_idx = fn(jnp.asarray(users), sharded_items,
                     jnp.full((2, 1), -1, np.int32))
    excl = np.asarray(base_idx)[:, :1].astype(np.int32)
    _, idx2 = fn(jnp.asarray(users), sharded_items, jnp.asarray(excl))
    for b in range(2):
        assert excl[b, 0] not in np.asarray(idx2)[b]

    ref_scores, ref_idx = TopKScorer(items).score(users, k, exclude_idx=excl)
    np.testing.assert_array_equal(np.asarray(idx2), ref_idx)


def test_k_larger_than_shard_slab():
    # k > I/n exercises the k_loc = I/n clamp
    users, items = _setup(n_items=64, batch=2)
    mesh = create_mesh({"data": 8})  # slab = 8 rows < k = 12
    k = 12
    fn = make_sharded_topk(mesh, "data", items.shape[0], k)
    sharded_items = jax.device_put(
        jnp.asarray(items), named_sharding(mesh, "data", None))
    excl = np.full((2, 1), -1, np.int32)
    s_scores, s_idx = fn(jnp.asarray(users), sharded_items, jnp.asarray(excl))
    ref_scores, ref_idx = TopKScorer(items).score(users, k)
    np.testing.assert_array_equal(np.asarray(s_idx), ref_idx)


def test_sharded_scorer_class_with_padding():
    # 250 items over 8 shards forces zero-row padding; padded rows must
    # never appear even for users whose true scores are all negative
    users, items = _setup(n_items=250, batch=3, seed=2)
    users[0] = -np.abs(users[0])  # strongly negative scores likely
    mesh = create_mesh({"data": 8})
    from predictionio_tpu.ops.topk import ShardedTopKScorer

    sharded = ShardedTopKScorer(items, mesh)
    ref = TopKScorer(items)
    for k in (5, 40):
        s_s, s_i = sharded.score(users, k)
        r_s, r_i = ref.score(users, k)
        assert (s_i < 250).all()
        np.testing.assert_array_equal(s_i, r_i)
        np.testing.assert_allclose(s_s, r_s, rtol=1e-5)


def test_als_model_sharded_serving_parity():
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.als import ALSModel
    from predictionio_tpu.ops.als import ALSFactors

    rng = np.random.default_rng(3)
    n_users, n_items, rank = 6, 40, 8
    factors = ALSFactors(
        user_factors=rng.normal(size=(n_users, rank)).astype(np.float32),
        item_factors=rng.normal(size=(n_items, rank)).astype(np.float32),
    )
    uids = BiMap.string_int([f"u{i}" for i in range(n_users)])
    iids = BiMap.string_int([f"i{i}" for i in range(n_items)])
    model = ALSModel(factors, uids, iids)
    base = model.recommend("u2", 5, exclude_items=["i3", "i7"])

    model.enable_sharded_serving(create_mesh({"data": 8}))
    sharded = model.recommend("u2", 5, exclude_items=["i3", "i7"])
    assert [i for i, _ in sharded] == [i for i, _ in base]


def test_sharded_serving_survives_persistence_roundtrip():
    """Pickled models re-enable sharded serving at load time
    (ALSAlgorithm.load_persistent_model) instead of silently reverting
    to a single-device scorer."""
    import pickle

    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models.als import ALSAlgorithm, ALSModel, ALSParams
    from predictionio_tpu.ops.als import ALSFactors
    from predictionio_tpu.ops.topk import ShardedTopKScorer
    from predictionio_tpu.parallel.mesh import MeshContext

    rng = np.random.default_rng(4)
    factors = ALSFactors(
        user_factors=rng.normal(size=(5, 8)).astype(np.float32),
        item_factors=rng.normal(size=(24, 8)).astype(np.float32),
    )
    model = ALSModel(
        factors,
        BiMap.string_int([f"u{i}" for i in range(5)]),
        BiMap.string_int([f"i{i}" for i in range(24)]),
    )
    mesh = create_mesh({"data": 8})
    model.enable_sharded_serving(mesh)

    algo = ALSAlgorithm(ALSParams())
    restored = pickle.loads(pickle.dumps(algo.make_persistent_model(model)))
    loaded = algo.load_persistent_model(restored, MeshContext(mesh=mesh))
    assert isinstance(loaded.scorer(), ShardedTopKScorer)
    assert loaded.recommend("u1", 3) == model.recommend("u1", 3)
