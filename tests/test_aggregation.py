"""$set/$unset/$delete aggregation semantics
(ref specs: LEventAggregatorSpec.scala / PEventAggregatorSpec.scala)."""

import datetime as dt

from predictionio_tpu.data.aggregation import aggregate_properties_from_events
from predictionio_tpu.data.event import Event

UTC = dt.timezone.utc


def ev(event, entity_id, props, minute):
    return Event(
        event=event,
        entity_type="user",
        entity_id=entity_id,
        properties=props,
        event_time=dt.datetime(2026, 1, 1, 0, minute, tzinfo=UTC),
    )


def test_set_merge_latest_wins():
    events = [
        ev("$set", "u1", {"a": 1, "b": 1}, 0),
        ev("$set", "u1", {"b": 2, "c": 3}, 1),
    ]
    result = aggregate_properties_from_events(events)
    assert result["u1"].to_dict() == {"a": 1, "b": 2, "c": 3}
    assert result["u1"].first_updated == events[0].event_time
    assert result["u1"].last_updated == events[1].event_time


def test_out_of_order_set_does_not_clobber():
    # older $set arriving later must not overwrite a newer value
    events = [
        ev("$set", "u1", {"a": "new"}, 5),
        ev("$set", "u1", {"a": "old", "b": "old"}, 1),
    ]
    result = aggregate_properties_from_events(events)
    assert result["u1"].to_dict() == {"a": "new", "b": "old"}


def test_unset_removes_keys():
    events = [
        ev("$set", "u1", {"a": 1, "b": 2}, 0),
        ev("$unset", "u1", {"a": None}, 1),
    ]
    result = aggregate_properties_from_events(events)
    assert result["u1"].to_dict() == {"b": 2}


def test_unset_then_newer_set_restores():
    events = [
        ev("$set", "u1", {"a": 1}, 0),
        ev("$unset", "u1", {"a": None}, 1),
        ev("$set", "u1", {"a": 9}, 2),
    ]
    result = aggregate_properties_from_events(events)
    assert result["u1"].to_dict() == {"a": 9}


def test_delete_removes_entity():
    events = [
        ev("$set", "u1", {"a": 1}, 0),
        ev("$delete", "u1", {}, 1),
    ]
    assert aggregate_properties_from_events(events) == {}


def test_delete_then_set_recreates():
    events = [
        ev("$set", "u1", {"a": 1, "b": 2}, 0),
        ev("$delete", "u1", {}, 1),
        ev("$set", "u1", {"c": 3}, 2),
    ]
    result = aggregate_properties_from_events(events)
    assert result["u1"].to_dict() == {"c": 3}
    assert result["u1"].first_updated == events[2].event_time


def test_multiple_entities_and_required_filter():
    events = [
        ev("$set", "u1", {"a": 1, "b": 2}, 0),
        ev("$set", "u2", {"a": 5}, 0),
        ev("rate", "u3", {"a": 9}, 0),  # non-special events ignored
    ]
    result = aggregate_properties_from_events(events)
    assert set(result) == {"u1", "u2"}
    filtered = aggregate_properties_from_events(events, required=["b"])
    assert set(filtered) == {"u1"}
