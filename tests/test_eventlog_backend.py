"""Native (C++) eventlog backend specifics: durability across reopen,
torn-tail WAL recovery, tombstone persistence, non-canonical id mapping.
The generic EventStore contract runs via tests/test_storage.py's
parametrized suite; these cover what only the native tier does.
Reference role: the HBase event store (SURVEY.md §2.5)."""

import datetime as dt
import os

import pytest

from predictionio_tpu.data.event import Event
from tests.test_storage import make_storage

UTC = dt.timezone.utc


def _mk(tmp_path):
    return make_storage("eventlog", tmp_path)


def ev(uid, minute=0, name="rate"):
    return Event(
        event=name,
        entity_type="user",
        entity_id=uid,
        target_entity_type="item",
        target_entity_id="i1",
        properties={"rating": 4.0},
        event_time=dt.datetime(2026, 3, 1, 12, minute, tzinfo=UTC),
    )


def test_reopen_persistence_and_tombstones(tmp_path):
    st = _mk(tmp_path)
    app = st.apps().insert("native")
    st.events().init(app.id)
    ids = st.events().insert_batch([ev("u1"), ev("u2", 1), ev("u3", 2)], app.id)
    assert st.events().delete(ids[1], app.id)
    st.events().close()

    st2 = _mk(tmp_path)
    got = st2.events().find(app.id)
    assert [e.entity_id for e in got] == ["u1", "u3"]
    # tz fidelity survives the binary round trip
    assert got[0].event_time == dt.datetime(2026, 3, 1, 12, 0, tzinfo=UTC)
    assert st2.events().get(ids[1], app.id) is None
    st2.events().close()


def test_torn_tail_recovery(tmp_path):
    """A crash mid-append leaves a partial record; reopen truncates it
    (WAL replay semantics, eventlog.cpp)."""
    st = _mk(tmp_path)
    app = st.apps().insert("torn")
    st.events().init(app.id)
    st.events().insert_batch([ev("u1"), ev("u2", 1)], app.id)
    st.events().close()

    log_dir = tmp_path / "store" / "events" / f"events_{app.id}"
    log_file = log_dir / "log.bin"
    with open(log_file, "ab") as f:
        f.write(b"\xff\xff\xff\x7f partial garbage")

    st2 = _mk(tmp_path)
    assert [e.entity_id for e in st2.events().find(app.id)] == ["u1", "u2"]
    # appends still work after recovery
    st2.events().insert(ev("u3", 2), app.id)
    assert len(st2.events().find(app.id)) == 3
    st2.events().close()


def test_non_hex_event_id_round_trip(tmp_path):
    st = _mk(tmp_path)
    app = st.apps().insert("ids")
    st.events().init(app.id)
    e = ev("u1").with_id("custom-id-not-hex")
    st.events().insert(e, app.id)
    got = st.events().get("custom-id-not-hex", app.id)
    assert got is not None and got.event_id == "custom-id-not-hex"
    assert st.events().find(app.id)[0].event_id == "custom-id-not-hex"
    st.events().close()


def test_time_window_and_limit(tmp_path):
    st = _mk(tmp_path)
    app = st.apps().insert("win")
    st.events().init(app.id)
    st.events().insert_batch([ev(f"u{i}", i) for i in range(10)], app.id)
    es = st.events()
    start = dt.datetime(2026, 3, 1, 12, 3, tzinfo=UTC)
    until = dt.datetime(2026, 3, 1, 12, 7, tzinfo=UTC)
    got = es.find(app.id, start_time=start, until_time=until)
    assert [e.entity_id for e in got] == ["u3", "u4", "u5", "u6"]  # half-open
    got = es.find(app.id, limit=3, reversed=True)
    assert [e.entity_id for e in got] == ["u9", "u8", "u7"]
    st.events().close()


def test_reinsert_after_delete_is_live(tmp_path):
    """Tombstones carry a log-offset cutoff: deleting id X then inserting
    a new event with id X must keep the new event visible — matching the
    memory/localfs/sqlite backends."""
    st = _mk(tmp_path)
    app = st.apps().insert("resurrect")
    es = st.events().__class__  # noqa: F841 (readability)
    st.events().init(app.id)
    e1 = ev("u1").with_id()
    st.events().insert(e1, app.id)
    assert st.events().delete(e1.event_id, app.id)
    assert st.events().get(e1.event_id, app.id) is None

    e2 = ev("u1-v2", 5).with_id(e1.event_id)
    st.events().insert(e2, app.id)
    got = st.events().get(e1.event_id, app.id)
    assert got is not None and got.entity_id == "u1-v2"
    assert [e.entity_id for e in st.events().find(app.id)] == ["u1-v2"]
    st.events().close()

    # survives reopen (tombstone cutoff is persistent)
    st2 = _mk(tmp_path)
    assert [e.entity_id for e in st2.events().find(app.id)] == ["u1-v2"]
    st2.events().close()


def test_second_process_gets_clean_lock_error(tmp_path):
    """A second OS process opening the same log fails with StorageError
    (flock single-writer guard) instead of corrupting the index."""
    import subprocess
    import sys
    import textwrap

    st = _mk(tmp_path)
    app = st.apps().insert("locked")
    st.events().init(app.id)
    st.events().insert(ev("u1"), app.id)

    code = textwrap.dedent(
        f"""
        from predictionio_tpu.data.backends.eventlog import EventLogEventStore
        from predictionio_tpu.data.storage import StorageError
        st = EventLogEventStore({str(tmp_path / "store" / "events")!r})
        try:
            st.find({app.id})
        except StorageError as e:
            assert "LOCK" in str(e), e
            print("LOCKED-OK")
        else:
            print("NO-LOCK")
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, cwd="/root/repo"
    )
    assert "LOCKED-OK" in proc.stdout, (proc.stdout, proc.stderr)
    st.events().close()


def test_columnar_nul_bytes_in_ids_round_trip(tmp_path):
    """The native columnar dictionaries use exact prefix offsets, so ids
    containing embedded NUL bytes round-trip on the NATIVE path (a
    '\\0'-joined dictionary would silently shift every later vocab
    entry). Covers the native backend directly — the REST edge-case test
    only exercises the npz fallback."""
    st = _mk(tmp_path)
    app = st.apps().insert("nul")
    st.events().init(app.id)
    weird = ["a\0b", "plain", "\0lead", "trail\0", "double\0\0mid"]
    batch = [
        Event(
            event="rate",
            entity_type="user",
            entity_id=uid,
            target_entity_type="item",
            target_entity_id=f"i\0{i}",
            properties={"rating": float(i)},
            event_time=dt.datetime(2026, 3, 1, 12, i, tzinfo=UTC),
        )
        for i, uid in enumerate(weird)
    ]
    st.events().insert_batch(batch, app.id)
    cols = st.events().find_columnar(
        app.id, value_property="rating", time_ordered=True
    )
    got_ents = [cols.entity_vocab[c] for c in cols.entity_codes]
    got_tgts = [cols.target_vocab[c] for c in cols.target_codes]
    assert got_ents == weird
    assert got_tgts == [f"i\0{i}" for i in range(len(weird))]
    assert list(cols.values) == [float(i) for i in range(len(weird))]
    st.events().close()


def test_columnar_append_rejects_u16_overflow(tmp_path):
    """A string >= 65535 bytes would wrap the u16 wire header length (or
    alias the absent sentinel); insert_columnar must fail loudly like
    the row path's struct.pack('H'), never corrupt record framing."""
    import numpy as np

    from predictionio_tpu.data.storage import EventColumns, StorageError

    st = _mk(tmp_path)
    app = st.apps().insert("overflow")
    st.events().init(app.id)
    cols = EventColumns(
        entity_codes=np.array([0], np.int32),
        target_codes=np.array([0], np.int32),
        name_codes=np.array([0], np.int32),
        values=np.array([1.0]),
        times_us=np.array([0], np.int64),
        entity_vocab=["u" * 0xFFFF],
        target_vocab=["i1"],
        names=["rate"],
    )
    with pytest.raises(StorageError):
        st.events().insert_columnar(
            cols, app.id, entity_type="user", target_entity_type="item",
            value_property="rating",
        )
    # the log is untouched — no partially-framed record
    assert st.events().find(app.id) == []
    st.events().close()


def test_bulk_throughput_sanity(tmp_path):
    """50k events in one batch append + filtered scan — exercises the
    native index path at a size where Python-side filtering would show."""
    st = _mk(tmp_path)
    app = st.apps().insert("bulk")
    st.events().init(app.id)
    batch = [
        Event(
            event="buy" if i % 3 == 0 else "view",
            entity_type="user",
            entity_id=f"u{i % 500}",
            target_entity_type="item",
            target_entity_id=f"i{i % 100}",
            event_time=dt.datetime(2026, 3, 1, tzinfo=UTC) + dt.timedelta(seconds=i),
        )
        for i in range(50_000)
    ]
    ids = st.events().insert_batch(batch, app.id)
    assert len(set(ids)) == 50_000
    buys = st.events().find(app.id, event_names=["buy"])
    assert len(buys) == len([e for e in batch if e.event == "buy"])
    one_user = st.events().find(app.id, entity_type="user", entity_id="u7")
    assert len(one_user) == 100
    st.events().close()
