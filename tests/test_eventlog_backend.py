"""Native (C++) eventlog backend specifics: durability across reopen,
torn-tail WAL recovery, tombstone persistence, non-canonical id mapping.
The generic EventStore contract runs via tests/test_storage.py's
parametrized suite; these cover what only the native tier does.
Reference role: the HBase event store (SURVEY.md §2.5)."""

import datetime as dt
import os

import pytest

from predictionio_tpu.data.event import Event
from tests.test_storage import make_storage

UTC = dt.timezone.utc


def _mk(tmp_path):
    return make_storage("eventlog", tmp_path)


def ev(uid, minute=0, name="rate"):
    return Event(
        event=name,
        entity_type="user",
        entity_id=uid,
        target_entity_type="item",
        target_entity_id="i1",
        properties={"rating": 4.0},
        event_time=dt.datetime(2026, 3, 1, 12, minute, tzinfo=UTC),
    )


def test_reopen_persistence_and_tombstones(tmp_path):
    st = _mk(tmp_path)
    app = st.apps().insert("native")
    st.events().init(app.id)
    ids = st.events().insert_batch([ev("u1"), ev("u2", 1), ev("u3", 2)], app.id)
    assert st.events().delete(ids[1], app.id)
    st.events().close()

    st2 = _mk(tmp_path)
    got = st2.events().find(app.id)
    assert [e.entity_id for e in got] == ["u1", "u3"]
    # tz fidelity survives the binary round trip
    assert got[0].event_time == dt.datetime(2026, 3, 1, 12, 0, tzinfo=UTC)
    assert st2.events().get(ids[1], app.id) is None
    st2.events().close()


def test_torn_tail_recovery(tmp_path):
    """A crash mid-append leaves a partial record; reopen truncates it
    (WAL replay semantics, eventlog.cpp)."""
    st = _mk(tmp_path)
    app = st.apps().insert("torn")
    st.events().init(app.id)
    st.events().insert_batch([ev("u1"), ev("u2", 1)], app.id)
    st.events().close()

    log_dir = tmp_path / "store" / "events" / f"events_{app.id}"
    log_file = log_dir / "log.bin"
    with open(log_file, "ab") as f:
        f.write(b"\xff\xff\xff\x7f partial garbage")

    st2 = _mk(tmp_path)
    assert [e.entity_id for e in st2.events().find(app.id)] == ["u1", "u2"]
    # appends still work after recovery
    st2.events().insert(ev("u3", 2), app.id)
    assert len(st2.events().find(app.id)) == 3
    st2.events().close()


def test_non_hex_event_id_round_trip(tmp_path):
    st = _mk(tmp_path)
    app = st.apps().insert("ids")
    st.events().init(app.id)
    e = ev("u1").with_id("custom-id-not-hex")
    st.events().insert(e, app.id)
    got = st.events().get("custom-id-not-hex", app.id)
    assert got is not None and got.event_id == "custom-id-not-hex"
    assert st.events().find(app.id)[0].event_id == "custom-id-not-hex"
    st.events().close()


def test_time_window_and_limit(tmp_path):
    st = _mk(tmp_path)
    app = st.apps().insert("win")
    st.events().init(app.id)
    st.events().insert_batch([ev(f"u{i}", i) for i in range(10)], app.id)
    es = st.events()
    start = dt.datetime(2026, 3, 1, 12, 3, tzinfo=UTC)
    until = dt.datetime(2026, 3, 1, 12, 7, tzinfo=UTC)
    got = es.find(app.id, start_time=start, until_time=until)
    assert [e.entity_id for e in got] == ["u3", "u4", "u5", "u6"]  # half-open
    got = es.find(app.id, limit=3, reversed=True)
    assert [e.entity_id for e in got] == ["u9", "u8", "u7"]
    st.events().close()


def test_reinsert_after_delete_is_live(tmp_path):
    """Tombstones carry a log-offset cutoff: deleting id X then inserting
    a new event with id X must keep the new event visible — matching the
    memory/localfs/sqlite backends."""
    st = _mk(tmp_path)
    app = st.apps().insert("resurrect")
    es = st.events().__class__  # noqa: F841 (readability)
    st.events().init(app.id)
    e1 = ev("u1").with_id()
    st.events().insert(e1, app.id)
    assert st.events().delete(e1.event_id, app.id)
    assert st.events().get(e1.event_id, app.id) is None

    e2 = ev("u1-v2", 5).with_id(e1.event_id)
    st.events().insert(e2, app.id)
    got = st.events().get(e1.event_id, app.id)
    assert got is not None and got.entity_id == "u1-v2"
    assert [e.entity_id for e in st.events().find(app.id)] == ["u1-v2"]
    st.events().close()

    # survives reopen (tombstone cutoff is persistent)
    st2 = _mk(tmp_path)
    assert [e.entity_id for e in st2.events().find(app.id)] == ["u1-v2"]
    st2.events().close()


def test_second_process_gets_clean_lock_error(tmp_path):
    """A second OS process opening the same log fails with StorageError
    (flock single-writer guard) instead of corrupting the index."""
    import subprocess
    import sys
    import textwrap

    st = _mk(tmp_path)
    app = st.apps().insert("locked")
    st.events().init(app.id)
    st.events().insert(ev("u1"), app.id)

    code = textwrap.dedent(
        f"""
        from predictionio_tpu.data.backends.eventlog import EventLogEventStore
        from predictionio_tpu.data.storage import StorageError
        st = EventLogEventStore({str(tmp_path / "store" / "events")!r})
        try:
            st.find({app.id})
        except StorageError as e:
            assert "LOCK" in str(e), e
            print("LOCKED-OK")
        else:
            print("NO-LOCK")
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, cwd="/root/repo"
    )
    assert "LOCKED-OK" in proc.stdout, (proc.stdout, proc.stderr)
    st.events().close()


def test_columnar_nul_bytes_in_ids_round_trip(tmp_path):
    """The native columnar dictionaries use exact prefix offsets, so ids
    containing embedded NUL bytes round-trip on the NATIVE path (a
    '\\0'-joined dictionary would silently shift every later vocab
    entry). Covers the native backend directly — the REST edge-case test
    only exercises the npz fallback."""
    st = _mk(tmp_path)
    app = st.apps().insert("nul")
    st.events().init(app.id)
    weird = ["a\0b", "plain", "\0lead", "trail\0", "double\0\0mid"]
    batch = [
        Event(
            event="rate",
            entity_type="user",
            entity_id=uid,
            target_entity_type="item",
            target_entity_id=f"i\0{i}",
            properties={"rating": float(i)},
            event_time=dt.datetime(2026, 3, 1, 12, i, tzinfo=UTC),
        )
        for i, uid in enumerate(weird)
    ]
    st.events().insert_batch(batch, app.id)
    cols = st.events().find_columnar(
        app.id, value_property="rating", time_ordered=True
    )
    got_ents = [cols.entity_vocab[c] for c in cols.entity_codes]
    got_tgts = [cols.target_vocab[c] for c in cols.target_codes]
    assert got_ents == weird
    assert got_tgts == [f"i\0{i}" for i in range(len(weird))]
    assert list(cols.values) == [float(i) for i in range(len(weird))]
    st.events().close()


def test_columnar_append_rejects_u16_overflow(tmp_path):
    """A string >= 65535 bytes would wrap the u16 wire header length (or
    alias the absent sentinel); insert_columnar must fail loudly like
    the row path's struct.pack('H'), never corrupt record framing."""
    import numpy as np

    from predictionio_tpu.data.storage import EventColumns, StorageError

    st = _mk(tmp_path)
    app = st.apps().insert("overflow")
    st.events().init(app.id)
    cols = EventColumns(
        entity_codes=np.array([0], np.int32),
        target_codes=np.array([0], np.int32),
        name_codes=np.array([0], np.int32),
        values=np.array([1.0]),
        times_us=np.array([0], np.int64),
        entity_vocab=["u" * 0xFFFF],
        target_vocab=["i1"],
        names=["rate"],
    )
    with pytest.raises(StorageError):
        st.events().insert_columnar(
            cols, app.id, entity_type="user", target_entity_type="item",
            value_property="rating",
        )
    # the log is untouched — no partially-framed record
    assert st.events().find(app.id) == []
    st.events().close()


def test_bulk_throughput_sanity(tmp_path):
    """50k events in one batch append + filtered scan — exercises the
    native index path at a size where Python-side filtering would show."""
    st = _mk(tmp_path)
    app = st.apps().insert("bulk")
    st.events().init(app.id)
    batch = [
        Event(
            event="buy" if i % 3 == 0 else "view",
            entity_type="user",
            entity_id=f"u{i % 500}",
            target_entity_type="item",
            target_entity_id=f"i{i % 100}",
            event_time=dt.datetime(2026, 3, 1, tzinfo=UTC) + dt.timedelta(seconds=i),
        )
        for i in range(50_000)
    ]
    ids = st.events().insert_batch(batch, app.id)
    assert len(set(ids)) == 50_000
    buys = st.events().find(app.id, event_names=["buy"])
    assert len(buys) == len([e for e in batch if e.event == "buy"])
    one_user = st.events().find(app.id, entity_type="user", entity_id="u7")
    assert len(one_user) == 100
    st.events().close()


def test_compaction_reclaims_space_and_preserves_data(tmp_path):
    """insert, delete half, compact: the log file shrinks, deleted
    records are physically gone (tombstone file emptied), remaining
    data and subsequent appends intact across reopen. Ref: the HBase
    major-compaction role (SURVEY.md §2.5)."""
    st = _mk(tmp_path)
    app = st.apps().insert("compact")
    st.events().init(app.id)
    ids = st.events().insert_batch([ev(f"u{i}", i % 60) for i in range(500)], app.id)
    for eid in ids[::2]:
        assert st.events().delete(eid, app.id)

    log_dir = tmp_path / "store" / "events" / f"events_{app.id}"
    before = (log_dir / "log.bin").stat().st_size
    stats = st.events().compact(app.id)
    assert stats["dropped"] == 250
    assert stats["after_bytes"] < stats["before_bytes"] == before
    # compaction commits a new generation (CURRENT protocol): the new
    # files carry the data, the old generation's files are removed
    assert (log_dir / "CURRENT").read_text().strip() == "1"
    assert (log_dir / "log.1.bin").stat().st_size == stats["after_bytes"]
    assert (log_dir / "tombstones.1.bin").stat().st_size == 0
    assert not (log_dir / "log.bin").exists()

    got = st.events().find(app.id)
    assert {e.entity_id for e in got} == {f"u{i}" for i in range(1, 500, 2)}
    # appends + deletes still work after the swap
    st.events().insert(ev("u-post", 59), app.id)
    assert st.events().delete(ids[1], app.id)
    st.events().close()

    st2 = _mk(tmp_path)
    got = st2.events().find(app.id)
    assert len(got) == 250  # 249 survivors + u-post
    assert got[-1].entity_id == "u-post"
    st2.events().close()


def test_index_snapshot_fast_reopen(tmp_path):
    """A clean close persists the index; reopen loads it (index.bin
    exists and queries return identical results to the pre-close state).
    The open-cost win is measured at scale by the bench's warm stage."""
    st = _mk(tmp_path)
    app = st.apps().insert("snap")
    st.events().init(app.id)
    st.events().insert_batch([ev(f"u{i}", i % 60) for i in range(1000)], app.id)
    st.events().close()

    log_dir = tmp_path / "store" / "events" / f"events_{app.id}"
    assert (log_dir / "index.bin").exists()

    st2 = _mk(tmp_path)
    got = st2.events().find(app.id, entity_id="u7", entity_type="user")
    assert len(got) == len([i for i in range(1000) if i % 1000 == 7 or f"u{i}" == "u7"])
    assert len(st2.events().find(app.id)) == 1000
    st2.events().close()


def test_index_snapshot_crash_suffix_replay(tmp_path):
    """Appends after the last snapshot (a crash: close() never ran) are
    replayed from the log on reopen; dupe/tombstone semantics stay
    exact (the lazily replayed suffix is id-verified on first need)."""
    import subprocess
    import sys
    import textwrap

    st = _mk(tmp_path)
    app = st.apps().insert("crash")
    st.events().init(app.id)
    ids = st.events().insert_batch([ev(f"u{i}") for i in range(10)], app.id)
    st.events().close()  # snapshot covers 10 records

    # "crash": a subprocess appends (incl. a re-used id — liveness must
    # pick the later record) and exits WITHOUT close: no new snapshot,
    # flock released by process exit
    code = textwrap.dedent(
        f"""
        import datetime as dt, os
        from predictionio_tpu.data.backends.eventlog import EventLogEventStore
        from predictionio_tpu.data.event import Event
        es = EventLogEventStore({str(tmp_path / "store" / "events")!r})
        def ev(uid, minute):
            return Event(event="rate", entity_type="user", entity_id=uid,
                         target_entity_type="item", target_entity_id="i1",
                         event_time=dt.datetime(2026, 3, 1, 12, minute,
                                                tzinfo=dt.timezone.utc))
        es.insert(ev("u1-v2", 30).with_id({ids[1]!r}), {app.id})
        es.insert(ev("u-extra", 31), {app.id})
        os._exit(0)  # crash: no el_close, no snapshot update
        """
    )
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr

    st3 = _mk(tmp_path)
    got = {e.entity_id for e in st3.events().find(app.id)}
    assert "u-extra" in got and "u1-v2" in got
    assert "u1" not in got  # superseded by the suffix record with same id
    assert st3.events().get(ids[1], app.id).entity_id == "u1-v2"
    st3.events().close()


def test_compaction_crash_orphans_are_ignored_and_cleaned(tmp_path):
    """A compaction that crashed BEFORE the CURRENT commit leaves
    next-generation files as orphans: reopen must serve the old
    generation untouched and remove the orphans (commit protocol,
    eventlog.cpp CURRENT)."""
    st = _mk(tmp_path)
    app = st.apps().insert("orphan")
    st.events().init(app.id)
    st.events().insert_batch([ev(f"u{i}", i % 60) for i in range(20)], app.id)
    st.events().close()

    log_dir = tmp_path / "store" / "events" / f"events_{app.id}"
    (log_dir / "log.1.bin").write_bytes(b"half-written garbage")
    (log_dir / "tombstones.1.bin").write_bytes(b"")
    assert not (log_dir / "CURRENT").exists()

    st2 = _mk(tmp_path)
    assert len(st2.events().find(app.id)) == 20
    assert not (log_dir / "log.1.bin").exists()
    assert not (log_dir / "tombstones.1.bin").exists()
    st2.events().close()


def test_compaction_relocated_reinsert_survives_reopen(tmp_path):
    """The data-loss scenario the generation protocol exists for: a
    record re-inserted after a delete (so a tombstone cutoff exceeds
    its compacted offset) must stay live across compact + reopen — the
    new generation's tombstone file is empty by construction."""
    st = _mk(tmp_path)
    app = st.apps().insert("reloc")
    st.events().init(app.id)
    e1 = ev("u-old").with_id()
    st.events().insert(e1, app.id)
    st.events().insert_batch([ev(f"f{i}", i % 60) for i in range(200)], app.id)
    assert st.events().delete(e1.event_id, app.id)  # cutoff = large offset
    st.events().insert(ev("u-new", 59).with_id(e1.event_id), app.id)
    stats = st.events().compact(app.id)
    assert stats["dropped"] == 1
    assert st.events().get(e1.event_id, app.id).entity_id == "u-new"
    st.events().close()

    st2 = _mk(tmp_path)
    got = st2.events().get(e1.event_id, app.id)
    assert got is not None and got.entity_id == "u-new"
    assert len(st2.events().find(app.id)) == 201
    st2.events().close()


def test_corrupt_index_snapshot_degrades_to_replay(tmp_path):
    """A corrupt index.bin (bit rot, partial write, bogus n_recs) must
    degrade to full-log replay — never crash the process or poison the
    index."""
    import struct as _struct

    st = _mk(tmp_path)
    app = st.apps().insert("rot")
    st.events().init(app.id)
    st.events().insert_batch([ev(f"u{i}", i % 60) for i in range(50)], app.id)
    st.events().close()
    log_dir = tmp_path / "store" / "events" / f"events_{app.id}"
    idx = log_dir / "index.bin"

    # 1) bogus n_recs in an otherwise-valid header (would resize(2^60)
    # and abort the process if trusted before the size bound-check)
    raw = bytearray(idx.read_bytes())
    raw[32:40] = _struct.pack("<Q", 1 << 60)  # n_recs field
    idx.write_bytes(bytes(raw))
    st2 = _mk(tmp_path)
    assert len(st2.events().find(app.id)) == 50
    st2.events().close()

    # 2) flipped bit in the RecMeta array (checksum must reject)
    raw = bytearray(idx.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    idx.write_bytes(bytes(raw))
    st3 = _mk(tmp_path)
    assert len(st3.events().find(app.id)) == 50
    st3.events().close()

    # 3) truncated file
    idx.write_bytes(idx.read_bytes()[: len(raw) // 3])
    st4 = _mk(tmp_path)
    assert len(st4.events().find(app.id)) == 50
    st4.events().close()


def test_parallel_columnar_scan_is_byte_identical(tmp_path, monkeypatch):
    """The multi-threaded fused scan (PIO_EVENTLOG_SCAN_THREADS) must
    produce EXACTLY the sequential scan's output — same rows in record
    order, same first-seen dictionary code assignment."""
    import numpy as np

    store = _mk(tmp_path).events()
    store.init(1)
    base = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)
    events = []
    for i in range(5000):
        has_target = i % 7 != 0
        events.append(Event(
            event=f"ev{i % 3}",
            entity_type="user",
            entity_id=f"user_{(i * 13) % 401}",
            target_entity_type="item" if has_target else None,
            target_entity_id=f"item_{(i * 7) % 97}" if has_target else None,
            properties={"rating": float(i % 9)} if i % 2 else {},
            event_time=base + dt.timedelta(seconds=i),
        ))
    store.insert_batch(events, 1)

    monkeypatch.setenv("PIO_EVENTLOG_SCAN_THREADS", "1")
    seq = store.find_columnar(1, value_property="rating", time_ordered=False)
    monkeypatch.setenv("PIO_EVENTLOG_SCAN_THREADS", "4")
    par = store.find_columnar(1, value_property="rating", time_ordered=False)

    assert par.entity_vocab == seq.entity_vocab
    assert par.target_vocab == seq.target_vocab
    assert par.names == seq.names
    np.testing.assert_array_equal(par.entity_codes, seq.entity_codes)
    np.testing.assert_array_equal(par.target_codes, seq.target_codes)
    np.testing.assert_array_equal(par.name_codes, seq.name_codes)
    np.testing.assert_array_equal(par.times_us, seq.times_us)
    np.testing.assert_array_equal(
        np.nan_to_num(par.values, nan=-1.0),
        np.nan_to_num(seq.values, nan=-1.0),
    )

    # filters compose with the parallel path too
    par_f = store.find_columnar(1, value_property="rating",
                                time_ordered=False, event_names=["ev1"])
    monkeypatch.setenv("PIO_EVENTLOG_SCAN_THREADS", "1")
    seq_f = store.find_columnar(1, value_property="rating",
                                time_ordered=False, event_names=["ev1"])
    assert len(par_f) == len(seq_f) > 0
    np.testing.assert_array_equal(par_f.entity_codes, seq_f.entity_codes)
    assert par_f.entity_vocab == seq_f.entity_vocab
    store.close()


def test_concurrent_appends_scans_and_compact(tmp_path, monkeypatch):
    """Thread-safety stress of the native store: writers appending row
    batches while readers run (multi-threaded) columnar scans and a
    compaction runs mid-stream. The C++ layer must serialize correctly
    (shared scan locks vs exclusive append/compact locks) — no crashes,
    no torn reads, and the final state exact. The reference leans on
    JVM memory safety here (SURVEY.md §5.2); this is the native
    equivalent's proof."""
    import threading

    import numpy as np

    monkeypatch.setenv("PIO_EVENTLOG_SCAN_THREADS", "2")
    store = _mk(tmp_path).events()
    store.init(1)
    base = dt.datetime(2026, 4, 1, tzinfo=dt.timezone.utc)

    def batch(writer, start, n):
        return [Event(
            event="rate", entity_type="user",
            entity_id=f"w{writer}_u{(start + i) % 50}",
            target_entity_type="item", target_entity_id=f"i{(start + i) % 20}",
            properties={"rating": float(1 + i % 5)},
            event_time=base + dt.timedelta(seconds=start + i),
        ) for i in range(n)]

    errors = []
    scan_counts = [[], []]  # per scanner thread: order is meaningful
    stop = threading.Event()

    def writer(w):
        try:
            for r in range(20):
                store.insert_batch(batch(w, r * 50, 50), 1)
        except Exception as e:  # noqa: BLE001
            errors.append(("writer", w, e))

    def scanner(slot):
        try:
            while not stop.is_set():
                cols = store.find_columnar(1, value_property="rating",
                                           time_ordered=False)
                n = len(cols)
                # torn-read guards: every code decodes, values sane
                if n:
                    assert int(cols.entity_codes.max()) < len(cols.entity_vocab)
                    vals = cols.values[~np.isnan(cols.values)]
                    assert vals.size == 0 or (vals.min() >= 1.0 and vals.max() <= 5.0)
                scan_counts[slot].append(n)
        except Exception as e:  # noqa: BLE001
            errors.append(("scanner", slot, e))

    writers = [threading.Thread(target=writer, args=(w,)) for w in range(3)]
    scans = [threading.Thread(target=scanner, args=(s,)) for s in range(2)]
    for t in scans:
        t.start()
    for t in writers:
        t.start()
    writers[0].join()
    store.compact(1)       # exclusive pass mid-stream
    for t in writers[1:]:
        t.join()
    stop.set()
    for t in scans:
        t.join()

    assert not errors, errors
    final = store.find_columnar(1, time_ordered=False)
    assert len(final) == 3 * 20 * 50
    # EACH scanner observed monotonically non-decreasing counts (no
    # deletes here, and compaction drops nothing) and never phantom rows
    assert any(scan_counts)
    for counts in scan_counts:
        assert counts == sorted(counts), counts
        assert not counts or counts[-1] <= len(final)
    store.close()


# ---------------------------------------------------------------------------
# Native JSON ingest lane (VERDICT r3 item 3): the event server's live
# lane without per-row Python objects — API-format JSON array bytes go
# straight to C++ (parse + EventValidation + wire packing + append, GIL
# released). Reference role: EventAPI's request pipeline
# (data/.../api/EventAPI.scala:209).
# ---------------------------------------------------------------------------

def test_json_lane_matches_python_path(tmp_path):
    """The native lane and the Event-object path must store identical
    events (every field, tz fidelity included)."""
    import json

    rows = [
        {"event": "rate", "entityType": "user", "entityId": "u1",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": 4.5},
         "eventTime": "2026-01-01T00:00:00.000Z"},
        {"event": "$set", "entityType": "user", "entityId": "ué中-\"q\"",
         "properties": {"age": 31, "tags": ["a", "b"], "n": {"x": [1, 2]}},
         "eventTime": "2026-01-02T10:30:00+05:30"},
        {"event": "view", "entityType": "user", "entityId": "u3",
         "targetEntityType": "item", "targetEntityId": "i9",
         "tags": ["t1", "t2"], "prId": "pr-1",
         "eventTime": 1767225600000},
    ]
    st_native = _mk(tmp_path / "native")
    st_native.events().init(1)
    ids, codes, names, etypes = st_native.events().insert_json_batch(
        json.dumps(rows).encode(), 1)
    assert codes == [0, 0, 0] and None not in ids
    assert names == ["rate", "$set", "view"]
    assert etypes == ["user"] * 3

    st_py = _mk(tmp_path / "py")
    st_py.events().init(1)
    st_py.events().insert_batch([Event.from_dict(r) for r in rows], 1)

    def canon(events):
        return sorted(
            (e.event, e.entity_type, e.entity_id, e.target_entity_type,
             e.target_entity_id, dict(e.properties.to_dict()), e.event_time,
             e.event_time.utcoffset(), e.tags, e.pr_id)
            for e in events
        )

    assert canon(st_native.events().find(1)) == canon(st_py.events().find(1))
    st_native.events().close()
    st_py.events().close()


def test_json_lane_validation_parity(tmp_path):
    """Every EventValidation rule fires with the right code, bad rows
    never land, and the Python path rejects the same rows."""
    import json

    from predictionio_tpu.data.backends.eventlog import _ROW_ERRORS
    from predictionio_tpu.data.event import (
        EventValidationError, validate_event,
    )

    bad = [
        ({"event": "", "entityType": "u", "entityId": "x"}, 4),
        ({"event": "$bogus", "entityType": "u", "entityId": "x"}, 11),
        ({"event": "r", "entityType": "u", "entityId": "x",
          "targetEntityType": "item"}, 7),
        ({"event": "$unset", "entityType": "u", "entityId": "x"}, 10),
        ({"event": "$set", "entityType": "u", "entityId": "x",
          "targetEntityType": "item", "targetEntityId": "i"}, 12),
        ({"event": "r", "entityType": "pio_x", "entityId": "x"}, 13),
        ({"event": "r", "entityType": "u", "entityId": "x",
          "properties": {"pio_k": 1}}, 15),
        ({"entityType": "u", "entityId": "x"}, 1),
    ]
    st = _mk(tmp_path)
    st.events().init(1)
    good = {"event": "rate", "entityType": "user", "entityId": "ok"}
    payload = [good] + [b for b, _ in bad]
    ids, codes, _, _ = st.events().insert_json_batch(
        json.dumps(payload).encode(), 1, strict=False)
    assert codes[0] == 0
    assert codes[1:] == [c for _, c in bad], codes
    assert all(c in _ROW_ERRORS for c in codes[1:])
    # only the good row landed
    assert [e.entity_id for e in st.events().find(1)] == ["ok"]
    # the Python path rejects the same rows
    for row, _ in bad:
        with pytest.raises((EventValidationError, ValueError)):
            validate_event(Event.from_dict(row))
    st.events().close()


def test_json_lane_strict_appends_nothing(tmp_path):
    import json

    from predictionio_tpu.data.storage import StorageError

    st = _mk(tmp_path)
    st.events().init(1)
    payload = [
        {"event": "rate", "entityType": "user", "entityId": "ok"},
        {"event": "", "entityType": "user", "entityId": "bad"},
    ]
    with pytest.raises(StorageError, match="event 1"):
        st.events().insert_json_batch(json.dumps(payload).encode(), 1)
    assert st.events().find(1) == []
    st.events().close()


def test_json_lane_unsupported_falls_back(tmp_path):
    import json

    from predictionio_tpu.data.backends.eventlog import JsonRowsUnsupported

    st = _mk(tmp_path)
    st.events().init(1)
    for rows in (
        # caller-stamped id (breaks the fresh-ids lazy-index invariant)
        [{"event": "r", "entityType": "u", "entityId": "x",
          "eventId": "abc"}],
        # compact ISO the fast parser declines (Python accepts it)
        [{"event": "r", "entityType": "u", "entityId": "x",
          "eventTime": "20260101"}],
        # non-object properties (Python shapes the error)
        [{"event": "r", "entityType": "u", "entityId": "x",
          "properties": "zz"}],
        # escaped property key could hide a reserved prefix
        [{"event": "r", "entityType": "u", "entityId": "x",
          "properties": {"pio_k": 1}}],
    ):
        raw = json.dumps(rows).encode()
        if "\\u0070" not in raw.decode() and "pio_k" in raw.decode():
            # ensure_ascii already resolved the escape: force it back
            raw = raw.replace(b'"pio_k"', b'"\\u0070io_k"')
        with pytest.raises(JsonRowsUnsupported):
            st.events().insert_json_batch(raw, 1)
    assert st.events().find(1) == []
    st.events().close()


def test_fsync_acked_event_survives_sigkill(tmp_path):
    """The HBase SYNC_WAL contract (hbase/HBLEvents.scala:42): with
    FSYNC=1 an acknowledged insert is on disk before the ack — the
    process being SIGKILLed right after the ack must not lose it, and
    reopen must replay it cleanly."""
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent(f"""
        import json, os
        from predictionio_tpu.data.backends.eventlog import EventLogEventStore
        store = EventLogEventStore({str(str(tmp_path / 'log'))!r}, fsync=True)
        store.init(1)
        ids, codes, _, _ = store.insert_json_batch(json.dumps([
            {{"event": "rate", "entityType": "user", "entityId": "durable",
              "eventTime": "2026-01-01T00:00:00Z"}},
        ]).encode(), 1)
        assert codes == [0]
        print("ACKED", ids[0], flush=True)
        os.kill(os.getpid(), 9)   # no close(), no snapshot, no atexit
    """)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=120,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == -9, proc.stderr
    acked_id = proc.stdout.split()[1]

    from predictionio_tpu.data.backends.eventlog import EventLogEventStore

    store = EventLogEventStore(str(tmp_path / "log"))
    got = store.get(acked_id, 1)
    assert got is not None and got.entity_id == "durable"
    assert [e.entity_id for e in store.find(1)] == ["durable"]
    store.close()


def test_json_lane_calendar_and_encoding_parity(tmp_path):
    """Code-review regressions: impossible calendar dates are per-row
    400 (not silently normalized), non-object array elements are
    per-row 400 (not a whole-batch failure), invalid UTF-8 bodies are
    rejected up front (json.loads parity), and NUL-bearing names fall
    back to the Python path instead of desyncing the stats buffers."""
    import json

    from predictionio_tpu.data.backends.eventlog import JsonRowsUnsupported
    from predictionio_tpu.data.storage import StorageError

    st = _mk(tmp_path)
    st.events().init(1)

    # impossible date: rejected per-row like Python fromisoformat
    rows = [
        {"event": "ok", "entityType": "u", "entityId": "x",
         "eventTime": "2026-02-28T00:00:00Z"},
        {"event": "bad", "entityType": "u", "entityId": "x",
         "eventTime": "2026-02-31T00:00:00Z"},
        {"event": "leap", "entityType": "u", "entityId": "x",
         "eventTime": "2024-02-29T00:00:00Z"},  # 2024 IS a leap year
    ]
    ids, codes, _, _ = st.events().insert_json_batch(
        json.dumps(rows).encode(), 1, strict=False)
    assert codes == [0, 16, 0], codes

    # non-object element: per-row code 17, batchmates unaffected
    raw = (b'[{"event":"a","entityType":"u","entityId":"u1"}, 42, '
           b'{"event":"b","entityType":"u","entityId":"u2"}]')
    ids, codes, names, _ = st.events().insert_json_batch(raw, 1, strict=False)
    assert codes == [0, 17, 0], codes
    assert names == ["a", "", "b"]

    # invalid UTF-8 body: malformed (the Python json parser refuses it
    # too), nothing appended
    bad = b'[{"event":"a\xff","entityType":"u","entityId":"u1"}]'
    n_before = len(st.events().find(1))
    with pytest.raises(ValueError, match="malformed"):
        st.events().insert_json_batch(bad, 1, strict=False)
    assert len(st.events().find(1)) == n_before

    # STRICT value grammar (code-review regression): mismatched
    # brackets and trailing-junk literals json.loads would reject must
    # never be stored (a poison extra slice breaks every later read)
    for poison in (
        b'[{"event":"e","entityType":"u","entityId":"x","tags":[}]}]',
        b'[{"event":"e","entityType":"u","entityId":"x",'
        b'"properties":{"a":truex}}]',
        b'[{"event":"e","entityType":"u","entityId":"x",'
        b'"properties":{"a":1.5abc}}]',
        b'[{"event":"e","entityType":"u","entityId":"x",'
        b'"properties":{"a":[1,{]}}}]',
    ):
        with pytest.raises((ValueError, JsonRowsUnsupported)):
            st.events().insert_json_batch(poison, 1, strict=False)
    assert len(st.events().find(1)) == n_before
    # every stored record still parses
    for e in st.events().find(1):
        e.properties.to_dict()

    # an escaped NUL inside a name would desync the NUL-joined stats
    # buffers: Python path instead
    nul = b'[{"event":"a\\u0000b","entityType":"u","entityId":"u1"}]'
    with pytest.raises(JsonRowsUnsupported):
        st.events().insert_json_batch(nul, 1, strict=False)
    st.events().close()


def test_json_lane_differential_fuzz(tmp_path):
    """Randomized differential test: for generated API-format events,
    the native JSON lane must store EXACTLY what the Event-object path
    stores (field-for-field, tz fidelity included) or decline to the
    Python path — and arbitrary byte mutations of valid bodies must
    never corrupt the log (every surviving record still decodes)."""
    import json
    import random

    from predictionio_tpu.data.backends.eventlog import JsonRowsUnsupported
    from predictionio_tpu.data.storage import StorageError

    rng = random.Random(20260730)
    ENT = ["u1", "ué", "日本語", 'q"uote', "back\\slash", "tab\tchar",
           "a" * 200, "nul-adjacent\u0001"]
    PROPS = [
        {}, {"rating": 4.5}, {"n": {"deep": [1, 2, {"x": None}]}},
        {"unicode": "中文", "b": True, "f": False, "z": None},
        {"list": [1.5, "two", [3]], "neg": -12.75, "exp": 1.5e-3},
    ]
    # every generated row carries an explicit eventTime: the "absent ->
    # now()" default necessarily differs by microseconds between the
    # two paths (covered by test_json_lane_matches_python_path instead)
    TIMES = ["2026-01-01T00:00:00Z", "2026-06-15T23:59:59.999Z",
             "2024-02-29T12:00:00+05:30", "2026-01-01 08:30:00-02:00",
             1767225600000]

    def gen_event():
        e = {"event": rng.choice(["rate", "view", "$set"]),
             "entityType": "user", "entityId": rng.choice(ENT)}
        if e["event"] != "$set" and rng.random() < 0.7:
            e["targetEntityType"] = "item"
            e["targetEntityId"] = rng.choice(ENT)
        p = rng.choice(PROPS)
        if e["event"] == "$set" and not p:
            p = {"rating": 1.0}
        if p:
            e["properties"] = p
        e["eventTime"] = rng.choice(TIMES)
        if rng.random() < 0.3:
            e["tags"] = ["t1", "ü2"][: rng.randint(1, 2)]
        if rng.random() < 0.2:
            e["prId"] = "pr-9"
        return e

    def canon(events):
        # None-safe sort key (targets/prId are optional)
        return sorted(
            (e.event, e.entity_type, e.entity_id,
             e.target_entity_type or "", e.target_entity_id or "",
             json.dumps(e.properties.to_dict(), sort_keys=True),
             e.event_time, str(e.event_time.utcoffset()), e.tags,
             e.pr_id or "")
            for e in events
        )

    compared = 0
    for trial in range(15):
        rows = [gen_event() for _ in range(rng.randint(1, 12))]
        raw = json.dumps(rows).encode()
        st_n = _mk(tmp_path / f"n{trial}")
        st_n.events().init(1)
        st_p = _mk(tmp_path / f"p{trial}")
        st_p.events().init(1)
        try:
            try:
                ids, codes, _, _ = st_n.events().insert_json_batch(raw, 1)
                assert all(c == 0 for c in codes), (codes, rows)
            except JsonRowsUnsupported:
                continue  # declining is always allowed
            st_p.events().insert_batch([Event.from_dict(r) for r in rows], 1)
            got_n = canon(st_n.events().find(1))
            got_p = canon(st_p.events().find(1))
            assert got_n == got_p, (trial, rows)
            compared += 1
        finally:
            st_n.events().close()
            st_p.events().close()

    assert compared >= 5, "native lane declined too many valid batches"

    # directed poison probes (code-review regression): constructs
    # json.loads REJECTS must never be accepted into the log
    st = _mk(tmp_path / "mut")
    st.events().init(1)
    for poison in (
        b'[{"event":"r","entityType":"u","entityId":"x",'
        b'"properties":{"k":"a\\qb"}}]',          # invalid \q escape
        b'[{"event":"r","entityType":"u","entityId":"x",'
        b'"properties":{"k":"a\\uZZ00"}}]',       # bad \u hex
        b'[{"event":"r","entityType":"u","entityId":"x",'
        b'"properties":{"k":"a\x01b"}}]',          # raw control char
    ):
        with pytest.raises((ValueError, JsonRowsUnsupported, StorageError)):
            st.events().insert_json_batch(poison, 1, strict=False)
    assert st.events().find(1) == []

    # mutation fuzz: corrupting valid bodies must never poison the log
    base = json.dumps([gen_event() for _ in range(4)]).encode()
    for trial in range(120):
        body = bytearray(base)
        muts = rng.randint(1, 3)
        for _ in range(muts):
            pos = rng.randrange(len(body))
            # bias toward the dangerous classes: structural bytes,
            # backslashes and control chars
            body[pos] = rng.choice(
                [0x5C, 0x22, 0x7B, 0x7D, 0x5B, 0x5D, 0x01, 0x1F]
                + [rng.randrange(256)])
        try:
            st.events().insert_json_batch(bytes(body), 1, strict=False)
        except (ValueError, JsonRowsUnsupported, StorageError):
            pass
    # every record the log DID accept must still decode cleanly
    for e in st.events().find(1):
        e.properties.to_dict()
        assert e.event and e.entity_type and e.entity_id
    st.events().close()


def test_json_lane_strict_comma_grammar(tmp_path):
    """ADVICE r4 (high): the native lane's object walks must REQUIRE
    the member comma. A missing comma inside properties used to be
    acked 201 with the malformed raw slice stored verbatim — poisoning
    json.loads on EVERY later read of the app (get/find/training). Both
    loops (parse_row top level + the properties walk) must now reject
    exactly what json.loads rejects, falling back to the Python lane
    which 400s it."""
    import json

    from predictionio_tpu.data.backends.eventlog import JsonRowsUnsupported

    st = _mk(tmp_path)
    st.events().init(1)
    ok = [{"event": "rate", "entityType": "u", "entityId": "x",
           "properties": {"a": 1, "b": 2}}]
    ids, codes, _, _ = st.events().insert_json_batch(
        json.dumps(ok).encode(), 1)
    assert codes == [0]
    n_before = len(st.events().find(1))

    for poison in (
        # missing comma between properties members (the poisoned-read
        # reproduction from the advisor finding)
        b'[{"event":"rate","entityType":"u","entityId":"x",'
        b'"properties":{"a":1 "b":2}}]',
        # missing comma between top-level members (silent grammar
        # divergence: 201 where the Python lane 400s)
        b'[{"event":"rate" "entityType":"u","entityId":"x"}]',
        # missing comma straight after the properties object
        b'[{"event":"rate","entityType":"u","entityId":"x",'
        b'"properties":{"a":1} "targetEntityType":"i"}]',
        # trailing comma in the event array (json.loads rejects)
        b'[{"event":"rate","entityType":"u","entityId":"x"},]',
    ):
        # json.loads parity: the reference body must actually be bad
        with pytest.raises(json.JSONDecodeError):
            json.loads(poison)
        with pytest.raises((ValueError, JsonRowsUnsupported)):
            st.events().insert_json_batch(poison, 1, strict=False)

    # nothing stored, and — the real stake — every read still parses
    events = st.events().find(1)
    assert len(events) == n_before
    for e in events:
        assert e.properties.to_dict() == {"a": 1, "b": 2}
    assert st.events().get(ids[0], 1).properties.to_dict() == {"a": 1, "b": 2}
    st.events().close()


def test_fingerprint_distinguishes_apps_with_identical_content(tmp_path):
    """ADVICE r4 (medium): the machine-global bincache keys on the
    fingerprint, so two apps whose logs coincide on the content
    quadruple (same record sizes/counts — here byte-identical data)
    must still produce DIFFERENT fingerprints, or a retrain on app B
    silently loads app A's cached binned layout."""
    import json

    st = _mk(tmp_path)
    raw = json.dumps([
        {"event": "rate", "entityType": "u", "entityId": f"u{i}",
         "targetEntityType": "i", "targetEntityId": f"i{i}",
         "properties": {"rating": 3.5}}
        for i in range(50)
    ]).encode()
    st.events().init(1)
    st.events().init(2)
    st.events().insert_json_batch(raw, 1)
    st.events().insert_json_batch(raw, 2)
    fp1 = st.events().data_fingerprint(1)
    fp2 = st.events().data_fingerprint(2)
    # identical content quadruple...
    assert fp1.split("-", 1)[1] == fp2.split("-", 1)[1]
    # ...but distinct log identity
    assert fp1 != fp2
    # channels are distinct logs too
    st.events().init(1, 7)
    st.events().insert_json_batch(raw, 1, 7)
    assert st.events().data_fingerprint(1, 7) != fp1
    # and the fingerprint is stable for the same unchanged log
    assert st.events().data_fingerprint(1) == fp1
    st.events().close()


# -- vectorized row-lane append (el_append_rows) --------------------------------

def test_insert_batch_fast_lane_full_round_trip(tmp_path):
    """The vectorized pack (numpy struct assembly + one native bulk
    call) must preserve EVERY record field the per-row _pack lane
    carried: tz-offset times, properties, tags, prId, caller-stamped
    canonical and non-canonical ids, NUL bytes inside ids."""
    st = _mk(tmp_path)
    app = st.apps().insert("rows")
    st.events().init(app.id)
    tz = dt.timezone(dt.timedelta(hours=-7))
    evs = [
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              properties={"rating": 4.5},
              event_time=dt.datetime(2026, 1, 1, tzinfo=UTC)),
        Event(event="$set", entity_type="user", entity_id="u2",
              properties={"a": [1, 2], "b": {"c": "x"}},
              tags=("t1", "t2"), pr_id="p9",
              event_time=dt.datetime(2026, 1, 2, 3, 4, 5, 123456, tzinfo=tz)),
        Event(event="view", entity_type="user", entity_id="u\x00weird",
              event_time=dt.datetime(2026, 2, 1, tzinfo=UTC),
              event_id="deadbeef" * 4),
        Event(event="view", entity_type="user", entity_id="u4",
              event_time=dt.datetime(2026, 2, 2, tzinfo=UTC),
              event_id="my-custom-id"),
    ]
    ids = st.events().insert_batch(evs, app.id)
    assert ids[2] == "deadbeef" * 4 and ids[3] == "my-custom-id"
    for eid, e in zip(ids, evs):
        got = st.events().get(eid, app.id)
        assert got is not None, eid
        assert got.event == e.event
        assert got.entity_id == e.entity_id
        assert got.target_entity_id == e.target_entity_id
        assert got.properties.to_dict() == dict(e.properties)
        assert got.event_time == e.event_time
        assert got.tags == e.tags and got.pr_id == e.pr_id
    # survives reopen (the packed wire records are well-formed)
    st.events().close()
    st2 = _mk(tmp_path)
    got = st2.events().get(ids[0], app.id)
    assert got is not None and got.properties.to_dict() == {"rating": 4.5}
    st2.events().close()


def test_insert_batch_fast_lane_wire_limit_error(tmp_path):
    from predictionio_tpu.data.storage import StorageError

    st = _mk(tmp_path)
    app = st.apps().insert("rows2")
    st.events().init(app.id)
    big = Event(event="rate", entity_type="user", entity_id="x" * 70_000,
                event_time=dt.datetime(2026, 1, 1, tzinfo=UTC))
    with pytest.raises(StorageError, match="65534"):
        st.events().insert_batch([big], app.id)
    # nothing appended: the batch is validated before any write
    assert st.events().find(app.id) == []
    st.events().close()


def test_insert_batch_fast_lane_moves_freshness_clock(tmp_path):
    from predictionio_tpu.obs import perfacct

    st = _mk(tmp_path)
    app = st.apps().insert("rows3")
    st.events().init(app.id)
    perfacct.LEDGER.clear()
    st.events().insert_batch([ev("u1")], app.id)
    assert perfacct.LEDGER.staleness_seconds() > 0.0
    perfacct.LEDGER.clear()
    st.events().close()
