"""SQLite backend specifics: durability across reopen, multi-process
visibility, blob round-trip (the properties the localfs tier only
approximates; ref role: hbase+elasticsearch persistence, SURVEY.md §2.5)."""

import datetime as dt
import json
import subprocess
import sys

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import Model
from predictionio_tpu.data.storage import Storage

from tests.test_storage import make_storage

UTC = dt.timezone.utc


def test_reopen_persistence(tmp_path):
    st = make_storage("sqlite", tmp_path)
    app = st.apps().insert("persist")
    st.events().init(app.id)
    st.events().insert(
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              properties={"rating": 4.5},
              event_time=dt.datetime(2026, 3, 1, 12, 30, tzinfo=UTC)),
        app.id,
    )
    st.models().insert(Model(id="m1", models=b"\x00\x01binary\xff"))
    st.client_for("METADATA").close()

    st2 = make_storage("sqlite", tmp_path)
    assert st2.apps().get_by_name("persist").id == app.id
    events = st2.events().find(app.id)
    assert len(events) == 1
    e = events[0]
    assert e.properties.get("rating", float) == 4.5
    # timezone fidelity through the payload round-trip
    assert e.event_time == dt.datetime(2026, 3, 1, 12, 30, tzinfo=UTC)
    assert st2.models().get("m1").models == b"\x00\x01binary\xff"


def test_uninitialized_table_strict(tmp_path):
    from predictionio_tpu.data.storage import StorageError

    st = make_storage("sqlite", tmp_path)
    app = st.apps().insert("strict")
    with pytest.raises(StorageError):
        st.events().find(app.id)
    st.events().remove(app.id)  # removing a missing table is a no-op
    st.events().init(app.id)
    assert st.events().find(app.id) == []


def test_cross_process_visibility(tmp_path):
    """A second OS process sees committed writes (WAL multi-process)."""
    st = make_storage("sqlite", tmp_path)
    app = st.apps().insert("xproc")
    st.events().init(app.id)
    st.events().insert(
        Event(event="view", entity_type="user", entity_id="u9"), app.id)

    script = f"""
import json
from tests.test_storage import make_storage
from pathlib import Path
st = make_storage("sqlite", Path({str(tmp_path)!r}))
app = st.apps().get_by_name("xproc")
events = st.events().find(app.id)
st.events().insert(events[0].with_id("child-written"), app.id)
print(json.dumps({{"app_id": app.id, "n": len(events)}}))
"""
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, cwd="/root/repo",
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo"},
        check=True,
    )
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result == {"app_id": app.id, "n": 1}
    # and the child's write is visible back in this process
    assert st.events().get("child-written", app.id) is not None


def test_find_uses_index_ordering(tmp_path):
    st = make_storage("sqlite", tmp_path)
    app = st.apps().insert("ord")
    st.events().init(app.id)
    for m in (5, 1, 3):
        st.events().insert(
            Event(event="e", entity_type="u", entity_id=f"x{m}",
                  event_time=dt.datetime(2026, 1, 1, 0, m, tzinfo=UTC)),
            app.id)
    times = [e.event_time.minute for e in st.events().find(app.id)]
    assert times == [1, 3, 5]
    times = [e.event_time.minute for e in st.events().find(app.id, reversed=True)]
    assert times == [5, 3, 1]
    limited = st.events().find(app.id, limit=2)
    assert [e.event_time.minute for e in limited] == [1, 3]
