"""Event model + validation (ref: data/.../storage/Event.scala:37,57
and TestEvents.scala timezone cases)."""

import datetime as dt

import pytest

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    validate_event,
)

UTC = dt.timezone.utc


def make(**kw):
    base = dict(event="rate", entity_type="user", entity_id="u1")
    base.update(kw)
    return Event(**base)


def test_basic_event_fields():
    e = make(
        target_entity_type="item",
        target_entity_id="i1",
        properties={"rating": 4.5},
        tags=["a", "b"],
        pr_id="pr1",
    )
    validate_event(e)
    assert e.properties.get("rating", float) == 4.5
    assert e.tags == ("a", "b")


def test_json_roundtrip_preserves_timezone():
    # ref: TestEvents.scala — events carry non-UTC zone offsets
    tz = dt.timezone(dt.timedelta(hours=12, minutes=45))  # Pacific/Chatham-like
    t = dt.datetime(2026, 12, 27, 11, 5, 1, 342000, tzinfo=tz)
    e = make(event_time=t, properties={"a": 1})
    d = e.to_dict(api_format=False)
    e2 = Event.from_dict(d)
    assert e2.event_time == t  # same instant
    assert e2.properties == e.properties


def test_millis_timestamp_parse():
    e = Event.from_dict(
        {"event": "buy", "entityType": "user", "entityId": "u1", "eventTime": 1735689600000}
    )
    assert e.event_time == dt.datetime(2025, 1, 1, tzinfo=UTC)


def test_missing_required_field():
    with pytest.raises(EventValidationError):
        Event.from_dict({"event": "rate", "entityId": "u1"})


@pytest.mark.parametrize("name", ["$set", "$unset", "$delete"])
def test_special_events_allowed(name):
    props = {"a": 1} if name != "$delete" else {}
    e = make(event=name, properties=props)
    validate_event(e)


def test_unknown_dollar_event_rejected():
    with pytest.raises(EventValidationError):
        validate_event(make(event="$bogus"))


def test_unset_requires_properties():
    with pytest.raises(EventValidationError):
        validate_event(make(event="$unset", properties={}))


def test_special_event_cannot_have_target():
    with pytest.raises(EventValidationError):
        validate_event(
            make(event="$set", properties={"a": 1}, target_entity_type="item", target_entity_id="i")
        )


def test_empty_entity_rejected():
    with pytest.raises(EventValidationError):
        validate_event(make(entity_id=""))
    with pytest.raises(EventValidationError):
        validate_event(make(entity_type=""))


def test_target_fields_must_pair():
    with pytest.raises(EventValidationError):
        validate_event(make(target_entity_type="item"))


def test_reserved_prefixes():
    # ref: Event.scala:62 isReservedPrefix — both "$" and "pio_" prefixes
    with pytest.raises(EventValidationError):
        validate_event(make(entity_type="pio_custom"))
    with pytest.raises(EventValidationError):
        validate_event(make(entity_type="$custom"))
    with pytest.raises(EventValidationError):
        validate_event(make(properties={"pio_x": 1}))
    with pytest.raises(EventValidationError):
        validate_event(make(properties={"$x": 1}))
    with pytest.raises(EventValidationError):
        validate_event(make(event="pio_custom_event"))
    with pytest.raises(EventValidationError):
        validate_event(
            make(target_entity_type="pio_custom", target_entity_id="t1")
        )
    # the only builtin entity type (ref: Event.scala:104)
    validate_event(make(entity_type="pio_pr"))
