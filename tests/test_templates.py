"""Engine template tests: similarproduct, ecommerce, classification, vanilla.

Each template trains end-to-end against the in-memory event store and
asserts the serve-time behaviors the reference templates implement
(candidate filters, serve-time event lookups, multi-algorithm
combining; see module docstrings for file:line contracts).
"""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.templates import classification as cls_t
from predictionio_tpu.templates import ecommerce as ecom_t
from predictionio_tpu.templates import similarproduct as simprod_t
from predictionio_tpu.templates import vanilla as vanilla_t

UTC = dt.timezone.utc
ctx = MeshContext()


def _t(minute):
    return dt.datetime(2026, 1, 1, 0, minute, tzinfo=UTC)


def setup_app(storage, name):
    app = storage.apps().insert(name)
    storage.events().init(app.id)
    return app


def put(storage, app_id, event, etype, eid, tetype=None, teid=None, props=None, minute=0):
    storage.events().insert(
        Event(
            event=event,
            entity_type=etype,
            entity_id=eid,
            target_entity_type=tetype,
            target_entity_id=teid,
            properties=props or {},
            event_time=_t(minute),
        ),
        app_id,
    )


@pytest.fixture()
def simprod_app(memory_storage):
    app = setup_app(memory_storage, "simprod")
    users = ["u1", "u2", "u3", "u4"]
    for u in users:
        put(memory_storage, app.id, "$set", "user", u)
    cats = {"i1": ["a"], "i2": ["a", "b"], "i3": ["b"], "i4": ["c"]}
    for i, cs in cats.items():
        put(memory_storage, app.id, "$set", "item", i, props={"categories": cs})
    # u1,u2 view i1+i2 (similar); u3 views i3; u4 views everything
    views = [
        ("u1", "i1"), ("u1", "i2"), ("u2", "i1"), ("u2", "i2"),
        ("u3", "i3"), ("u4", "i1"), ("u4", "i2"), ("u4", "i3"), ("u4", "i4"),
    ]
    for m, (u, i) in enumerate(views):
        put(memory_storage, app.id, "view", "user", u, "item", i, minute=m)
    likes = [
        ("u1", "i1", "like"), ("u1", "i2", "like"), ("u2", "i1", "like"),
        ("u2", "i2", "like"), ("u3", "i4", "dislike"), ("u4", "i3", "like"),
    ]
    for m, (u, i, e) in enumerate(likes):
        put(memory_storage, app.id, e, "user", u, "item", i, minute=30 + m)
    return app


class TestSimilarProduct:
    def test_datasource_reads(self, memory_storage, simprod_app):
        ds = simprod_t.SimilarProductDataSource(
            simprod_t.SimilarProductDSParams(app_name="simprod"))
        td = ds.read_training(ctx)
        assert td.users == ["u1", "u2", "u3", "u4"]
        assert td.items == ["i1", "i2", "i3", "i4"]
        assert td.item_categories["i2"] == ["a", "b"]
        assert len(td.view_events) == 9
        assert ("u3", "i4", False) in td.like_events

    def test_train_and_similar(self, memory_storage, simprod_app):
        engine = simprod_t.similar_product_engine()
        ep = simprod_t.default_engine_params(
            "simprod",
            als_params=simprod_t.SimilarProductParams(rank=4, num_iterations=10),
            like_params=simprod_t.SimilarProductParams(rank=4, num_iterations=10),
        )
        result = engine.train(ctx, ep)
        assert len(result.models) == 2
        als_model = result.models[0]
        # i1 and i2 are co-viewed -> i2 should top the similar list for i1
        recs = als_model.similar(["i1"], num=3)
        assert recs, "expected nonempty similar items"
        assert recs[0][0] == "i2"
        # query item itself is never returned
        assert all(item != "i1" for item, _ in recs)

    def test_filters(self, memory_storage, simprod_app):
        engine = simprod_t.similar_product_engine()
        ep = simprod_t.default_engine_params(
            "simprod",
            als_params=simprod_t.SimilarProductParams(rank=4, num_iterations=10),
        )
        model = engine.train(ctx, ep).models[0]
        # category filter: only items in category "b" (i2, i3) qualify
        recs = model.similar(["i1"], num=4, categories={"b"})
        assert recs and all(item in {"i2", "i3"} for item, _ in recs)
        # whitelist
        recs = model.similar(["i1"], num=4, white_list={"i3"})
        assert all(item == "i3" for item, _ in recs)
        # blacklist
        recs = model.similar(["i1"], num=4, black_list={"i2"})
        assert all(item != "i2" for item, _ in recs)
        # unknown query items -> empty
        assert model.similar(["zzz"], num=4) == []

    def test_standardizing_serving(self):
        serving = simprod_t.StandardizingServing.create()
        preds = [
            {"itemScores": [{"item": "a", "score": 10.0},
                            {"item": "b", "score": 20.0},
                            {"item": "c", "score": 30.0}]},
            {"itemScores": [{"item": "b", "score": 1.0},
                            {"item": "c", "score": 2.0},
                            {"item": "d", "score": 3.0}]},
        ]
        out = serving.serve({"num": 2}, preds)
        items = [s["item"] for s in out["itemScores"]]
        # z-scores: list1 -> a=-1,b=0,c=1; list2 -> b=-1,c=0,d=1
        # summed: c=1, d=1, b=-1, a=-1 -> top2 = c, d
        assert items == ["c", "d"]
        assert out["itemScores"][0]["score"] == pytest.approx(1.0, abs=1e-6)
        assert out["itemScores"][1]["score"] == pytest.approx(1.0, abs=1e-6)
        # num == 1 skips standardization (raw scores summed)
        out1 = serving.serve({"num": 1}, preds)
        assert [s["item"] for s in out1["itemScores"]] == ["c"]
        assert out1["itemScores"][0]["score"] == pytest.approx(32.0)
        # stddev 0 -> score 0
        same = [{"itemScores": [{"item": "a", "score": 5.0},
                                {"item": "b", "score": 5.0}]}]
        out_same = serving.serve({"num": 2}, same)
        assert all(s["score"] == 0.0 for s in out_same["itemScores"])


@pytest.fixture()
def ecom_app(memory_storage):
    app = setup_app(memory_storage, "ecom")
    for u in ["u1", "u2", "u3"]:
        put(memory_storage, app.id, "$set", "user", u)
    cats = {"i1": ["a"], "i2": ["a"], "i3": ["b"], "i4": ["b"]}
    for i, cs in cats.items():
        put(memory_storage, app.id, "$set", "item", i, props={"categories": cs})
    rates = [
        ("u1", "i1", 5.0, 0), ("u1", "i2", 4.0, 1),
        ("u2", "i1", 4.0, 2), ("u2", "i2", 5.0, 3), ("u2", "i3", 1.0, 4),
        ("u3", "i3", 5.0, 5), ("u3", "i4", 4.0, 6),
        # u1 re-rates i1 later: latest value wins
        ("u1", "i1", 1.0, 7),
    ]
    for u, i, r, m in rates:
        put(memory_storage, app.id, "rate", "user", u, "item", i,
            props={"rating": r}, minute=m)
    return app


def _ecom_model(memory_storage, **algo_kw):
    engine = ecom_t.ecommerce_engine()
    ep = ecom_t.default_engine_params(
        "ecom",
        algo_params=ecom_t.ECommAlgorithmParams(
            app_name="ecom", rank=4, num_iterations=10, **algo_kw),
    )
    result = engine.train(ctx, ep)
    algo = engine.make_algorithms(ep)[0]
    return algo, result.models[0]


class TestECommerce:
    def test_datasource_and_latest_rating_dedupe(self, memory_storage, ecom_app):
        ds = ecom_t.ECommDataSource(ecom_t.ECommDSParams(app_name="ecom"))
        td = ds.read_training(ctx)
        assert len(td.rate_events) == 8
        algo, model = _ecom_model(memory_storage)
        assert model.user_factors.shape == (3, 4)
        assert model.item_factors.shape == (4, 4)

    def test_predict_known_user(self, memory_storage, ecom_app):
        algo, model = _ecom_model(memory_storage)
        out = algo.predict(model, {"user": "u2", "num": 2})
        assert out["itemScores"]
        items = [s["item"] for s in out["itemScores"]]
        assert len(items) <= 2

    def test_category_and_blacklist(self, memory_storage, ecom_app):
        algo, model = _ecom_model(memory_storage)
        out = algo.predict(
            model, {"user": "u1", "num": 4, "categories": ["b"]})
        assert all(s["item"] in {"i3", "i4"} for s in out["itemScores"])
        out = algo.predict(
            model, {"user": "u1", "num": 4, "blackList": ["i1", "i2", "i3", "i4"]})
        assert out["itemScores"] == []

    def test_unseen_only_filters_seen_items(self, memory_storage, ecom_app):
        # u1 "buys" i2 -> with unseen_only, i2 must not be recommended
        put(memory_storage, ecom_app.id, "buy", "user", "u1", "item", "i2", minute=40)
        algo, model = _ecom_model(memory_storage, unseen_only=True,
                                  seen_events=["buy"])
        out = algo.predict(model, {"user": "u1", "num": 4})
        assert all(s["item"] != "i2" for s in out["itemScores"])

    def test_unavailable_items_constraint(self, memory_storage, ecom_app):
        put(memory_storage, ecom_app.id, "$set", "constraint", "unavailableItems",
            props={"items": ["i1", "i2", "i3", "i4"]}, minute=41)
        algo, model = _ecom_model(memory_storage)
        assert algo.predict(model, {"user": "u2", "num": 4})["itemScores"] == []

    def test_new_user_falls_back_to_recent_views(self, memory_storage, ecom_app):
        # u9 was not in training but has viewed i1
        put(memory_storage, ecom_app.id, "$set", "user", "u9")
        put(memory_storage, ecom_app.id, "view", "user", "u9", "item", "i1", minute=42)
        algo, model = _ecom_model(memory_storage)
        out = algo.predict(model, {"user": "u9", "num": 3})
        assert out["itemScores"], "new user with recent views should get recs"
        assert all(s["item"] != "i1" or s["score"] > 0 for s in out["itemScores"])
        # new user with no history -> empty
        out = algo.predict(model, {"user": "u10", "num": 3})
        assert out["itemScores"] == []


@pytest.fixture()
def cls_app(memory_storage):
    app = setup_app(memory_storage, "cls")
    rng = np.random.default_rng(0)
    # two separable classes in count-feature space
    for n in range(30):
        label = float(n % 2)
        base = np.array([8.0, 1.0, 1.0]) if label == 0 else np.array([1.0, 1.0, 8.0])
        attrs = np.maximum(base + rng.integers(-1, 2, size=3), 0.0)
        put(memory_storage, app.id, "$set", "user", f"u{n}",
            props={"plan": label, "attr0": float(attrs[0]),
                   "attr1": float(attrs[1]), "attr2": float(attrs[2])})
    # an entity missing required properties is skipped (ref: required=...)
    put(memory_storage, app.id, "$set", "user", "incomplete", props={"plan": 1.0})
    return app


class TestClassification:
    def test_datasource_requires_all_properties(self, memory_storage, cls_app):
        ds = cls_t.ClassificationDataSource(
            cls_t.ClassificationDSParams(app_name="cls"))
        td = ds.read_training(ctx)
        assert td.features.shape == (30, 3)

    def test_naive_bayes_end_to_end(self, memory_storage, cls_app):
        engine = cls_t.classification_engine()
        ep = cls_t.default_engine_params("cls")
        model = engine.train(ctx, ep).models[0]
        assert model.predict([8.0, 1.0, 1.0]) == 0.0
        assert model.predict([1.0, 1.0, 8.0]) == 1.0

    def test_logistic_end_to_end(self, memory_storage, cls_app):
        from predictionio_tpu.core.params import EngineParams
        from predictionio_tpu.models.classification import LogisticRegressionParams

        engine = cls_t.classification_engine()
        ep = EngineParams(
            data_source_params=("", cls_t.ClassificationDSParams(app_name="cls")),
            algorithm_params_list=[
                ("logistic", LogisticRegressionParams(iterations=120)),
            ],
        )
        model = engine.train(ctx, ep).models[0]
        assert model.predict([8.0, 1.0, 1.0]) == 0.0
        assert model.predict([1.0, 1.0, 8.0]) == 1.0

    def test_eval_folds(self, memory_storage, cls_app):
        engine = cls_t.classification_engine()
        ep = cls_t.default_engine_params("cls", eval_k=3)
        results = engine.eval(ctx, ep)
        assert len(results) == 3
        # NB on separable data should get most test points right
        correct = total = 0
        for _ei, qpa in results:
            for _q, pred, actual in qpa:
                total += 1
                correct += pred["label"] == actual["label"]
        assert total == 30  # the incomplete entity contributes no point
        assert correct / total >= 0.8


class TestVanilla:
    def test_end_to_end(self, memory_storage):
        engine = vanilla_t.vanilla_engine()
        ep = vanilla_t.default_engine_params(mult=3)
        result = engine.train(ctx, ep)
        algo = engine.make_algorithms(ep)[0]
        assert algo.predict(result.models[0], {"q": 2.0}) == {"p": 6.0}


class TestRecommendationColumnar:
    """The bulk dict-encoded read path must train identically to the
    per-event row path (ref: DataSource.scala:31 semantics preserved)."""

    @pytest.fixture()
    def reco_app(self, memory_storage):
        app = setup_app(memory_storage, "reco-col")
        rng = np.random.default_rng(11)
        m = 0
        for u in range(12):
            for i in rng.choice(8, size=5, replace=False):
                if (u + i) % 3 == 0:
                    put(memory_storage, app.id, "buy", "user", f"u{u}",
                        "item", f"i{i}", minute=m)
                else:
                    put(memory_storage, app.id, "rate", "user", f"u{u}",
                        "item", f"i{i}",
                        props={"rating": float(1 + (u * i) % 5)}, minute=m)
                m += 1
        return app

    def test_columnar_matches_row_path(self, memory_storage, reco_app):
        from predictionio_tpu.templates import recommendation as reco_t

        ds_row = reco_t.RecoDataSource(
            reco_t.RecoDataSourceParams(app_name="reco-col", columnar=False)
        )
        ds_col = reco_t.RecoDataSource(
            reco_t.RecoDataSourceParams(app_name="reco-col", columnar=True)
        )
        prep = reco_t.RecoPreparator(None)
        pd_row = prep.prepare(ctx, ds_row.read_training(ctx))
        pd_col = prep.prepare(ctx, ds_col.read_training(ctx))

        # identical triples after resolving ids through each path's BiMap
        def resolved(pd):
            inv_u = pd.user_ids.inverse()
            inv_i = pd.item_ids.inverse()
            return sorted(
                (inv_u[int(u)], inv_i[int(i)], float(r))
                for u, i, r in zip(pd.user_idx, pd.item_idx, pd.ratings)
            )

        assert resolved(pd_row) == resolved(pd_col)
        assert len(pd_col.user_ids) == 12
        # buy events resolved to the constant buy_rating in both paths
        assert 4.0 in [r for _, _, r in resolved(pd_col)]


class TestECommerceLookupCache:
    """Serve-time lookups are TTL-cached so unseen_only doesn't scan
    storage inside every request (divergence documented on
    ECommAlgorithmParams; the reference scans per request, :148-251)."""

    def _spy(self, monkeypatch):
        calls = {"n": 0}
        real = ecom_t.store.find_by_entity

        def counting(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(ecom_t.store, "find_by_entity", counting)
        return calls

    def test_ttl_cache_bounds_storage_scans(self, memory_storage, ecom_app,
                                            monkeypatch):
        algo, model = _ecom_model(memory_storage, unseen_only=True,
                                  lookup_ttl_sec=60.0)
        calls = self._spy(monkeypatch)
        for _ in range(5):
            algo.predict(model, {"user": "u2", "num": 2})
        # one seen-items scan + one unavailable-items scan, then cached
        assert calls["n"] == 2, calls["n"]
        # a different user misses the per-user cache exactly once
        algo.predict(model, {"user": "u1", "num": 2})
        algo.predict(model, {"user": "u1", "num": 2})
        assert calls["n"] == 3

    def test_ttl_zero_restores_reference_behavior(self, memory_storage,
                                                  ecom_app, monkeypatch):
        algo, model = _ecom_model(memory_storage, unseen_only=True,
                                  lookup_ttl_sec=0.0)
        calls = self._spy(monkeypatch)
        algo.predict(model, {"user": "u2", "num": 2})
        algo.predict(model, {"user": "u2", "num": 2})
        assert calls["n"] == 4  # 2 lookups per request, uncached

    def test_cached_results_still_filter_seen(self, memory_storage, ecom_app):
        algo, model = _ecom_model(memory_storage, unseen_only=True,
                                  seen_events=["rate"], lookup_ttl_sec=60.0)
        for _ in range(2):
            out = algo.predict(model, {"user": "u2", "num": 4})
            items = [s["item"] for s in out["itemScores"]]
            assert not {"i1", "i2", "i3"} & set(items)


class TestColumnarRowEquivalence:
    """The bulk dict-encoded read path of the similarproduct and
    ecommerce templates must produce the SAME training data as the
    per-event row path — including the time order the latest-event-wins
    dedupers depend on (models/ecommerce.py:195,
    models/similarproduct.py:246)."""

    def test_similarproduct(self, memory_storage, simprod_app):
        row = simprod_t.SimilarProductDataSource(
            simprod_t.SimilarProductDSParams(app_name="simprod",
                                             columnar=False)
        ).read_training(ctx)
        col = simprod_t.SimilarProductDataSource(
            simprod_t.SimilarProductDSParams(app_name="simprod",
                                             columnar=True)
        ).read_training(ctx)
        assert col.users == row.users
        assert col.items == row.items
        assert col.item_categories == row.item_categories
        assert sorted(col.view_events) == sorted(row.view_events)
        # likes are time-ordered on both paths: latest-wins dedupe agrees
        latest_row = {(u, i): l for u, i, l in row.like_events}
        latest_col = {(u, i): l for u, i, l in col.like_events}
        assert latest_col == latest_row
        assert sorted(col.like_events) == sorted(row.like_events)

    def test_ecommerce(self, memory_storage, ecom_app):
        row = ecom_t.ECommDataSource(
            ecom_t.ECommDSParams(app_name="ecom", columnar=False)
        ).read_training(ctx)
        col = ecom_t.ECommDataSource(
            ecom_t.ECommDSParams(app_name="ecom", columnar=True)
        ).read_training(ctx)
        assert col.users == row.users and col.items == row.items
        assert sorted(col.rate_events) == sorted(row.rate_events)
        latest_row = {(u, i): r for u, i, r in row.rate_events}
        latest_col = {(u, i): r for u, i, r in col.rate_events}
        assert latest_col == latest_row

    def test_ecommerce_trains_identically(self, memory_storage, ecom_app):
        engine = ecom_t.ecommerce_engine()
        out = {}
        for flag in (False, True):
            ep = ecom_t.default_engine_params("ecom")
            ep.data_source_params[1].columnar = flag
            result = engine.train(ctx, ep)
            algo = engine.make_algorithms(ep)[0]
            out[flag] = algo.predict(result.models[0],
                                     {"user": "u1", "num": 3})
        assert out[True] == out[False]
