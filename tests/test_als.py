"""ALS compute core + DASE template end-to-end
(ref: MLlib ALS behavior used by examples/scala-parallel-recommendation)."""

import datetime as dt

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from predictionio_tpu.data.event import Event
from predictionio_tpu.models.als import ALSAlgorithm, ALSModel, ALSParams
from predictionio_tpu.ops.als import ALSConfig, als_train, predict_rmse
from predictionio_tpu.ops.ragged import build_padded_groups
from predictionio_tpu.ops.topk import TopKScorer, cosine_normalize
from predictionio_tpu.parallel.mesh import MeshContext, create_mesh
from predictionio_tpu.templates.recommendation import (
    RecoDataSourceParams,
    recommendation_engine,
)
from predictionio_tpu.workflow.deploy import prepare_deploy
from predictionio_tpu.workflow.train import run_train

UTC = dt.timezone.utc


# ---------------------------------------------------------------------------
# ragged -> padded binning
# ---------------------------------------------------------------------------

def test_padded_groups_basic():
    g = np.array([0, 0, 2, 2, 2])
    i = np.array([10, 11, 20, 21, 22])
    v = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    pg = build_padded_groups(g, i, v, n_groups=3, len_multiple=4)
    assert pg.idx.shape == (3, 4)
    assert pg.counts.tolist() == [2, 0, 3]
    assert pg.idx[0, :2].tolist() == [10, 11]
    assert pg.mask[0].tolist() == [1, 1, 0, 0]
    assert pg.val[2, :3].tolist() == [3.0, 4.0, 5.0]
    assert pg.mask[1].sum() == 0


def test_segmented_groups_splits_long_groups():
    from predictionio_tpu.ops.ragged import build_segmented_groups

    # 3 groups: sizes 5, 0, 11; L=8 -> rows 1, 0, 2
    g = np.array([0] * 5 + [2] * 11)
    i = np.arange(16)
    v = np.arange(16, dtype=float)
    sg = build_segmented_groups(g, i, v, n_groups=3, seg_len=8)
    assert sg.counts.tolist() == [5, 0, 11] + [0] * (len(sg.counts) - 3)
    assert sg.idx[0, :5].tolist() == [0, 1, 2, 3, 4]
    assert sg.idx[1].tolist() == list(range(5, 13))     # group 2 part 1
    assert sg.idx[2, :3].tolist() == [13, 14, 15]       # group 2 part 2
    assert sg.seg[:3].tolist() == [0, 2, 2]
    # seg nondecreasing (sorted-scatter invariant), incl. padded rows
    assert all(a <= b for a, b in zip(sg.seg, sg.seg[1:]))
    assert sg.rows_per_shard % sg.row_block == 0
    assert sg.groups_per_shard % sg.group_block == 0


def test_segmented_groups_sharded_layout():
    from predictionio_tpu.ops.ragged import build_segmented_groups

    g = np.array([0] * 5 + [2] * 11)
    i = np.arange(16)
    v = np.ones(16, dtype=float)
    sg = build_segmented_groups(g, i, v, n_groups=3, seg_len=8, n_shards=2)
    # shard 0 owns groups [0, g_per_shard), shard 1 the rest; every
    # shard sees the same (padded) row count and local segment ids
    assert sg.idx.shape[0] == 2 * sg.rows_per_shard
    s1 = slice(sg.rows_per_shard, 2 * sg.rows_per_shard)
    for shard_seg in (sg.seg[: sg.rows_per_shard], sg.seg[s1]):
        assert all(a <= b for a, b in zip(shard_seg, shard_seg[1:]))
        assert shard_seg.max() < sg.groups_per_shard
    # all 16 entries present exactly once
    assert int(sg.mask.sum()) == 16


def test_segmented_groups_max_len_keeps_latest():
    from predictionio_tpu.ops.ragged import build_segmented_groups

    g = np.zeros(10, dtype=int)
    i = np.arange(10)
    v = np.arange(10, dtype=float)
    sg = build_segmented_groups(g, i, v, n_groups=1, seg_len=8, max_len=6)
    assert sg.counts[0] == 6
    assert sg.idx[0, :6].tolist() == [4, 5, 6, 7, 8, 9]


def test_padded_groups_truncation_keeps_latest():
    g = np.zeros(10, dtype=int)
    i = np.arange(10)
    v = np.arange(10, dtype=float)
    pg = build_padded_groups(g, i, v, n_groups=1, max_len=4, len_multiple=4)
    # keeps the LAST 4 entries (recency)
    assert pg.idx[0].tolist() == [6, 7, 8, 9]
    assert pg.counts[0] == 4


def test_padded_groups_group_axis_padding():
    pg = build_padded_groups(np.array([0]), np.array([1]), np.array([1.0]),
                             n_groups=3, group_multiple=8)
    assert pg.idx.shape[0] == 8
    assert pg.n_groups == 3
    assert pg.mask[3:].sum() == 0


# ---------------------------------------------------------------------------
# ALS solver
# ---------------------------------------------------------------------------

def _synthetic(n_u=200, n_i=80, k=4, density=0.3, seed=0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_u, k))
    V = rng.normal(size=(n_i, k))
    R = U @ V.T
    mask = rng.random((n_u, n_i)) < density
    uu, ii = np.nonzero(mask)
    return (uu, ii, R[uu, ii].astype(np.float32)), R, mask


def test_als_recovers_low_rank_matrix():
    coo, R, mask = _synthetic()
    cfg = ALSConfig(rank=6, iterations=10, reg=0.01, block_size=64)
    f = als_train(coo, 200, 80, cfg)
    assert predict_rmse(f, coo) < 0.1
    # generalization to held-out entries of the low-rank matrix
    uu, ii = np.nonzero(~mask)
    heldout_rmse = float(
        np.sqrt(np.mean((np.einsum("nk,nk->n", f.user_factors[uu], f.item_factors[ii]) - R[uu, ii]) ** 2))
    )
    assert heldout_rmse < 0.5


def test_als_mesh_matches_single_device():
    coo, _, _ = _synthetic()
    cfg = ALSConfig(rank=6, iterations=5, reg=0.05, block_size=32)
    f1 = als_train(coo, 200, 80, cfg)
    mesh = create_mesh({"data": 8})
    f8 = als_train(coo, 200, 80, cfg, mesh=mesh)
    np.testing.assert_allclose(f1.user_factors, f8.user_factors, atol=1e-4)
    np.testing.assert_allclose(f1.item_factors, f8.item_factors, atol=1e-4)


def test_als_implicit_separates_positives():
    rng = np.random.default_rng(1)
    coo, R, mask = _synthetic(density=0.2, seed=1)
    uu, ii, vals = coo
    pos = vals > 0
    cfg = ALSConfig(rank=8, iterations=8, reg=0.1, implicit=True, alpha=40.0, block_size=64)
    f = als_train((uu[pos], ii[pos], np.ones(pos.sum(), np.float32)), 200, 80, cfg)
    pred_pos = np.einsum("nk,nk->n", f.user_factors[uu[pos]], f.item_factors[ii[pos]]).mean()
    nu, ni = np.nonzero(~mask)
    pred_un = np.einsum("nk,nk->n", f.user_factors[nu], f.item_factors[ni]).mean()
    assert pred_pos > pred_un + 0.2


def test_als_empty_users_get_zero_factors():
    # user 5 has no ratings; solver must stay nonsingular and return zeros
    coo = (np.array([0, 1]), np.array([0, 1]), np.array([1.0, 2.0], np.float32))
    cfg = ALSConfig(rank=4, iterations=2, reg=0.1, block_size=8)
    f = als_train(coo, 6, 2, cfg)
    assert np.all(np.isfinite(f.user_factors))
    np.testing.assert_allclose(f.user_factors[5], 0.0, atol=1e-6)


# ---------------------------------------------------------------------------
# top-k scoring
# ---------------------------------------------------------------------------

def test_topk_scorer_and_exclusion():
    Y = np.eye(4, dtype=np.float32)  # 4 items = unit axes
    scorer = TopKScorer(Y)
    u = np.array([[3.0, 2.0, 1.0, 0.5]], dtype=np.float32)
    scores, idx = scorer.score(u, 2)
    assert idx[0].tolist() == [0, 1]
    scores, idx = scorer.score(u, 2, exclude_idx=np.array([[0, -1]], dtype=np.int32))
    assert idx[0].tolist() == [1, 2]


def test_cosine_normalize():
    m = np.array([[3.0, 4.0], [0.0, 0.0]])
    n = cosine_normalize(m)
    np.testing.assert_allclose(n[0], [0.6, 0.8])
    assert np.all(np.isfinite(n))


# ---------------------------------------------------------------------------
# DASE template end-to-end
# ---------------------------------------------------------------------------

def _seed_events(storage, app_name="reco-app"):
    app = storage.apps().insert(app_name)
    storage.events().init(app.id)
    rng = np.random.default_rng(42)
    # 30 users x 12 items, block structure: users 0-14 like items 0-5,
    # users 15-29 like items 6-11
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    n = 0
    for u in range(30):
        liked = range(6) if u < 15 else range(6, 12)
        disliked = range(6, 12) if u < 15 else range(6)
        for i in liked:
            if rng.random() < 0.8:
                storage.events().insert(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties={"rating": 5.0},
                          event_time=t0 + dt.timedelta(minutes=n)), app.id)
                n += 1
        for i in disliked:
            if rng.random() < 0.5:
                storage.events().insert(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties={"rating": 1.0},
                          event_time=t0 + dt.timedelta(minutes=n)), app.id)
                n += 1
        # a few buys (implicit 4.0)
        storage.events().insert(
            Event(event="buy", entity_type="user", entity_id=f"u{u}",
                  target_entity_type="item",
                  target_entity_id=f"i{list(liked)[0]}",
                  event_time=t0 + dt.timedelta(minutes=n)), app.id)
        n += 1
    return app


def test_recommendation_template_end_to_end(memory_storage):
    _seed_events(memory_storage)
    engine = recommendation_engine()
    ep = engine.engine_params_from_variant({
        "engineFactory": "predictionio_tpu.templates.recommendation.recommendation_engine",
        "datasource": {"name": "", "params": {"app_name": "reco-app"}},
        "algorithms": [{"name": "als", "params": {
            "rank": 8, "num_iterations": 8, "lambda_": 0.05, "block_size": 32}}],
    })
    ctx = MeshContext(mesh=create_mesh({"data": 8}))
    instance = run_train(engine, ep, engine_id="reco", storage=memory_storage, ctx=ctx)
    assert instance.status == "COMPLETED"

    deployment = prepare_deploy(engine, instance, ctx, memory_storage)
    result = deployment.query({"user": "u3", "num": 4})
    items = [r["item"] for r in result["itemScores"]]
    assert len(items) == 4
    # u3 is in the first block: recommendations should be block-0 items
    assert sum(1 for i in items if int(i[1:]) < 6) >= 3
    scores = [r["score"] for r in result["itemScores"]]
    assert scores == sorted(scores, reverse=True)
    # unknown user -> empty result, not an error
    assert deployment.query({"user": "nobody", "num": 3}) == {"itemScores": []}


def test_recommendation_read_eval_folds(memory_storage):
    _seed_events(memory_storage, "reco-eval")
    ds = RecoDataSource = None
    from predictionio_tpu.templates.recommendation import RecoDataSource

    ds = RecoDataSource(RecoDataSourceParams(app_name="reco-eval", eval_k=3))
    folds = ds.read_eval(MeshContext())
    assert len(folds) == 3
    total = sum(len(qa) for _, _, qa in folds)
    all_train = sum(len(td.ratings) for td, _, _ in folds)
    # each rating appears in exactly one test fold and k-1 train folds
    assert all_train == 2 * total
    q, a = folds[0][2][0]
    assert set(q) == {"user", "num"} and set(a) == {"item", "rating"}


def test_als_batch_predict_matches_predict(memory_storage):
    _seed_events(memory_storage, "reco-bp")
    engine = recommendation_engine()
    ep = engine.engine_params_from_variant({
        "engineFactory": "x",
        "datasource": {"name": "", "params": {"app_name": "reco-bp"}},
        "algorithms": [{"name": "als", "params": {"rank": 4, "num_iterations": 4,
                                                   "block_size": 32}}],
    })
    ctx = MeshContext()
    result = engine.train(ctx, ep)
    algo = engine.make_algorithms(ep)[0]
    model = result.models[0]
    queries = [(0, {"user": "u1", "num": 3}), (1, {"user": "nobody", "num": 3}),
               (2, {"user": "u20", "num": 2})]
    batch = dict(algo.batch_predict(model, queries))
    assert [r["item"] for r in batch[0]["itemScores"]] == \
        [r["item"] for r in algo.predict(model, {"user": "u1", "num": 3})["itemScores"]]
    assert batch[1] == {"itemScores": []}
    assert len(batch[2]["itemScores"]) == 2


def test_whitelist_respects_blacklist(memory_storage):
    _seed_events(memory_storage, "reco-wl")
    engine = recommendation_engine()
    ep = engine.engine_params_from_variant({
        "engineFactory": "x",
        "datasource": {"name": "", "params": {"app_name": "reco-wl"}},
        "algorithms": [{"name": "als", "params": {"rank": 4, "num_iterations": 4,
                                                   "block_size": 32}}],
    })
    result = engine.train(MeshContext(), ep)
    algo = engine.make_algorithms(ep)[0]
    model = result.models[0]
    out = algo.predict(model, {
        "user": "u1", "num": 5, "whitelist": ["i0", "i1", "i2"], "blacklist": ["i1"],
    })
    items = [r["item"] for r in out["itemScores"]]
    assert "i1" not in items
    assert set(items) <= {"i0", "i2"}


def test_topk_shape_bucketing():
    """Varying k / exclusion widths must reuse a few compiled shapes."""
    Y = np.arange(40, dtype=np.float32).reshape(20, 2)
    scorer = TopKScorer(Y, max_exclude=8)
    u = np.ones((1, 2), dtype=np.float32)
    for k in (1, 3, 5, 7):
        scores, idx = scorer.score(u, k, exclude_idx=np.arange(k, dtype=np.int32))
        assert scores.shape == (1, k)
        assert not set(idx[0].tolist()) & set(range(k))
    # overlong exclusion list is truncated to max_exclude, keeping the tail
    long_excl = np.arange(12, dtype=np.int32)
    _, idx = scorer.score(u, 5, exclude_idx=long_excl)
    assert not set(idx[0].tolist()) & set(range(4, 12))


def test_grid_train_vmapped_matches_sequential():
    """als_grid_train: all reg grid points in ONE vmapped program
    (SURVEY.md §7.6 — grid points vmapped, a capability Spark's
    sequential batchEval never had)."""
    from predictionio_tpu.ops.als import als_grid_train, predict_rmse

    rng = np.random.default_rng(9)
    nnz, n_users, n_items = 600, 40, 16
    coo = (rng.integers(0, n_users, nnz), rng.integers(0, n_items, nnz),
           (rng.random(nnz) * 4 + 1).astype(np.float32))
    cfg = ALSConfig(rank=8, iterations=4, block_size=16, seg_len=8,
                    compute_dtype="float32", cg_dtype="float32")

    out = als_grid_train(coo, n_users, n_items, cfg,
                         regs=[0.05, 0.05, 1.0, 10.0])
    assert len(out) == 4
    # identical regs (+ shared init) -> identical factors
    np.testing.assert_array_equal(out[0].user_factors, out[1].user_factors)
    # stronger regularization -> smaller factors, worse train fit
    n0 = np.linalg.norm(out[0].user_factors)
    n3 = np.linalg.norm(out[3].user_factors)
    assert n3 < n0
    assert predict_rmse(out[0], coo) < predict_rmse(out[3], coo)
    # each grid point trains as well as a dedicated sequential run
    for reg, factors in zip((0.05, 1.0), (out[0], out[2])):
        solo = als_train(coo, n_users, n_items,
                         ALSConfig(rank=8, iterations=4, reg=reg,
                                   block_size=16, seg_len=8,
                                   compute_dtype="float32",
                                   cg_dtype="float32"))
        grid_rmse = predict_rmse(factors, coo)
        solo_rmse = predict_rmse(solo, coo)
        assert abs(grid_rmse - solo_rmse) < 0.05, (reg, grid_rmse, solo_rmse)


def test_grid_train_multi_scalar_matches_sequential():
    """VERDICT r4 item 6: candidates differing in reg AND iteration
    budget AND cg budget ride ONE vmapped dispatch — each candidate's
    factors match its own dedicated sequential run (the run-to-max +
    freeze masking must be numerically faithful, not approximate)."""
    import dataclasses

    from predictionio_tpu.ops.als import als_grid_train

    rng = np.random.default_rng(11)
    n, n_users, n_items = 8000, 120, 40
    coo = (rng.integers(0, n_users, n), rng.integers(0, n_items, n),
           (1.0 + rng.integers(0, 9, n) * 0.5).astype(np.float32))
    cfg = ALSConfig(rank=4, iterations=4, reg=0.1, block_size=32,
                    compute_dtype="float32", cg_dtype="float32")
    regs = [0.05, 0.1, 0.5]
    iters = [2, 4, 3]
    cgs = [6, 4, 6]
    out = als_grid_train(coo, n_users, n_items, cfg, regs=regs,
                        iterations=iters, cg_iters=cgs)
    assert len(out) == 3
    for g, (reg, it, cg) in enumerate(zip(regs, iters, cgs)):
        solo = als_train(coo, n_users, n_items, dataclasses.replace(
            cfg, reg=reg, iterations=it, cg_iters=cg))
        np.testing.assert_allclose(
            out[g].user_factors, solo.user_factors, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            out[g].item_factors, solo.item_factors, rtol=2e-4, atol=2e-4)


def test_grid_train_implicit_alpha_axis():
    """The implicit-feedback confidence scale rides the grid too."""
    import dataclasses

    from predictionio_tpu.ops.als import als_grid_train

    rng = np.random.default_rng(3)
    n, n_users, n_items = 5000, 80, 30
    coo = (rng.integers(0, n_users, n), rng.integers(0, n_items, n),
           rng.integers(1, 6, n).astype(np.float32))
    cfg = ALSConfig(rank=4, iterations=3, reg=0.1, block_size=32,
                    implicit=True, compute_dtype="float32",
                    cg_dtype="float32")
    alphas = [0.5, 2.0, 8.0]
    out = als_grid_train(coo, n_users, n_items, cfg,
                        regs=[0.1] * 3, alphas=alphas)
    for g, alpha in enumerate(alphas):
        solo = als_train(coo, n_users, n_items,
                         dataclasses.replace(cfg, alpha=alpha))
        # vmapped YtY/einsum reduce order differs slightly from the
        # sequential program: tolerance, not exactness, is the contract
        np.testing.assert_allclose(
            out[g].user_factors, solo.user_factors, rtol=6e-4, atol=6e-4)


def test_map_batch_matches_default():
    """map_batch (lax.map batch_size) is a measured-rejected perf knob
    kept for re-measurement; its vmapped path must stay numerically
    equal to the default, including a batch that does not divide the
    block count."""
    import dataclasses

    coo = (np.array([0, 1, 2, 3, 1, 2]), np.array([0, 1, 0, 1, 0, 1]),
           np.array([1.0, 2.0, 3.0, 4.0, 5.0, 1.5], np.float32))
    cfg = ALSConfig(rank=4, iterations=2, reg=0.1, block_size=8,
                    compute_dtype="float32", cg_dtype="float32")
    base = als_train(coo, 5, 2, cfg)
    for mb in (2, 3):
        f = als_train(coo, 5, 2, dataclasses.replace(cfg, map_batch=mb))
        np.testing.assert_allclose(f.user_factors, base.user_factors,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(f.item_factors, base.item_factors,
                                   rtol=1e-5, atol=1e-5)


def test_grid_train_validates_candidate_list_lengths():
    """Mismatched per-candidate lists must raise ValueError (a bare
    assert vanishes under `python -O` and would vmap over garbage
    scalars — advisor finding, r6), and must raise BEFORE any layout
    work touches the device."""
    from predictionio_tpu.ops.als import als_grid_train

    rng = np.random.default_rng(2)
    coo = (rng.integers(0, 12, 60), rng.integers(0, 8, 60),
           (rng.random(60) * 4 + 1).astype(np.float32))
    cfg = ALSConfig(rank=4, iterations=2, block_size=8, seg_len=8)
    for kw in ({"alphas": [1.0]}, {"iterations": [2, 3, 4]},
               {"cg_iters": [4]}):
        name = next(iter(kw))
        with pytest.raises(ValueError, match=f"`{name}`.*match len\\(regs\\)"):
            als_grid_train(coo, 12, 8, cfg, regs=[0.1, 0.2], **kw)
