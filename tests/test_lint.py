"""graftlint unit tests: per-rule positive/negative fixtures.

Each rule JT01-JT06 gets at least one fixture that MUST fire and one
that MUST stay silent, written as real (parseable) source so the rules
are exercised end-to-end through lint_file, including suppression
handling. Nothing here imports jax — graftlint is pure AST.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from predictionio_tpu.tools.lint import (
    PROJECT_RULES,
    RULES,
    lint_file,
    lint_paths,
    lint_project,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
PACKAGE = REPO_ROOT / "predictionio_tpu"


def lint_src(tmp_path: Path, src: str, relpath: str = "mod.py"):
    """Write ``src`` under tmp_path at ``relpath`` and lint that file."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return lint_file(str(path))


def lint_project_src(tmp_path: Path, src: str, relpath: str = "mod.py"):
    """Write ``src`` under tmp_path and run WHOLE-PROGRAM mode over the
    directory (per-file rules plus JT18-JT21) — the fixture project is
    exactly the files written so far."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    findings, _files = lint_project([str(tmp_path)])
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


# -- engine behavior -----------------------------------------------------------

def test_all_rules_registered():
    assert {"JT01", "JT02", "JT03", "JT04", "JT05", "JT06",
            "JT07", "JT08", "JT09", "JT10", "JT11", "JT12",
            "JT13", "JT14", "JT15", "JT16", "JT17",
            "JT22", "JT23"} <= set(RULES)
    # the whole-program concurrency layer registers separately: project
    # rules never run in per-file mode
    assert {"JT18", "JT19", "JT20", "JT21"} == set(PROJECT_RULES)
    assert not {"JT18", "JT19", "JT20", "JT21"} & set(RULES)


def test_syntax_error_is_reported_not_raised(tmp_path):
    findings = lint_src(tmp_path, "def broken(:\n")
    assert rule_ids(findings) == ["GL01"]


def test_line_suppression_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            return float(x)  # graftlint: disable=JT01 — fixture: reviewed host sync
    """)
    assert findings == []


def test_file_suppression(tmp_path):
    findings = lint_src(tmp_path, """\
        # graftlint: disable-file=JT04 — fixture: probe loop, degradation is the signal
        def f():
            try:
                g()
            except Exception:
                pass
    """, relpath="serving/probe.py")
    assert findings == []


def test_unjustified_suppression_is_gl00(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax

        @jax.jit
        def f(x):
            return float(x)  # graftlint: disable=JT01
    """)
    # the JT01 is suppressed, but the bare suppression itself is flagged
    assert rule_ids(findings) == ["GL00"]


def test_gl00_is_not_suppressible(tmp_path):
    # disable=all hides the JT01 but can NOT hide its own GL00 — an
    # unjustified blanket suppression must never pass the gate
    findings = lint_src(tmp_path, """\
        import jax

        @jax.jit
        def f(x):
            return float(x)  # graftlint: disable=all
    """)
    assert rule_ids(findings) == ["GL00"]


def test_suppression_inside_docstring_is_inert(tmp_path):
    findings = lint_src(tmp_path, '''\
        """Docs quoting the syntax:

            x = 1  # graftlint: disable-file=JT01 — example only
        """
        import jax

        @jax.jit
        def f(x):
            return float(x)
    ''')
    assert rule_ids(findings) == ["JT01"]


# -- JT01 host-sync-in-jit -----------------------------------------------------

def test_jt01_positive_host_casts_in_jit(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            a = float(x)
            b = x.item()
            c = np.asarray(x)
            return a, b, c
    """)
    assert rule_ids(findings) == ["JT01", "JT01", "JT01"]


def test_jt01_positive_partial_jit(tmp_path):
    findings = lint_src(tmp_path, """\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            return int(x)
    """)
    assert rule_ids(findings) == ["JT01"]


def test_jt01_positive_double_conversion_outside_jit(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax.numpy as jnp
        import numpy as np

        def predict(xs):
            return jnp.asarray(np.asarray(xs, dtype=np.float32))
    """)
    assert rule_ids(findings) == ["JT01"]
    assert "redundant double conversion" in findings[0].message


def test_jt01_negative(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            n = int(x.shape[0])      # static shape metadata: fine
            return jnp.sum(x) / n

        def host_side(x):
            return float(np.asarray(x)[0])   # not under jit: fine
    """)
    assert findings == []


def test_jt01_negative_static_param_casts(tmp_path):
    findings = lint_src(tmp_path, """\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            return x * float(n)   # n is a concrete Python value at trace
    """)
    assert findings == []


# -- JT02 python-branch-on-tracer ---------------------------------------------

def test_jt02_positive_if_and_while(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            while x < 0:
                x = x + 1
            return -x
    """)
    assert rule_ids(findings) == ["JT02", "JT02"]
    assert "`x`" in findings[0].message


def test_jt02_negative_static_and_shape_branches(tmp_path):
    findings = lint_src(tmp_path, """\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "train":          # static arg: fine
                x = x * 2
            if x.shape[0] > 2:           # shape metadata: fine
                x = x[:2]
            if len(x) > 4:               # len() is static under trace
                x = x[:4]
            return x

        def g(x):
            if x > 0:                    # not under jit: fine
                return x
            return -x
    """)
    assert findings == []


# -- JT03 low-precision-accumulation ------------------------------------------

def test_jt03_positive_direct_and_tainted(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax.numpy as jnp

        def gramian(x, w):
            s = jnp.sum(x.astype(jnp.bfloat16), axis=0)
            xb = x.astype(jnp.bfloat16)
            g = jnp.matmul(xb, w)
            h = xb @ w
            return s, g, h
    """)
    assert rule_ids(findings) == ["JT03", "JT03", "JT03"]


def test_jt03_negative_f32_accumulators(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax.numpy as jnp

        def gramian(x, w, compute_dtype):
            s = jnp.sum(x.astype(jnp.bfloat16), axis=0, dtype=jnp.float32)
            xb = x.astype(jnp.bfloat16)
            g = jnp.matmul(xb, w, preferred_element_type=jnp.float32)
            e = jnp.einsum("ij,jk->ik", xb, w,
                           preferred_element_type=jnp.float32)
            xv = x.astype(compute_dtype)   # dynamic dtype: not flagged
            return s, g, e, jnp.sum(xv), jnp.sum(x)
    """)
    assert findings == []


# -- JT04 silent-broad-except --------------------------------------------------

def test_jt04_positive_in_scoped_paths(tmp_path):
    src = """\
        def f():
            try:
                g()
            except Exception:
                pass
    """
    for rel in ("serving/foo.py", "workflow/bar.py", "data/storage.py"):
        findings = lint_src(tmp_path, src, relpath=rel)
        assert rule_ids(findings) == ["JT04"], rel


def test_jt04_negative(tmp_path):
    findings = lint_src(tmp_path, """\
        import logging

        log = logging.getLogger(__name__)

        def logs():
            try:
                g()
            except Exception:
                log.exception("g failed")

        def reraises():
            try:
                g()
            except Exception:
                raise

        def relays(p):
            try:
                g()
            except Exception as e:   # relayed to the caller, not silent
                p.error = e

        def narrow():
            try:
                g()
            except ValueError:       # narrowed type: out of JT04 scope
                pass
    """, relpath="serving/ok.py")
    assert findings == []


def test_jt04_silent_outside_scoped_paths_is_fine(tmp_path):
    findings = lint_src(tmp_path, """\
        def f():
            try:
                g()
            except Exception:
                pass
    """, relpath="ops/kernel_helpers.py")
    assert findings == []


# -- JT05 mesh-axis-consistency ------------------------------------------------

MESH_PY = """\
    MESH_AXES = ("data", "model")
"""


def test_jt05_positive_undeclared_axis(tmp_path):
    (tmp_path / "pkg" / "parallel").mkdir(parents=True)
    (tmp_path / "pkg" / "parallel" / "mesh.py").write_text(
        textwrap.dedent(MESH_PY))
    findings = lint_src(tmp_path, """\
        from jax.sharding import PartitionSpec as P

        SPEC = P("batch", None)
    """, relpath="pkg/ops/kernel.py")
    assert rule_ids(findings) == ["JT05"]
    assert "'batch'" in findings[0].message


def test_jt05_negative_declared_axes(tmp_path):
    (tmp_path / "pkg" / "parallel").mkdir(parents=True)
    (tmp_path / "pkg" / "parallel" / "mesh.py").write_text(
        textwrap.dedent(MESH_PY))
    findings = lint_src(tmp_path, """\
        from jax.sharding import NamedSharding, PartitionSpec as P

        SPEC = P("data", None)
        REP = P()
        NESTED = P(("data", "model"), None)

        def dynamic(mesh):
            return P(mesh.axis_names[0])   # non-literal: not checked
    """, relpath="pkg/ops/kernel.py")
    assert findings == []


def test_jt05_reads_custom_mesh_axes(tmp_path):
    (tmp_path / "pkg" / "parallel").mkdir(parents=True)
    (tmp_path / "pkg" / "parallel" / "mesh.py").write_text(
        'MESH_AXES = ("stage", "expert")\n')
    findings = lint_src(tmp_path, """\
        from jax.sharding import PartitionSpec as P

        A = P("stage")
        B = P("data")
    """, relpath="pkg/templates/moe.py")
    assert rule_ids(findings) == ["JT05"]
    assert "'data'" in findings[0].message


# -- JT06 blocking-transfer-in-handler ----------------------------------------

def test_jt06_positive_blocking_in_handler(tmp_path):
    findings = lint_src(tmp_path, """\
        import numpy as np

        class _QueryRequestHandler:
            def do_POST(self):
                result = self.model.predict(self.payload)
                result.block_until_ready()
                self._send(200, np.asarray(result).tolist())
    """, relpath="serving/query_server.py")
    assert rule_ids(findings) == ["JT06", "JT06"]


def test_jt06_negative(tmp_path):
    findings = lint_src(tmp_path, """\
        import numpy as np

        class _QueryRequestHandler:
            def do_POST(self):
                # device work routed through the micro-batcher
                self._send(200, self.server_ref.query(self.payload))

        class BatchWorker:            # not a handler class
            def drain(self, result):
                result.block_until_ready()
    """, relpath="serving/query_server.py")
    assert findings == []


def test_jt06_only_applies_to_server_modules(tmp_path):
    findings = lint_src(tmp_path, """\
        class _Handler:
            def do_GET(self, x):
                x.block_until_ready()
    """, relpath="ops/not_a_server.py")
    assert findings == []


# -- JT07 missing-buffer-donation ---------------------------------------------

def test_jt07_positive_decorated_step(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax

        @jax.jit
        def train_step(params, opt_state, batch):
            return params, opt_state, 0.0

        def loop(params, opt_state, batches):
            for b in batches:
                params, opt_state, loss = train_step(params, opt_state, b)
            return params
    """)
    assert rule_ids(findings) == ["JT07"]
    assert "opt_state, params" in findings[0].message


def test_jt07_positive_jit_assignment_and_attribute_target(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax

        class Trainer:
            def __init__(self, step_fn):
                self._step = jax.jit(step_fn)

            def run(self, batch):
                self.params, loss = self._step(self.params, batch)
                return loss
    """)
    assert rule_ids(findings) == ["JT07"]
    assert "`self._step`" in findings[0].message


def test_jt07_negative_donated_and_unrelated(tmp_path):
    findings = lint_src(tmp_path, """\
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, batch):
            return params, opt_state, 0.0

        @jax.jit
        def score(params, batch):
            return 0.0

        def loop(params, opt_state, batches):
            stepper = jax.jit(lambda p, b: p, donate_argnames=("p",))
            for b in batches:
                params, opt_state, loss = train_step(params, opt_state, b)
                params = stepper(params, b)
                loss = score(params, b)          # no rebind of an arg
                other = not_jitted(params, b)    # unknown callee: silent
            return params
    """)
    assert findings == []


# -- JT08 compile-cache-key-instability ---------------------------------------

def test_jt08_positive_closure_over_dict_and_clock(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        import time

        def build_step(cfg):
            tables = {"a": 1, "b": 2}
            started = time.time()
            step = jax.jit(lambda x: x * tables["a"] + started)
            return step
    """)
    assert rule_ids(findings) == ["JT08", "JT08"]
    messages = " ".join(f.message for f in findings)
    assert "`tables`" in messages and "`started`" in messages


def test_jt08_positive_decorated_nested_def_and_direct_call(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        import os

        def build(cfg):
            layout = [1, 2, 3]

            @jax.jit
            def inner(x):
                return x + layout[0]

            return inner

        @jax.jit
        def stamped(x):
            return x + os.getpid()
    """)
    assert sorted(rule_ids(findings)) == ["JT08", "JT08"]
    messages = " ".join(f.message for f in findings)
    assert "`layout`" in messages and "os.getpid" in messages


def test_jt08_negative_stable_captures(tmp_path):
    # scalar config reads, module constants, declared-static args and
    # jax.random (pure function of an explicit key) are all cache-stable
    findings = lint_src(tmp_path, """\
        from functools import partial
        import jax

        SCALE = 2.0

        def build_step(cfg):
            rate = cfg.rate
            key = jax.random.PRNGKey(0)
            step = jax.jit(lambda x: x * rate * SCALE)

            @partial(jax.jit, static_argnames=("n",))
            def inner(x, n):
                return x + jax.random.normal(key, (n,))

            return step, inner
    """)
    assert findings == []


def test_jt08_negative_sibling_scope_locals_do_not_leak(tmp_path):
    # a sibling helper's LOCAL `layout` must not shadow the stable
    # module-level value the closure actually captures
    findings = lint_src(tmp_path, """\
        import jax

        layout = (1, 2, 3)

        def outer():
            def helper():
                layout = [1, 2]
                return layout

            step = jax.jit(lambda x: x + layout[0])
            return helper, step
    """)
    assert findings == []


def test_jt08_negative_dict_as_argument_not_capture(tmp_path):
    # passing the mapping IN (traced or static argument) is the fix —
    # the rule must not flag the corrected form
    findings = lint_src(tmp_path, """\
        import jax

        def build_step(cfg):
            tables = {"a": 1}

            def inner(x, scale):
                return x * scale

            return jax.jit(inner)(1.0, tables["a"])
    """)
    assert findings == []


# -- the committed tree is clean ----------------------------------------------

def test_self_check_committed_tree_is_clean():
    """`python -m predictionio_tpu.tools.lint predictionio_tpu/` exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.tools.lint",
         str(PACKAGE)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, f"\n{proc.stdout}\n{proc.stderr}"
    assert "clean" in proc.stdout


def test_json_output_shape(tmp_path):
    import json

    proc = subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.tools.lint",
         "--format", "json", str(PACKAGE / "models")],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
        timeout=120,
    )
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["files_scanned"] > 0


# -- JT09 unsupervised-daemon-thread -------------------------------------------

def test_jt09_positive_bare_loop_thread(tmp_path):
    findings = lint_src(tmp_path, """\
        import threading

        def _loop():
            while True:
                do_work()

        threading.Thread(target=_loop, daemon=True).start()
    """)
    assert rule_ids(findings) == ["JT09"]
    assert "_loop" in findings[0].message


def test_jt09_positive_method_target_and_narrow_except(tmp_path):
    # a narrow except (queue.Empty) is flow control, not supervision —
    # any other exception still kills the thread silently
    findings = lint_src(tmp_path, """\
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while not self.stopped:
                    try:
                        item = self.q.get_nowait()
                    except queue.Empty:
                        continue
                    self.handle(item)
    """)
    assert rule_ids(findings) == ["JT09"]


def test_jt09_negative_supervised_inside_loop(tmp_path):
    findings = lint_src(tmp_path, """\
        import logging
        import threading

        log = logging.getLogger(__name__)

        def _loop():
            while True:
                try:
                    do_work()
                except Exception:
                    log.exception("iteration failed")

        threading.Thread(target=_loop, daemon=True).start()
    """)
    assert findings == []


def test_jt09_negative_supervised_around_loop(tmp_path):
    # a broad-except-log WRAPPING the loop still logs the thread's
    # death — not silent, so not a finding
    findings = lint_src(tmp_path, """\
        import logging
        import threading

        log = logging.getLogger(__name__)

        def _run():
            try:
                while True:
                    step()
            except Exception:
                log.exception("worker died")

        threading.Thread(target=_run).start()
    """)
    assert findings == []


def test_jt09_negative_looplss_target_and_external_callable(tmp_path):
    findings = lint_src(tmp_path, """\
        import threading

        def _once():
            send_one_request()

        def start(server):
            threading.Thread(target=_once, daemon=True).start()
            threading.Thread(target=server.serve_forever, daemon=True).start()
    """)
    assert findings == []


def test_jt09_nested_def_loops_do_not_leak_into_target(tmp_path):
    # the helper's loop runs in whoever CALLS it — the thread target
    # itself has no loop of its own
    findings = lint_src(tmp_path, """\
        import threading

        def _target():
            def helper(items):
                for i in items:
                    use(i)
            register(helper)

        threading.Thread(target=_target).start()
    """)
    assert findings == []


def test_jt09_supervised_loop_does_not_mask_sibling(tmp_path):
    # one supervised loop + one bare sibling loop in the same thread
    # body: the bare one is still a finding (per-loop reporting)
    findings = lint_src(tmp_path, """\
        import logging
        import threading

        log = logging.getLogger(__name__)

        def _run():
            while True:
                try:
                    serve_one()
                except Exception:
                    log.exception("iteration failed")
            while True:
                drain_one()

        threading.Thread(target=_run).start()
    """)
    assert rule_ids(findings) == ["JT09"]
    assert findings[0].line == 12  # the drain loop, not the main one


# -- JT10 outbound-call-without-timeout ----------------------------------------

def test_jt10_positive_urlopen_without_timeout(tmp_path):
    findings = lint_src(tmp_path, """\
        import urllib.request

        def fetch(url):
            with urllib.request.urlopen(url) as resp:
                return resp.read()
    """)
    assert rule_ids(findings) == ["JT10"]
    assert "timeout" in findings[0].message


def test_jt10_positive_httpconnection_and_create_connection(tmp_path):
    findings = lint_src(tmp_path, """\
        import http.client
        import socket

        def a(host, port):
            return http.client.HTTPConnection(host, port)

        def b(addr):
            return socket.create_connection(addr)
    """)
    assert rule_ids(findings) == ["JT10", "JT10"]


def test_jt10_negative_timeout_kwarg_or_positional(tmp_path):
    findings = lint_src(tmp_path, """\
        import http.client
        import socket
        import urllib.request
        from urllib.request import urlopen

        def a(req, deadline):
            with urllib.request.urlopen(req, timeout=deadline) as r:
                return r.read()

        def b(req, body):
            return urlopen(req, body, 10)  # positional timeout

        def c(host, port):
            return http.client.HTTPSConnection(host, port, 30)

        def d(addr):
            return socket.create_connection(addr, 5)
    """)
    assert findings == []


def test_jt10_star_args_not_decidable(tmp_path):
    # *args / **kwargs may carry the timeout: conservative silence
    findings = lint_src(tmp_path, """\
        import urllib.request

        def fetch(req, *args, **kwargs):
            return urllib.request.urlopen(req, *args, **kwargs)
    """)
    assert findings == []


def test_jt10_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        import urllib.request

        def fetch(url):
            return urllib.request.urlopen(url)  # graftlint: disable=JT10 — fixture: interactive CLI, user can ^C
    """)
    assert findings == []

# -- JT11 unbounded-metric-label-cardinality -----------------------------------

def test_jt11_positive_trace_id_label(tmp_path):
    findings = lint_src(tmp_path, """\
        from predictionio_tpu.obs import metrics

        REQS = metrics.counter("pio_reqs_total", "requests", ("trace",))

        def record(trace_id):
            REQS.labels(trace_id).inc()
    """)
    assert rule_ids(findings) == ["JT11"]
    assert "trace_id" in findings[0].message


def test_jt11_positive_entity_id_attribute_and_fstring(tmp_path):
    findings = lint_src(tmp_path, """\
        from predictionio_tpu.obs import metrics

        LAT = metrics.histogram("pio_lat_seconds", "latency", ("who", "q"))

        def record(event, query, seconds):
            LAT.labels(event.entity_id, f"q-{query}").observe(seconds)
    """)
    assert rule_ids(findings) == ["JT11", "JT11"]


def test_jt11_positive_str_wrapped_user_id(tmp_path):
    findings = lint_src(tmp_path, """\
        from predictionio_tpu.obs import metrics

        HITS = metrics.counter("pio_hits_total", "hits", ("user",))

        def record(user_id):
            HITS.labels(str(user_id)).inc()
    """)
    assert rule_ids(findings) == ["JT11"]


def test_jt11_negative_bounded_labels(tmp_path):
    # route templates, engine ids, status codes, device ids: bounded
    findings = lint_src(tmp_path, """\
        from predictionio_tpu.obs import metrics

        REQS = metrics.counter(
            "pio_http_requests_total", "requests",
            ("server", "method", "route", "status"))
        MEM = metrics.gauge("pio_mem_bytes", "memory", ("device", "kind"))

        def record(server, method, route, status, dev, engine_id):
            REQS.labels(server, method, route, str(status)).inc()
            MEM.labels(str(dev.id), "bytes_in_use").set(1.0)
    """)
    assert findings == []


def test_jt11_negative_non_metric_labels_method(tmp_path):
    # a .labels() on something that is not a metric family still only
    # fires on per-request-shaped values — plot axes etc. stay silent
    findings = lint_src(tmp_path, """\
        def draw(ax, names):
            ax.labels(names)
    """)
    assert findings == []


def test_jt11_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        from predictionio_tpu.obs import metrics

        REQS = metrics.counter("pio_reqs_total", "requests", ("trace",))

        def record(trace_id):
            REQS.labels(trace_id).inc()  # graftlint: disable=JT11 — fixture: bounded test registry
    """)
    assert findings == []


# -- JT12 join-wait-without-timeout --------------------------------------------

def test_jt12_positive_bare_thread_join_and_event_wait(tmp_path):
    findings = lint_src(tmp_path, """\
        import threading

        def stop(worker, done):
            worker.join()
            done.wait()
    """)
    assert rule_ids(findings) == ["JT12", "JT12"]
    assert "timeout" in findings[0].message


def test_jt12_positive_popen_wait(tmp_path):
    findings = lint_src(tmp_path, """\
        import subprocess

        def reap(proc):
            proc.wait()
    """)
    assert rule_ids(findings) == ["JT12"]


def test_jt12_negative_timeout_passed(tmp_path):
    # keyword, positional, and any-arg forms all bound the wait
    findings = lint_src(tmp_path, """\
        def stop(worker, done, barrier, proc):
            worker.join(timeout=60)
            done.wait(5.0)
            barrier.wait(timeout=10)
            proc.wait(timeout=30)
    """)
    assert findings == []


def test_jt12_positive_literal_none_timeout(tmp_path):
    # join(None) / wait(timeout=None) is the bare unbounded wait
    # spelled out — passing it must not satisfy the rule
    findings = lint_src(tmp_path, """\
        def stop(worker, done):
            worker.join(None)
            done.wait(timeout=None)
    """)
    assert rule_ids(findings) == ["JT12", "JT12"]


def test_jt12_negative_string_join_and_module_wait(tmp_path):
    # str.join(iterable) and futures.wait(fs) carry arguments; the
    # bare-name `wait(fs)` is a module-level call, not a method
    findings = lint_src(tmp_path, """\
        from concurrent.futures import wait

        def fmt(parts, futures):
            text = ",".join(parts)
            wait(futures)
            return text
    """)
    assert findings == []


def test_jt12_negative_dma_descriptor_wait(tmp_path):
    # Pallas async-copy descriptors: `make_copy(...).wait()` is a
    # device-side completion wait with no timeout concept — the
    # receiver-is-a-call shape stays silent
    findings = lint_src(tmp_path, """\
        def kernel(copy_fn, k):
            copy_fn(k).wait()
    """)
    assert findings == []


def test_jt12_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        def stop(worker):
            worker.join()  # graftlint: disable=JT12 — fixture: joined thread is provably short-lived
    """)
    assert findings == []


def test_jt12_negative_timeoutless_receivers(tmp_path):
    # queue.Queue.join / Pool.join / os.wait HAVE no timeout parameter:
    # "pass timeout=" would be a TypeError, so the rule stays silent
    findings = lint_src(tmp_path, """\
        import os

        def drain(work_queue, worker_pool):
            work_queue.join()
            worker_pool.join()
            os.wait()
    """)
    assert findings == []


def test_jt12_positive_queue_adjacent_names_still_flagged(tmp_path):
    # the exemption is for receivers that ARE queues/pools (head word),
    # not for anything queue-adjacent: a bare Event.wait() named after
    # a queue is exactly the forever-hang the rule exists to catch
    findings = lint_src(tmp_path, """\
        def stop(queue_drained_evt, pool_ready):
            queue_drained_evt.wait()
            pool_ready.wait()
    """)
    assert rule_ids(findings) == ["JT12", "JT12"]


# -- JT13 copy-inducing-device-transfer ----------------------------------------

def test_jt13_positive_list_tolist_and_stepped_slice(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp

        def put(xs, arr):
            a = jax.device_put([1, 2, 3])
            b = jnp.asarray(xs.tolist())
            c = jnp.array([x * 2 for x in xs])
            d = jax.device_put(arr[::2])
            e = jnp.asarray(arr[:, ::4])
            return a, b, c, d, e
    """, relpath="ops/mod.py")
    assert rule_ids(findings) == ["JT13"] * 5
    assert "serialize/copy" in findings[0].message


def test_jt13_negative_contiguous_and_ndarray(tmp_path):
    # ndarray vars, contiguous row slices and step-1 slices stay silent
    findings = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        def put(arr):
            ok1 = jnp.asarray(arr)
            ok2 = jax.device_put(arr[1:5])
            ok3 = jnp.array(arr[::1])
            ok4 = jax.device_put(np.ascontiguousarray(arr.T))
            return ok1, ok2, ok3, ok4
    """, relpath="ops/mod.py")
    assert findings == []


def test_jt13_scoped_to_data_path_modules(tmp_path):
    # the hazard is bulk data movement; CLI/test glue is out of scope
    src = """\
        import jax

        def put():
            return jax.device_put([1, 2, 3])
    """
    assert rule_ids(lint_src(tmp_path, src, relpath="ops/m.py")) == ["JT13"]
    assert lint_src(tmp_path, src, relpath="tools/m.py") == []


def test_jt13_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax

        def put():
            return jax.device_put([0.0])  # graftlint: disable=JT13 — fixture: one-element warmup constant
    """, relpath="ops/m.py")
    assert findings == []


# -- JT14 full-sort-for-topk ---------------------------------------------------

def test_jt14_positive_truncated_sorts(tmp_path):
    findings = lint_src(tmp_path, """\
        import numpy as np
        import jax.numpy as jnp

        def rank(scores, mat, k):
            a = np.argsort(-scores)[:k]
            b = np.argsort(scores)[-k:]
            c = jnp.sort(mat)[:, :k]
            d = np.sort(scores)[1:]
            return a, b, c, d
    """, relpath="serving/mod.py")
    assert rule_ids(findings) == ["JT14"] * 4
    assert "argpartition" in findings[0].message


def test_jt14_negative_full_order_and_partition(tmp_path):
    # a FULL order (no truncation), pure step slices, argpartition and
    # sorting only k survivors stay silent
    findings = lint_src(tmp_path, """\
        import numpy as np

        def rank(scores, part, k):
            full = np.argsort(scores)
            rev = np.argsort(scores)[::-1]
            sel = np.argpartition(-scores, k - 1)[:k]
            order = np.argsort(-scores[sel])
            return full, rev, sel, order
    """, relpath="ops/mod.py")
    assert findings == []


def test_jt14_scoped_to_ranking_paths(tmp_path):
    src = """\
        import numpy as np

        def rank(scores, k):
            return np.argsort(-scores)[:k]
    """
    assert rule_ids(lint_src(tmp_path, src, relpath="index/m.py")) == ["JT14"]
    assert lint_src(tmp_path, src, relpath="tools/m.py") == []


def test_jt14_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        import numpy as np

        def rank(scores, k):
            return np.argsort(-scores)[:k]  # graftlint: disable=JT14 — fixture: scores is a dozen rows
    """, relpath="models/m.py")
    assert findings == []


# -- JT15 nonmonotonic-duration-clock ------------------------------------------

def test_jt15_positive_direct_wall_delta(tmp_path):
    findings = lint_src(tmp_path, """\
        import time

        def timed(work):
            t0 = time.time()
            work()
            return time.time() - t0
    """)
    assert rule_ids(findings) == ["JT15"]
    assert "monotonic" in findings[0].message


def test_jt15_positive_cadence_through_attribute(tmp_path):
    # the cadence-freeze pattern: now = time.time(); now - self._last
    findings = lint_src(tmp_path, """\
        import time

        class Sampler:
            def tick(self, now=None):
                now = time.time() if now is None else now
                if now - self._last < 5.0:
                    return False
                self._last = now
                return True
    """)
    assert rule_ids(findings) == ["JT15"]


def test_jt15_negative_monotonic_and_timestamp_arithmetic(tmp_path):
    # monotonic deltas, one-sided timestamp arithmetic (now - window),
    # wall timestamps stored in records, and deltas of values merely
    # READ OUT of a container that holds a timestamp all stay silent
    findings = lint_src(tmp_path, """\
        import time

        def fine(window, record):
            t0 = time.monotonic()
            dur = time.monotonic() - t0
            start = time.time() - window
            record = {"start_unix": round(time.time(), 3), "t0": 1.0}
            total = record["t0"] - sum(record.values())
            name = int(time.time() * 1e3)
            return dur, start, total, name
    """)
    assert findings == []


def test_jt15_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        import time

        def staleness(first):
            now = time.time()
            return now - first  # graftlint: disable=JT15 — fixture: cross-process wall horizon by design
    """)
    assert findings == []


# -- JT16 unledgered-device-residency ------------------------------------------

def test_jt16_positive_direct_self_assignment(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp

        class Model:
            def load(self, table):
                self._table = jax.device_put(table)
    """, relpath="models/m.py")
    assert rule_ids(findings) == ["JT16"]
    assert "MemLedger" in findings[0].message


def test_jt16_positive_one_hop_local(tmp_path):
    # the two-statement spelling of the same residency: a local holds
    # the transfer result, then lands on self
    findings = lint_src(tmp_path, """\
        import jax.numpy as jnp

        class Index:
            def warm(self, vectors):
                padded = pad(jnp.asarray(vectors), 128)
                self._device_padded = padded
    """, relpath="index/i.py")
    assert rule_ids(findings) == ["JT16"]


def test_jt16_positive_tuple_targets_and_annassign_taint(tmp_path):
    # `self._u, self._i = device_put(...), device_put(...)` is two
    # residency stores, and an ANNOTATED local carries the taint too
    findings = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp

        class Model:
            def load(self, u, i):
                self._u, self._i = jax.device_put(u), jax.device_put(i)

            def warm(self, x):
                padded: object = jnp.asarray(x)
                self._cache = padded
    """, relpath="models/m.py")
    assert rule_ids(findings) == ["JT16", "JT16"]


def test_jt16_negative_register_in_same_scope(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax
        from predictionio_tpu.obs import memacct

        class Model:
            def load(self, table):
                self._table = jax.device_put(table)
                memacct.LEDGER.register(self, "m", "table",
                                        int(self._table.nbytes))

            def load_helper(self, table):
                self._table = jax.device_put(table)
                self._register_mem(self._table.nbytes)
    """, relpath="models/m.py")
    assert findings == []


def test_jt16_negative_out_of_scope_paths_and_locals(tmp_path):
    # ops-layer trainers price at their own coarser seam (out of the
    # rule's path scope), and a LOCAL device array is a compute
    # temporary, not residency
    src = """\
        import jax
        import jax.numpy as jnp

        class Trainer:
            def step(self, x):
                dev = jnp.asarray(x)
                return dev * 2
    """
    assert lint_src(tmp_path, src, relpath="ops/t.py") == []
    src2 = """\
        import jax

        class Trainer:
            def place(self, x):
                self._x = jax.device_put(x)
    """
    assert lint_src(tmp_path, src2, relpath="ops/t.py") == []


def test_jt16_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        import jax

        class Model:
            def load(self, table):
                self._table = jax.device_put(table)  # graftlint: disable=JT16 — fixture: test-only toy table, bytes negligible
    """, relpath="models/m.py")
    assert findings == []


# -- JT17 untraced-intra-fleet-call --------------------------------------------

def test_jt17_positive_request_without_trace_headers(tmp_path):
    findings = lint_src(tmp_path, """\
        import urllib.request

        def notify_peer(url, body):
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status
    """, relpath="serving/push_lane.py")
    assert rule_ids(findings) == ["JT17"]


def test_jt17_positive_direct_url_urlopen_and_connection_ctor(tmp_path):
    findings = lint_src(tmp_path, """\
        import http.client
        import urllib.request

        def probe(host, port):
            conn = http.client.HTTPConnection(host, port, timeout=2)
            return conn

        def reload_member(port):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/reload", timeout=5) as r:
                return r.status
    """, relpath="workflow/lanes.py")
    assert rule_ids(findings) == ["JT17", "JT17"]


def test_jt17_negative_traced_headers_helper(tmp_path):
    findings = lint_src(tmp_path, """\
        import urllib.request

        from predictionio_tpu.obs import trace

        def notify_peer(url, body):
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers=trace.traced_headers(
                    {"Content-Type": "application/json"}))
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status
    """, relpath="serving/push_lane.py")
    assert findings == []


def test_jt17_negative_manual_header_attach(tmp_path):
    findings = lint_src(tmp_path, """\
        import urllib.request

        from predictionio_tpu.obs import trace

        def notify_peer(url, body, trace_id):
            req = urllib.request.Request(url, data=body, method="POST")
            req.add_header(trace.TRACE_HEADER, trace_id)
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status
    """, relpath="serving/push_lane.py")
    assert findings == []


def test_jt17_negative_caller_owned_headers_param(tmp_path):
    # a pooled client whose caller hands the headers in: propagation
    # is the caller's duty (the router's _ReplicaClient shape)
    findings = lint_src(tmp_path, """\
        import http.client

        class PooledClient:
            def request(self, method, path, body, headers, timeout):
                conn = http.client.HTTPConnection("127.0.0.1", 1,
                                                  timeout=timeout)
                conn.request(method, path, body=body, headers=headers)
                return conn.getresponse()
    """, relpath="serving/pool.py")
    assert findings == []


def test_jt17_negative_out_of_scope_path_and_prebuilt_request(tmp_path):
    src = """\
        import urllib.request

        def fetch(url):
            req = urllib.request.Request(url)
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.read()
    """
    # interactive CLI tooling is out of the rule's layer scope
    assert lint_src(tmp_path, src, relpath="tools/cli_like.py") == []
    # in scope the Request ctor is the one finding; urlopen(req) on the
    # prebuilt object is not double-flagged
    findings = lint_src(tmp_path, src, relpath="serving/lane.py")
    assert rule_ids(findings) == ["JT17"]


def test_jt17_suppressible_with_justification(tmp_path):
    findings = lint_src(tmp_path, """\
        import urllib.request

        def push_external(url, body):
            req = urllib.request.Request(url, data=body, method="POST")  # graftlint: disable=JT17 — fixture: external sink, not a fleet member
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status
    """, relpath="obs/sink.py")
    assert findings == []


def test_jt17_positive_url_string_in_a_variable(tmp_path):
    # parking the URL in a variable must not defeat the audit: there is
    # no Request construction site anywhere to carry the headers
    findings = lint_src(tmp_path, """\
        import urllib.request

        def reload_member(replica):
            url = f"{replica.base_url}/reload"
            with urllib.request.urlopen(url, timeout=5) as r:
                return r.status
    """, relpath="serving/lane.py")
    assert rule_ids(findings) == ["JT17"]


def test_jt17_negative_closure_over_prebuilt_request(tmp_path):
    # the retrying-inner-attempt shape: the Request is built (with the
    # headers) in the outer scope, the nested attempt urlopens it
    findings = lint_src(tmp_path, """\
        import urllib.request

        from predictionio_tpu.obs import trace

        def push(url, body):
            req = urllib.request.Request(
                url, data=body, headers=trace.traced_headers())

            def attempt():
                with urllib.request.urlopen(req, timeout=5) as r:
                    return r.status

            return attempt()
    """, relpath="serving/lane.py")
    assert findings == []


# -- multi-line statement suppression ------------------------------------------

def test_suppression_on_closing_line_of_wrapped_statement(tmp_path):
    # the directive sits on the CLOSING line of a wrapped call; the
    # finding fires at the statement's first line — matching must honor
    # the whole statement span, not just line one
    findings = lint_src(tmp_path, """\
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(
                x,
                np.float32,
            )  # graftlint: disable=JT01 — fixture: reviewed host sync
    """)
    assert findings == []


def test_multiline_suppression_does_not_leak_to_next_statement(tmp_path):
    # the span ends with the statement: a second, separate host sync on
    # the following line must still fire
    findings = lint_src(tmp_path, """\
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            a = np.asarray(
                x,
            )  # graftlint: disable=JT01 — fixture: reviewed host sync
            b = float(x)
            return a, b
    """)
    assert rule_ids(findings) == ["JT01"]


def test_multiline_suppression_on_wrapped_with_header(tmp_path):
    # compound statements expand over the HEADER only (a directive on
    # the closing paren of a wrapped `with` belongs to the with itself,
    # not to every statement in its body)
    findings = lint_src(tmp_path, """\
        import jax
        import numpy as np

        @jax.jit
        def f(x, cm):
            with cm(
                x,
            ):  # graftlint: disable=JT01 — fixture: reviewed ctx sync
                return float(x)
    """)
    assert rule_ids(findings) == ["JT01"]  # body finding NOT suppressed


# -- JT18 unguarded-shared-mutation --------------------------------------------

def test_jt18_positive_unguarded_write_on_thread_path(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def reset(self):
                with self._lock:
                    self._items = []

            def _drain(self):
                self._items = []
    """)
    assert rule_ids(findings) == ["JT18"]
    assert "Box._items" in findings[0].message
    assert "Box._lock" in findings[0].message


def test_jt18_positive_unguarded_iteration(tmp_path):
    # iteration is the probe-vs-drain read shape: a concurrent mutate
    # corrupts the loop mid-flight
    findings = lint_project_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def start(self):
                threading.Thread(target=self._scan, daemon=True).start()

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def reset(self):
                with self._lock:
                    self._items = []

            def _scan(self):
                return [x for x in self._items]
    """)
    assert rule_ids(findings) == ["JT18"]
    assert "iterated" in findings[0].message


def test_jt18_suppressible_with_justification(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def reset(self):
                with self._lock:
                    self._items = []

            def _drain(self):
                self._items = []  # graftlint: disable=JT18 — fixture: copy-on-write swap, readers hold one ref
    """)
    assert findings == []


def test_jt18_negative_guarded_access_is_clean(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def reset(self):
                with self._lock:
                    self._items = []

            def _drain(self):
                with self._lock:
                    self._items = []
    """)
    assert findings == []


def test_jt18_negative_thread_unreachable_is_clean(tmp_path):
    # same unguarded write, but nothing ever runs _drain on a thread —
    # single-threaded use of a locked class is not a race
    findings = lint_project_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def reset(self):
                with self._lock:
                    self._items = []

            def _drain(self):
                self._items = []
    """)
    assert findings == []


def test_jt18_negative_called_with_lock_held(tmp_path):
    # the _locked-helper idiom: every call site of _flush holds the
    # lock, so the helper's unguarded touch executes under it
    findings = lint_project_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def start(self):
                threading.Thread(target=self.run, daemon=True).start()

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def run(self):
                with self._lock:
                    self._flush()

            def _flush(self):
                self._items = []
    """)
    assert findings == []


# -- JT19 lock-order-cycle -----------------------------------------------------

def test_jt19_positive_opposite_acquisition_orders(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """)
    assert rule_ids(findings) == ["JT19"]
    assert "cycle" in findings[0].message


def test_jt19_positive_nonreentrant_self_deadlock_via_call(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Reent:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    assert rule_ids(findings) == ["JT19"]
    assert "re-acquired" in findings[0].message


def test_jt19_suppressible_with_justification(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:  # graftlint: disable=JT19 — fixture: one() and two() proven mutually exclusive by caller
                        pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """)
    assert findings == []


def test_jt19_negative_consistent_order_is_clean(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    """)
    assert findings == []


def test_jt19_negative_rlock_reacquire_is_clean(tmp_path):
    # RLock is reentrant by design: the self-edge is legal
    findings = lint_project_src(tmp_path, """\
        import threading

        class Reent:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """)
    assert findings == []


# -- JT20 check-then-act-split -------------------------------------------------

def test_jt20_positive_split_test_and_write(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Once:
            def __init__(self):
                self._lock = threading.Lock()
                self._key = None

            def start(self):
                threading.Thread(target=self.work, daemon=True).start()

            def work(self):
                with self._lock:
                    if self._key is not None:
                        return
                k = object()
                with self._lock:
                    self._key = k
    """)
    assert rule_ids(findings) == ["JT20"]
    assert "Once._key" in findings[0].message


def test_jt20_suppressible_with_justification(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Once:
            def __init__(self):
                self._lock = threading.Lock()
                self._key = None

            def start(self):
                threading.Thread(target=self.work, daemon=True).start()

            def work(self):
                with self._lock:
                    if self._key is not None:
                        return
                k = object()
                with self._lock:  # graftlint: disable=JT20 — fixture: double-arm is idempotent here by design
                    self._key = k
    """)
    assert findings == []


def test_jt20_negative_revalidated_second_region(tmp_path):
    # the sanctioned fix: the second region re-checks the premise
    # before acting, so the split transaction is safe
    findings = lint_project_src(tmp_path, """\
        import threading

        class Once:
            def __init__(self):
                self._lock = threading.Lock()
                self._key = None

            def start(self):
                threading.Thread(target=self.work, daemon=True).start()

            def work(self):
                with self._lock:
                    if self._key is not None:
                        return
                k = object()
                with self._lock:
                    if self._key is None:
                        self._key = k
    """)
    assert findings == []


def test_jt20_negative_atomic_setdefault_second_region(tmp_path):
    # dict.setdefault is an atomic check-and-write: it IS the
    # re-validation (the load_library fix shape)
    findings = lint_project_src(tmp_path, """\
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._libs = {}

            def start(self):
                threading.Thread(target=self.load, daemon=True).start()

            def load(self):
                with self._lock:
                    if "k" in self._libs:
                        return self._libs["k"]
                lib = object()
                with self._lock:
                    return self._libs.setdefault("k", lib)
    """)
    assert findings == []


def test_jt20_negative_single_region_is_clean(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading

        class Once:
            def __init__(self):
                self._lock = threading.Lock()
                self._key = None

            def start(self):
                threading.Thread(target=self.work, daemon=True).start()

            def work(self):
                with self._lock:
                    if self._key is None:
                        self._key = object()
    """)
    assert findings == []


# -- JT21 blocking-call-under-lock ---------------------------------------------

def test_jt21_positive_sleep_under_lock(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    time.sleep(0.1)
    """)
    assert rule_ids(findings) == ["JT21"]
    assert "time.sleep" in findings[0].message
    assert "Box._lock" in findings[0].message


def test_jt21_positive_helper_only_called_with_lock_held(tmp_path):
    # the call sits in a helper with no `with` of its own — only the
    # project-wide inferred-held fixpoint can see the lock
    findings = lint_project_src(tmp_path, """\
        import threading
        import urllib.request

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    self._fetch()

            def _fetch(self):
                urllib.request.urlopen("http://example.invalid", timeout=5)
    """)
    assert rule_ids(findings) == ["JT21"]
    assert "every resolvable caller holds it" in findings[0].message


def test_jt21_suppressible_with_justification(tmp_path):
    findings = lint_project_src(tmp_path, """\
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    time.sleep(0.1)  # graftlint: disable=JT21 — fixture: the sleep IS the guarded capture window
    """)
    assert findings == []


def test_jt21_negative_blocking_call_outside_lock(tmp_path):
    # the sanctioned fix: copy under the lock, do the I/O outside it
    findings = lint_project_src(tmp_path, """\
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._delay = 0.1

            def poke(self):
                with self._lock:
                    delay = self._delay
                time.sleep(delay)
    """)
    assert findings == []


def test_jt21_negative_condition_wait_is_not_flagged(tmp_path):
    # Condition.wait under its own lock is the CORRECT idiom (it
    # releases the lock while parked) — deliberately outside the
    # blocking vocabulary
    findings = lint_project_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._cond = threading.Condition()

            def park(self):
                with self._cond:
                    self._cond.wait(timeout=1.0)
    """)
    assert findings == []


# -- project mode: engine plumbing ---------------------------------------------

def test_project_mode_includes_per_file_findings(tmp_path):
    # whole-program mode is a superset: per-file rules still run
    findings = lint_project_src(tmp_path, """\
        import jax

        @jax.jit
        def f(x):
            return float(x)
    """)
    assert rule_ids(findings) == ["JT01"]


def test_project_cli_json_shape(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent("""\
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.tools.lint",
         "--project", "--json", str(tmp_path)],
        capture_output=True, text=True, cwd=str(REPO_ROOT))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["files_scanned"] == 1
    (finding,) = doc["findings"]
    # stable machine-readable keys for CI wrappers
    assert finding["rule"] == "JT19"
    assert finding["path"].endswith("mod.py")
    assert isinstance(finding["line"], int)


# -- JT22: unjournaled state transitions ---------------------------------------


class TestJT22UnjournaledStateTransition:
    def test_flags_state_write_without_journal(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Breaker:
                def trip(self):
                    self._state = "open"
        """, relpath="resilience/policy.py")
        assert "JT22" in rule_ids(findings)

    def test_flags_bare_state_tail_on_other_object(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Supervisor:
                def evict(self, replica):
                    replica.state = "evicted"
        """, relpath="serving/fleet.py")
        assert "JT22" in rule_ids(findings)

    def test_journal_emit_in_scope_vouches(self, tmp_path):
        findings = lint_src(tmp_path, """
            from predictionio_tpu.obs import journal

            class Breaker:
                def trip(self):
                    self._state = "open"
                    journal.emit("breaker", state="open")
        """, relpath="resilience/policy.py")
        assert "JT22" not in rule_ids(findings)

    def test_journal_object_method_vouches(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Episodes:
                def open(self):
                    self._episode_state = "active"
                    self._journal.emit("shed_episode", phase="start")
        """, relpath="resilience/admission.py")
        assert "JT22" not in rule_ids(findings)

    def test_init_writes_exempt(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Replica:
                def __init__(self):
                    self.state = "stopped"
        """, relpath="serving/fleet.py")
        assert "JT22" not in rule_ids(findings)

    def test_out_of_scope_paths_exempt(self, tmp_path):
        # a `state` attribute outside resilience//fleet//stream is
        # ordinary data, not an ops transition
        findings = lint_src(tmp_path, """
            class Parser:
                def advance(self):
                    self.state = "in_block"
        """, relpath="tools/parser.py")
        assert "JT22" not in rule_ids(findings)

    def test_suppression_with_justification(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Breaker:
                def reset(self):
                    self._state = "closed"  # graftlint: disable=JT22 — test-only reset, not an operational transition
        """, relpath="resilience/policy.py")
        assert "JT22" not in rule_ids(findings)
        assert "GL00" not in rule_ids(findings)

    def test_nested_def_does_not_vouch_outer_scope(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Supervisor:
                def swap(self, replica):
                    def note():
                        journal.emit("swap", phase="start")
                    replica.state = "draining"
        """, relpath="serving/fleet.py")
        assert "JT22" in rule_ids(findings)

    def test_tree_is_clean(self):
        # every transition seam the ops journal covers must STAY
        # journaled: the packaged resilience/fleet/stream modules carry
        # no unsuppressed JT22 findings
        import subprocess
        import sys as _sys

        proc = subprocess.run(
            [_sys.executable, "-m", "predictionio_tpu.tools.lint",
             "--json",
             str(REPO_ROOT / "predictionio_tpu" / "resilience"),
             str(REPO_ROOT / "predictionio_tpu" / "serving"),
             str(REPO_ROOT / "predictionio_tpu" / "workflow")],
            capture_output=True, text=True, cwd=str(REPO_ROOT))
        doc = json.loads(proc.stdout)
        assert [f for f in doc["findings"]
                if f["rule"] == "JT22"] == []


# -- JT23: unbounded per-key dict growth ---------------------------------------


class TestJT23UnboundedPerKeyDictGrowth:
    def test_flags_tainted_key_write_without_bound(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, user_id):
                    self._counts[user_id] = self._counts.get(user_id, 0) + 1
        """, relpath="serving/tracker.py")
        assert "JT23" in rule_ids(findings)

    def test_flags_tuple_key_with_tainted_component(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, app_id, entity_id):
                    self._seen[(app_id, entity_id)] += 1
        """, relpath="obs/tracker.py")
        assert "JT23" in rule_ids(findings)

    def test_flags_setdefault_on_tainted_key(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, session_id):
                    self._tbl.setdefault(session_id, []).append(1)
        """, relpath="serving/tracker.py")
        assert "JT23" in rule_ids(findings)

    def test_len_cap_check_vouches(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, user_id):
                    if len(self._counts) >= 1024:
                        return
                    self._counts[user_id] = 1
        """, relpath="serving/tracker.py")
        assert "JT23" not in rule_ids(findings)

    def test_pop_eviction_vouches(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, user_id):
                    self._counts[user_id] = 1
                    if self._full():
                        self._counts.pop(next(iter(self._counts)))
        """, relpath="obs/tracker.py")
        assert "JT23" not in rule_ids(findings)

    def test_other_overflow_row_vouches(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, user_id):
                    key = user_id if self._admit(user_id) else "(other)"
                    self._counts[key] = 1
        """, relpath="serving/tracker.py")
        assert "JT23" not in rule_ids(findings)

    def test_untainted_key_exempt(self, tmp_path):
        # a small closed key domain (event kind, status code) is not a
        # traffic-sized table
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, kind):
                    self._by_kind[kind] = 1
        """, relpath="serving/tracker.py")
        assert "JT23" not in rule_ids(findings)

    def test_out_of_scope_paths_exempt(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Loader:
                def index(self, user_id):
                    self._rows[user_id] = 1
        """, relpath="data/loader.py")
        assert "JT23" not in rule_ids(findings)

    def test_suppression_with_justification(self, tmp_path):
        findings = lint_src(tmp_path, """
            class Tracker:
                def observe(self, user_id):
                    self._counts[user_id] = 1  # graftlint: disable=JT23 — test fixture, bounded by caller
        """, relpath="serving/tracker.py")
        assert "JT23" not in rule_ids(findings)
        assert "GL00" not in rule_ids(findings)

    def test_tree_is_clean(self):
        # serving/ and obs/ must keep per-key state in bounded sketches
        # (obs/dataobs.py) or capped tables — no unsuppressed JT23
        import subprocess
        import sys as _sys

        proc = subprocess.run(
            [_sys.executable, "-m", "predictionio_tpu.tools.lint",
             "--json",
             str(REPO_ROOT / "predictionio_tpu" / "serving"),
             str(REPO_ROOT / "predictionio_tpu" / "obs")],
            capture_output=True, text=True, cwd=str(REPO_ROOT))
        doc = json.loads(proc.stdout)
        assert [f for f in doc["findings"]
                if f["rule"] == "JT23"] == []
