"""Latency-aware serving placement (ops.topk): the host path must be
semantically identical to the device path, and the auto route must pick
the device only when batch*catalog FLOPs amortize the measured dispatch
floor. Reference role: MLlib's recommendProducts is a driver-side scan
(SURVEY.md §7.5) — the host path IS that contract; the device path and
the sharded scorer are the TPU upgrades on it."""

import numpy as np
import pytest

from predictionio_tpu.ops import topk as T


@pytest.fixture()
def factors():
    rng = np.random.default_rng(3)
    return rng.normal(size=(257, 16)).astype(np.float32)


def test_host_matches_device(factors):
    rng = np.random.default_rng(4)
    uv = rng.normal(size=(5, 16)).astype(np.float32)
    excl = np.array([[0, 1, -1], [5, -1, -1], [-1, -1, -1],
                     [250, 251, 252], [7, 8, 9]], np.int32)
    host = T.TopKScorer(factors, placement="host")
    dev = T.TopKScorer(factors, placement="device")
    hs, hi = host.score(uv, 7, excl)
    ds, di = dev.score(uv, 7, excl)
    np.testing.assert_array_equal(hi, di)
    np.testing.assert_allclose(hs, ds, rtol=1e-4, atol=1e-4)
    # excluded ids never appear
    for b in range(5):
        assert not set(excl[b][excl[b] >= 0]) & set(hi[b])


def test_host_matches_device_masked(factors):
    rng = np.random.default_rng(5)
    uv = rng.normal(size=(3, 16)).astype(np.float32)
    mask = rng.random(257) > 0.5
    host = T.TopKScorer(factors, placement="host")
    dev = T.TopKScorer(factors, placement="device")
    hs, hi = host.score_masked(uv, 9, mask)
    ds, di = dev.score_masked(uv, 9, mask)
    np.testing.assert_array_equal(hi, di)
    np.testing.assert_allclose(hs, ds, rtol=1e-4, atol=1e-4)
    assert mask[hi].all()


def test_host_k_exceeds_catalog(factors):
    host = T.TopKScorer(factors[:5], placement="host")
    s, i = host.score(np.ones((1, 16), np.float32), 10)
    assert s.shape == (1, 5) and sorted(i[0]) == list(range(5))


def test_host_respects_max_exclude_cap(factors):
    """Entries beyond max_exclude are dropped oldest-first on BOTH paths."""
    host = T.TopKScorer(factors, placement="host", max_exclude=2)
    dev = T.TopKScorer(factors, placement="device", max_exclude=2)
    uv = np.ones((1, 16), np.float32)
    excl = np.array([[3, 4, 5, 6]], np.int32)  # 3, 4 dropped (oldest)
    # k=255 keeps the comparison away from the tied NEG_INF tail (the
    # two excluded entries), where ordering is legitimately unspecified
    _, hi = host.score(uv, 255, excl)
    _, di = dev.score(uv, 255, excl)
    np.testing.assert_array_equal(hi, di)
    assert not {5, 6} & set(hi[0])
    assert {3, 4} <= set(hi[0])  # the dropped-oldest ids still rank


def test_auto_routing_crossover(factors, monkeypatch):
    scorer = T.TopKScorer(factors, placement="auto")
    # a slow (tunneled) backend: lone queries must go host-side
    monkeypatch.setattr(T, "_dispatch_latency", 0.1)
    assert scorer._route(1) == "host"
    # ...but a big batch amortizes the dispatch floor
    assert scorer._route(200_000) == "device"
    # a locally-attached chip: even lone queries stay on device only if
    # the host matvec is slower — tiny catalog => host still wins
    monkeypatch.setattr(T, "_dispatch_latency", 1e-4)
    assert scorer._route(1) == "host"
    big = T.TopKScorer(np.zeros((3_000_000, 64), np.float32), placement="auto")
    assert big._route(64) == "device"


def test_env_override(factors, monkeypatch):
    monkeypatch.setenv("PIO_SERVE_PLACEMENT", "device")
    assert T.TopKScorer(factors).placement == "device"
    monkeypatch.setenv("PIO_SERVE_PLACEMENT", "bogus")
    with pytest.raises(ValueError):
        T.TopKScorer(factors)


def test_host_route_never_touches_device(factors, monkeypatch):
    """A host-placed deployment must not allocate the catalog in HBM."""
    scorer = T.TopKScorer(factors, placement="host")
    scorer.score(np.ones((2, 16), np.float32), 5)
    assert scorer._device_factors is None
