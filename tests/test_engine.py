"""Engine pipeline wiring (ref: core/src/test/.../EngineTest.scala:23-263)."""

import pytest

from predictionio_tpu.core import Engine, EngineParams, FirstServing, AverageServing
from predictionio_tpu.core.params import EmptyParams, params_from_dict
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.workflow.config import WorkflowParams

from tests.sample_engine import (
    Algo0,
    AlgoNoParams,
    DataSource0,
    IdParams,
    Preparator0,
    Prediction,
    Query,
    Serving0,
)


def make_engine():
    return Engine(
        data_source_classes={"ds": DataSource0},
        preparator_classes={"prep": Preparator0},
        algorithm_classes={"algo": Algo0, "noparams": AlgoNoParams},
        serving_classes={"serve": Serving0, "first": FirstServing},
    )


def make_params(algo_ids=(3,)):
    return EngineParams(
        data_source_params=("ds", IdParams(id=1)),
        preparator_params=("prep", IdParams(id=2)),
        algorithm_params_list=[("algo", IdParams(id=i)) for i in algo_ids],
        serving_params=("serve", IdParams(id=9)),
    )


ctx = MeshContext()


def test_train_wiring_single_algo():
    result = make_engine().train(ctx, make_params())
    (model,) = result.models
    assert model.algo_id == 3
    assert model.pd.prep_id == 2
    assert model.pd.td.ds_id == 1


def test_train_multi_algorithm():
    result = make_engine().train(ctx, make_params(algo_ids=(3, 4, 5)))
    assert [m.algo_id for m in result.models] == [3, 4, 5]
    # all share the same prepared-data lineage
    assert all(m.pd.td.ds_id == 1 for m in result.models)


def test_sanity_check_failure_propagates():
    ep = make_params()
    ep.data_source_params = ("ds", IdParams(id=1, fail_sanity=True))
    with pytest.raises(ValueError, match="TD sanity failure"):
        make_engine().train(ctx, ep)
    # skip_sanity_check suppresses it (ref: WorkflowParams.skipSanityCheck)
    result = make_engine().train(ctx, ep, WorkflowParams(skip_sanity_check=True))
    assert result.models is not None


def test_stop_after_read_and_prepare():
    e = make_engine()
    r1 = e.train(ctx, make_params(), WorkflowParams(stop_after_read=True))
    assert r1.stopped_after == "read"
    assert r1.models is None and r1.training_data.ds_id == 1
    r2 = e.train(ctx, make_params(), WorkflowParams(stop_after_prepare=True))
    assert r2.stopped_after == "prepare"
    assert r2.prepared_data.prep_id == 2


def test_eval_wiring():
    results = make_engine().eval(ctx, make_params(algo_ids=(3, 4)))
    assert len(results) == 2  # 2 folds
    for fold, (ei, qpa) in enumerate(results):
        assert ei.ds_id == 1 and ei.fold == fold
        assert len(qpa) == 2
        for q, p, a in qpa:
            assert a.q == q.q
            # serving sums algo ids -> proves both algorithms' predictions arrived
            assert p.algo_id == 3 + 4
            assert p.q == q.q


def test_unknown_component_name():
    with pytest.raises(KeyError, match="DataSource"):
        ep = make_params()
        ep.data_source_params = ("nope", IdParams())
        make_engine().train(ctx, ep)


def test_empty_algorithm_list_rejected():
    ep = make_params()
    ep.algorithm_params_list = []
    with pytest.raises(ValueError):
        make_engine().train(ctx, ep)


def test_doer_create_no_params_ctor():
    ep = make_params()
    ep.algorithm_params_list = [("noparams", EmptyParams())]
    result = make_engine().train(ctx, ep)
    assert result.models[0].algo_id == -1


def test_builtin_servings():
    assert FirstServing.create().serve(None, [Prediction(1, 0), Prediction(2, 0)]).algo_id == 1
    assert AverageServing.create().serve(None, [1.0, 2.0, 3.0]) == 2.0


def test_variant_to_engine_params():
    variant = {
        "id": "default",
        "engineFactory": "ignored.Here",
        "datasource": {"name": "ds", "params": {"id": 7}},
        "preparator": {"name": "prep", "params": {"id": 8}},
        "algorithms": [
            {"name": "algo", "params": {"id": 1}},
            {"name": "algo", "params": {"id": 2}},
        ],
        "serving": {"name": "serve", "params": {"id": 9}},
    }
    ep = make_engine().engine_params_from_variant(variant)
    assert ep.data_source_params == ("ds", IdParams(id=7))
    assert [p.id for _, p in ep.algorithm_params_list] == [1, 2]
    result = make_engine().train(ctx, ep)
    assert [m.algo_id for m in result.models] == [1, 2]


def test_variant_unknown_param_fails_fast():
    with pytest.raises(ValueError, match="unknown params"):
        make_engine().engine_params_from_variant(
            {
                "engineFactory": "x.Y",
                "datasource": {"name": "ds", "params": {"bogus": 1}},
                "algorithms": [{"name": "algo", "params": {}}],
            }
        )


def test_params_from_dict():
    p = params_from_dict(IdParams, {"id": 5})
    assert p == IdParams(id=5)
    assert params_from_dict(None, {}) == EmptyParams()
    with pytest.raises(ValueError):
        params_from_dict(None, {"x": 1})
