"""Device-memory accounting plane (obs/memacct.py): the per-model HBM
ledger, train high-water tracking, the OOM preflight, and their
surfaces (/admin/memory, pio mem, dashboard /memory, timeline,
benchcmp keys).

Acceptance pinned here:
  - on CPU with PIO_PEAK_HBM_BYTES set, GET /admin/memory attribution
    sums to within 1% of the ledger's registered nbytes for every
    loaded model;
  - a fleet serving a baseline REFUSES an oversized candidate at
    /reload (507 + reason surfaced through `pio fleet`) and via the
    canary lane, keeps answering with zero non-429 client errors, and
    accepts the same candidate under {"force": true}.
"""

from __future__ import annotations

import gc
import json
import pickle
import time

import numpy as np
import pytest

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.metadata import Model
from predictionio_tpu.models.als import ALSModel
from predictionio_tpu.obs import memacct, metrics
from predictionio_tpu.ops.als import ALSFactors
from predictionio_tpu.serving.engine_server import EngineServer

from tests.test_canary import canary_fleet, _await, _load
from tests.test_fleet import post
from tests.test_health import get_json, train_const


@pytest.fixture(autouse=True)
def _clean_ledger():
    memacct.clear()
    yield
    memacct.clear()


def _als_model(n_users=16, n_items=24, rank=8) -> ALSModel:
    factors = ALSFactors(
        user_factors=np.random.default_rng(0).normal(
            size=(n_users, rank)).astype(np.float32),
        item_factors=np.random.default_rng(1).normal(
            size=(n_items, rank)).astype(np.float32),
    )
    return ALSModel(factors,
                    BiMap.from_vocab([f"u{i}" for i in range(n_users)]),
                    BiMap.from_vocab([f"i{i}" for i in range(n_items)]))


# -- ledger basics -------------------------------------------------------------

def test_register_release_and_gauge_retire():
    class Owner:
        pass

    o = Owner()
    memacct.LEDGER.register(o, "m1", "factors", 1000)
    memacct.LEDGER.register(o, "m1", "index", 500)
    assert memacct.LEDGER.model_bytes() == {
        "m1": {"factors": 1000, "index": 500}}
    gauge = metrics.REGISTRY.get("pio_model_device_bytes")
    assert gauge.labels("m1", "factors").value == 1000.0
    # re-register replaces (re-pricing under the same owner key)
    memacct.LEDGER.register(o, "m1", "factors", 1200)
    assert memacct.LEDGER.model_bytes()["m1"]["factors"] == 1200
    assert memacct.LEDGER.release(o) == 2
    assert memacct.LEDGER.model_bytes() == {}
    # the gauge children are REMOVED, not frozen at their last value
    assert ("m1", "factors") not in {
        values for values, _ in gauge.children()}


def test_dead_owner_is_swept_without_release():
    class Owner:
        pass

    o = Owner()
    memacct.LEDGER.register(o, "m2", "factors", 777)
    del o
    gc.collect()
    assert "m2" not in memacct.LEDGER.model_bytes()


def test_als_model_registration_matches_nbytes():
    """The factors footprint IS the tables' nbytes — the ledger is an
    accounting of real arrays, not a guess."""
    model = _als_model()
    components = memacct.LEDGER.model_bytes()["als"]
    expected = (model.user_factors.nbytes + model.item_factors.nbytes)
    assert components["factors"] == expected
    assert components["id_maps"] > 0
    # building the retrieval index adds its component under the SAME
    # model label (the owner wires mem_model before build)
    model.retrieval_index()
    components = memacct.LEDGER.model_bytes()["als"]
    assert components["index"] >= model.item_factors.nbytes


def test_release_model_retires_index_and_scorer_too():
    model = _als_model()
    model.retrieval_index()
    assert "index" in memacct.LEDGER.model_bytes()["als"]
    memacct.release_model(model)
    assert "als" not in memacct.LEDGER.model_bytes()


def test_upsert_rows_reprices_grown_tables():
    model = _als_model(n_users=4, n_items=4, rank=4)
    before = memacct.LEDGER.model_bytes()["als"]["factors"]
    model.upsert_rows(user_rows=[("brand-new", np.ones(4, np.float32))])
    after = memacct.LEDGER.model_bytes()["als"]["factors"]
    assert after == before + 4 * 4  # one new float32 row


def test_unpickle_registers_the_load_seam():
    model = _als_model()
    blob = pickle.dumps(model)
    memacct.clear()
    loaded = pickle.loads(blob)
    assert memacct.LEDGER.model_bytes()["als"]["factors"] == (
        loaded.user_factors.nbytes + loaded.item_factors.nbytes)


# -- capacity / headroom / probe ----------------------------------------------

def test_env_basis_headroom_and_probe(monkeypatch):
    monkeypatch.setenv("PIO_PEAK_HBM_BYTES", "10000")

    class Owner:
        pass

    o = Owner()
    memacct.LEDGER.register(o, "m", "factors", 4000)
    report = memacct.capacity_report()
    assert report["basis"] == "env"
    assert report["capacity_bytes"] == 10000
    assert report["in_use_bytes"] == 4000
    assert report["headroom_bytes"] == 6000
    assert metrics.REGISTRY.get(
        "pio_device_headroom_bytes").value == 6000.0
    assert memacct.device_memory_probe().status == "ok"
    # push under the floor (5% of 10000 = 500): DEGRADED, not FAILED —
    # still serving, but the next deploy will be refused
    memacct.LEDGER.register(o, "m", "factors", 9800)
    result = memacct.device_memory_probe()
    assert result.status == "degraded"
    assert "preflight" in result.reason


def test_readyz_carries_the_device_memory_probe(memory_storage):
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    try:
        status, body = get_json(
            f"http://127.0.0.1:{server.port}/readyz")
        assert status == 200
        assert "device_memory" in body["probes"]
    finally:
        server.stop()


# -- train high-water ----------------------------------------------------------

def test_peak_from_compiled_fallback_contract():
    class Attrs:
        def memory_analysis(self):
            class MA:
                argument_size_in_bytes = 100
                output_size_in_bytes = 50
                temp_size_in_bytes = 30
                alias_size_in_bytes = 20
            return MA()

    class AsDict:
        def memory_analysis(self):
            return {"argument_size_in_bytes": 10,
                    "output_size_in_bytes": 5,
                    "temp_size_in_bytes": 1,
                    "alias_size_in_bytes": 0}

    class Nothing:
        def memory_analysis(self):
            return None

    class Raises:
        def memory_analysis(self):
            raise NotImplementedError("backend says no")

    assert memacct.peak_from_compiled(Attrs()) == 160
    assert memacct.peak_from_compiled(AsDict()) == 16
    # None / raising / empty-total: analytic-fallback territory, never
    # an exception — accounting must not change whether training runs
    assert memacct.peak_from_compiled(Nothing()) is None
    assert memacct.peak_from_compiled(Raises()) is None


def test_peak_from_jitted_on_cpu_and_note():
    import jax

    fn = jax.jit(lambda x: x * 2.0)
    x = np.ones((16, 16), np.float32)
    fn(x)
    peak = memacct.peak_from_jitted(fn, x)
    # CPU jax reports CompiledMemoryStats here; either way the
    # contract holds: an int or the analytic-fallback None
    assert peak is None or peak >= 2 * x.nbytes
    memacct.note_train_peak("als", 12345, source="analytic")
    assert memacct.train_peaks()["als"] == {"bytes": 12345,
                                            "source": "analytic"}
    assert metrics.REGISTRY.get("pio_train_peak_bytes").labels(
        "als").value == 12345.0


def test_als_trainer_registers_and_notes_peak():
    from predictionio_tpu.ops.als import ALSConfig, ALSTrainer

    rng = np.random.default_rng(7)
    n = 400
    u = rng.integers(0, 24, n).astype(np.int64)
    i = rng.integers(0, 32, n).astype(np.int64)
    r = rng.normal(size=n).astype(np.float32)
    trainer = ALSTrainer((u, i, r), 24, 32,
                         ALSConfig(rank=4, iterations=1, block_size=64))
    assert memacct.LEDGER.model_bytes()["als"]["train_data"] == (
        int(trainer.transfer_bytes))
    trainer.step_n(1)
    peak = memacct.train_peaks()["als"]
    assert peak["source"] == "analytic"
    assert peak["bytes"] >= trainer.transfer_bytes
    del trainer
    gc.collect()
    assert "als" not in memacct.LEDGER.model_bytes()


# -- OOM preflight -------------------------------------------------------------

def _store_blob(storage, instance_id: str, nbytes: int) -> None:
    storage.models().insert(Model(id=instance_id, models=b"x" * nbytes))


def test_preflight_refuses_forces_and_disables(memory_storage,
                                               monkeypatch):
    monkeypatch.setenv("PIO_PEAK_HBM_BYTES", "1000")
    _store_blob(memory_storage, "fat", 900)   # estimate 1800 > 1000
    with pytest.raises(memacct.PreflightRefused) as exc:
        memacct.preflight_check("fat", memory_storage)
    decision = exc.value.decision
    assert decision["result"] == "refused"
    assert decision["estimated_bytes"] == 1800
    assert decision["headroom_bytes"] == 1000
    assert memacct.last_preflight()["result"] == "refused"
    # force: allowed, recorded as forced
    assert memacct.preflight_check(
        "fat", memory_storage, force=True)["result"] == "forced"
    # a small candidate passes
    _store_blob(memory_storage, "thin", 100)
    assert memacct.preflight_check(
        "thin", memory_storage)["result"] == "allowed"
    # unknown blob: must not block (the ledger prices it after load)
    assert memacct.preflight_check(
        "missing", memory_storage)["result"] == "unknown_size"
    # kill switch
    monkeypatch.setenv("PIO_MEM_PREFLIGHT", "0")
    assert memacct.preflight_check(
        "fat", memory_storage)["result"] == "allowed"


def test_engine_server_reload_answers_507_then_force(memory_storage,
                                                     monkeypatch):
    engine, baseline = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        train_const(memory_storage)  # the candidate
        monkeypatch.setenv("PIO_PEAK_HBM_BYTES", "8")
        status, body = get_json(base + "/reload")
        assert status == 507, body
        assert body["preflight"]["result"] == "refused"
        assert body["preflight"]["headroom_bytes"] == 8
        # the serving model is untouched by the refusal
        status, info = get_json(base + "/")
        assert info["engineInstanceId"] == baseline.id
        # operator override
        status, body = get_json(base + "/reload?force=1")
        assert status == 200, body
        assert body["engineInstanceId"] != baseline.id
    finally:
        server.stop()


def test_hot_swap_releases_old_models(memory_storage):
    """Deregistration on /reload: the swapped-OUT deployment's
    footprints leave the ledger with the swap — gauges never leak a
    retired instance."""
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    try:
        old_model = server.deployment.models[0]
        memacct.LEDGER.register(old_model, "const", "factors", 512)
        assert memacct.LEDGER.model_bytes()["const"]["factors"] == 512
        train_const(memory_storage)
        server.reload()
        assert "const" not in memacct.LEDGER.model_bytes()
    finally:
        server.stop()


def test_replica_stop_releases_models(memory_storage):
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    model = server.deployment.models[0]
    memacct.LEDGER.register(model, "const", "factors", 256)
    server.stop()
    assert "const" not in memacct.LEDGER.model_bytes()


# -- the fleet preflight e2e (acceptance) --------------------------------------

def test_fleet_refuses_oversized_candidate_then_force(memory_storage,
                                                      monkeypatch,
                                                      capsys):
    """A 3-replica fleet on a baseline: the oversized candidate is
    refused at every replica's /reload (507 surfaced via `pio fleet`),
    refused on the canary lane too, the fleet keeps answering with
    zero non-429 errors throughout — and the SAME candidate deploys
    under {"force": true}."""
    from predictionio_tpu.tools import cli

    monkeypatch.setenv("PIO_DRAIN_TIMEOUT", "5")
    engine, baseline = train_const(memory_storage)
    with canary_fleet(memory_storage, engine) as (fleet, router, base):
        _, candidate = train_const(memory_storage)
        assert candidate.id != baseline.id
        # every const-model blob estimates far beyond 8 bytes
        monkeypatch.setenv("PIO_PEAK_HBM_BYTES", "8")
        failures, results = [], []
        with _load(base, failures, results):
            # rolling swap through the router: starts, then every
            # replica's preflight refuses — outcome partial, fleet
            # stays on the baseline
            status, body = get_json(base + "/reload")
            assert status == 202, body
            _await(lambda: (not fleet.snapshot()["swap"]["active"]
                            and fleet.snapshot()["swap"]["last"]),
                   message="refused swap to finish")
            last = fleet.snapshot()["swap"]["last"]
            assert last["outcome"] == "partial"
            assert last["swapped"] == []
            assert all("507" in e for e in last["errors"]), last
            assert any("preflight refused" in e
                       for e in last["errors"]), last
            assert fleet.version() == baseline.id
            # the refusal reason reaches the operator via `pio fleet`
            assert cli.main(["fleet", "--url", base]) == 0
            out = capsys.readouterr().out
            assert "preflight refused" in out and "507" in out
            # canary lane: same refusal, error verdict — the candidate
            # never reaches a replica
            status, body, _ = post(
                base + "/admin/fleet",
                body=json.dumps({"canary": "start"}).encode())
            assert status == 202, body
            _await(lambda: (fleet.canary().get("last") or {}).get(
                "outcome") == "error", message="canary refusal")
            canary_errors = " ".join(fleet.canary()["last"]["errors"])
            assert "507" in canary_errors
            assert not fleet.canary().get("active")
            # the SAME candidate under {"force": true}: accepted, the
            # whole fleet rolls onto it
            _await(lambda: not (fleet._canary_thread is not None
                                and fleet._canary_thread.is_alive()),
                   message="canary thread exit")
            status, body, _ = post(
                base + "/admin/fleet",
                body=json.dumps({"reload": True,
                                 "force": True}).encode())
            assert status == 202, body
            _await(lambda: fleet.version() == candidate.id,
                   message="forced swap onto the candidate")
        assert not failures, failures[:5]
        assert results.count(200) > 20


def test_force_started_canary_promotes_with_force(memory_storage,
                                                  monkeypatch):
    """A canary force-started past the preflight must PROMOTE with the
    same force — otherwise every other replica's 507 would strand the
    fleet permanently mixed (review regression)."""
    monkeypatch.setenv("PIO_CANARY_AUTO", "0")
    monkeypatch.setenv("PIO_DRAIN_TIMEOUT", "5")
    engine, baseline = train_const(memory_storage)
    with canary_fleet(memory_storage, engine, n=2) as (fleet, _r, _b):
        _, candidate = train_const(memory_storage)
        monkeypatch.setenv("PIO_PEAK_HBM_BYTES", "8")
        assert fleet.start_canary(force=True)
        _await(lambda: fleet.canary().get("active"),
               message="forced canary active")
        assert fleet.canary()["forced"] is True
        fleet.promote_canary()
        _await(lambda: fleet.version() == candidate.id,
               message="forced promotion converges")


# -- surfaces ------------------------------------------------------------------

def test_admin_memory_sums_match_ledger_within_1pct(memory_storage,
                                                    monkeypatch):
    """Acceptance: /admin/memory attribution vs the ledger's registered
    nbytes, per loaded model, on CPU with PIO_PEAK_HBM_BYTES set."""
    monkeypatch.setenv("PIO_PEAK_HBM_BYTES", str(1 << 30))
    model = _als_model()
    model.retrieval_index()
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    try:
        status, served = get_json(
            f"http://127.0.0.1:{server.port}/admin/memory")
        assert status == 200
        ledger = {}
        for fp in memacct.LEDGER.footprints():
            ledger[fp.model] = ledger.get(fp.model, 0) + fp.nbytes
        assert served["models"], served
        for name, block in served["models"].items():
            assert block["total_bytes"] == pytest.approx(
                ledger[name], rel=0.01)
            assert block["total_bytes"] == sum(
                block["components"].values())
        assert served["basis"] == "env"
        assert served["capacity_bytes"] == (1 << 30)
        assert served["headroom_bytes"] == (
            served["capacity_bytes"] - served["in_use_bytes"])
    finally:
        server.stop()


def test_pio_mem_cli_renders_both_modes(memory_storage, monkeypatch,
                                        capsys):
    from predictionio_tpu.tools import cli

    monkeypatch.setenv("PIO_PEAK_HBM_BYTES", str(1 << 30))
    model = _als_model()  # kept referenced: the ledger holds weakrefs
    memacct.note_train_peak("als", 4096, source="analytic")
    # in-process
    assert cli.main(["mem"]) == 0
    out = capsys.readouterr().out
    assert "headroom" in out and "als" in out and "train peak" in out
    assert "preflight on" in out
    # over HTTP (any PIO server serves /admin/memory)
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        assert cli.main(["mem", "--url", base, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["models"]["als"]["components"]["factors"] > 0
        assert payload["train_peaks"]["als"]["bytes"] == 4096
    finally:
        server.stop()
        del model


def test_dashboard_memory_panel(memory_storage, monkeypatch):
    from predictionio_tpu.tools.dashboard import DashboardServer

    monkeypatch.setenv("PIO_PEAK_HBM_BYTES", str(1 << 30))
    model = _als_model()  # kept referenced: the ledger holds weakrefs
    server = DashboardServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
    try:
        from tests.test_health import get

        status, html, _ = get(
            f"http://127.0.0.1:{server.port}/memory")
        assert status == 200
        assert "Per-model ledger" in html and "als" in html
        assert "OOM preflight" in html
        # linked from the index
        status, index_html, _ = get(f"http://127.0.0.1:{server.port}/")
        assert '"/memory"' in index_html
    finally:
        server.stop()
        del model


def test_timeline_mem_series(monkeypatch):
    from predictionio_tpu.obs.timeline import Timeline

    monkeypatch.setenv("PIO_PEAK_HBM_BYTES", str(1 << 20))
    model = _als_model()  # kept referenced: the ledger holds weakrefs
    tl = Timeline(interval=0.0)
    assert tl.sample(force=True)
    series = tl.series()["series"]
    assert "mem.headroom" in series
    assert "mem.model_bytes.als" in series
    # the headroom sample is capacity - ledger total (env basis; the
    # ring stores 6 significant figures, hence the loose tolerance)
    assert series["mem.headroom"][-1][1] == pytest.approx(
        (1 << 20) - memacct.LEDGER.total_bytes(), rel=1e-4)
    del model


def test_snapshot_cadence_refreshes_gauges(monkeypatch):
    """Satellite: the device-memory gauges ride the flight-recorder
    snapshot cadence — a serving process reports continuously, not
    only post-train."""
    from predictionio_tpu.obs import flight

    monkeypatch.setenv("PIO_PEAK_HBM_BYTES", "5000")

    class Owner:
        pass

    o = Owner()
    memacct.LEDGER.register(o, "m", "factors", 1234)
    # stale on purpose
    memacct.DEVICE_HEADROOM_BYTES.set(0.0)
    assert memacct.refresh() >= 0  # the listener flight invokes
    assert refresh_headroom() == 5000 - 1234
    # and the listener is actually registered on the cadence
    assert ("memacct", memacct.refresh) in flight._snapshot_listeners


def refresh_headroom() -> float:
    return metrics.REGISTRY.get("pio_device_headroom_bytes").value


def test_jaxmon_delegate_still_answers():
    from predictionio_tpu.obs import jaxmon

    assert jaxmon.update_device_memory_gauges() >= 0


# -- benchcmp keys -------------------------------------------------------------

class TestMemBenchKeys:
    @staticmethod
    def _round(tmp_path, name, hbm, peak):
        doc = {"parsed": {
            "metric": "als_ml20m_rating_updates_per_sec_per_chip",
            "value": 6.0e7,
            "key": {"model_hbm_bytes": hbm,
                    "train_peak_bytes": peak}}}
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_direction_inference(self):
        from predictionio_tpu.tools import benchcmp

        assert benchcmp.lower_is_better("key.model_hbm_bytes")
        assert benchcmp.lower_is_better("key.train_peak_bytes")

    def test_hbm_regression_exits_1(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 1.0e9, 2.0e9),
                 self._round(tmp_path, "BENCH_r02.json", 1.6e9, 2.0e9)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 1
        out = capsys.readouterr().out
        assert "key.model_hbm_bytes" in out and "REGRESSION" in out

    def test_train_peak_regression_exits_1(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 1.0e9, 2.0e9),
                 self._round(tmp_path, "BENCH_r02.json", 1.0e9, 3.0e9)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 1
        assert "key.train_peak_bytes" in capsys.readouterr().out

    def test_shrinking_is_an_improvement(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 2.0e9, 3.0e9),
                 self._round(tmp_path, "BENCH_r02.json", 1.0e9, 2.0e9)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 0
        assert "IMPROVED" in capsys.readouterr().out
