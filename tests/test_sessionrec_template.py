"""Sessionrec engine template: end-to-end against the in-memory event
store — ordered histories in, next-item predictions out, leave-last-out
evaluation fold."""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.models.sessionrec import SessionRecParams
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.templates import sessionrec as seq_t

UTC = dt.timezone.utc
ctx = MeshContext()

N_ITEMS = 8
N_USERS = 24
HIST = 12


@pytest.fixture()
def seq_app(memory_storage):
    app = memory_storage.apps().insert("seqapp")
    memory_storage.events().init(app.id)
    # every user walks the item cycle from an offset — next item fully
    # determined by the previous one
    for u in range(N_USERS):
        for t in range(HIST):
            memory_storage.events().insert(
                Event(
                    event="view",
                    entity_type="user",
                    entity_id=f"u{u}",
                    target_entity_type="item",
                    target_entity_id=f"i{(u + t) % N_ITEMS}",
                    event_time=dt.datetime(2026, 1, 1, 0, 0, t, tzinfo=UTC),
                ),
                app.id,
            )
    return app


FAST = SessionRecParams(
    dim=32, heads=2, layers=1, max_len=HIST, dropout=0.0,
    epochs=25, batch_size=32, learning_rate=3e-3,
)


def test_datasource_orders_and_eval_holds_out_last(memory_storage, seq_app):
    ds = seq_t.SeqDataSource(
        seq_t.SeqDataSourceParams(app_name="seqapp", eval_enabled=True))
    td = ds.read_training(ctx)
    assert len(td.columns.times) == N_USERS * HIST  # columnar by default
    folds = ds.read_eval(ctx)
    assert len(folds) == 1
    train_td, info, qa = folds[0]
    assert info["protocol"] == "leave-last-out"
    assert len(train_td.columns.times) == N_USERS * (HIST - 1)
    assert len(qa) == N_USERS
    # the held-out actual is each user's final item in the cycle
    for q, a in qa:
        u = int(q["user"][1:])
        assert a["item"] == f"i{(u + HIST - 1) % N_ITEMS}"


def test_train_and_predict_next(memory_storage, seq_app):
    engine = seq_t.sessionrec_engine()
    ep = seq_t.default_engine_params("seqapp", algo_params=FAST)
    result = engine.train(ctx, ep)
    model = result.models[0]

    hits = 0
    for u in range(8):
        preds = engine.make_algorithms(ep)[0].predict(
            model, {"user": f"u{u}", "num": 1})
        expect = f"i{(u + HIST) % N_ITEMS}"
        hits += bool(preds["itemScores"]) and preds["itemScores"][0]["item"] == expect
    assert hits >= 6, f"only {hits}/8 next-item hits"

    # anonymous session query: explicit items history, no known user
    preds = engine.make_algorithms(ep)[0].predict(
        model, {"items": ["i2", "i3", "i4"], "num": 1})
    assert preds["itemScores"][0]["item"] == "i5"

    # unknown user with no items -> empty, not an error
    assert engine.make_algorithms(ep)[0].predict(model, {"user": "nobody", "num": 3}) == {
        "itemScores": []
    }


def test_model_pickles_and_serves(memory_storage, seq_app):
    import pickle

    engine = seq_t.sessionrec_engine()
    ep = seq_t.default_engine_params("seqapp", algo_params=FAST)
    model = engine.train(ctx, ep).models[0]
    blob = pickle.dumps(model)
    loaded = pickle.loads(blob)
    a = engine.make_algorithms(ep)[0].predict(model, {"user": "u0", "num": 3})
    b = engine.make_algorithms(ep)[0].predict(loaded, {"user": "u0", "num": 3})
    assert [x["item"] for x in a["itemScores"]] == [x["item"] for x in b["itemScores"]]


def test_num_larger_than_catalog_returns_full_ranking(memory_storage, seq_app):
    engine = seq_t.sessionrec_engine()
    ep = seq_t.default_engine_params("seqapp", algo_params=FAST)
    model = engine.train(ctx, ep).models[0]
    preds = engine.make_algorithms(ep)[0].predict(model, {"user": "u0", "num": 500})
    assert 0 < len(preds["itemScores"]) <= N_ITEMS


def test_batch_predict_honors_exclude_seen(memory_storage, seq_app):
    engine = seq_t.sessionrec_engine()
    ep = seq_t.default_engine_params("seqapp", algo_params=FAST)
    model = engine.train(ctx, ep).models[0]
    algo = engine.make_algorithms(ep)[0]
    # u0 saw every item except none (8-item catalog, 12 views) — use an
    # explicit short session so some items remain unseen
    q = {"items": ["i0", "i1"], "num": 8, "excludeSeen": True}
    batched = dict(algo.batch_predict(model, [(0, q)]))[0]
    single = algo.predict(model, q)
    items = {x["item"] for x in batched["itemScores"]}
    assert items == {x["item"] for x in single["itemScores"]}
    assert not items & {"i0", "i1"}


def test_batch_predict_matches_predict(memory_storage, seq_app):
    engine = seq_t.sessionrec_engine()
    ep = seq_t.default_engine_params("seqapp", algo_params=FAST)
    model = engine.train(ctx, ep).models[0]
    algo = engine.make_algorithms(ep)[0]
    queries = [(i, {"user": f"u{i}", "num": 3}) for i in range(6)]
    queries.append((6, {"user": "ghost", "num": 3}))
    batched = dict(algo.batch_predict(model, queries))
    for i, q in queries:
        single = algo.predict(model, q)
        assert [x["item"] for x in batched[i]["itemScores"]] == [
            x["item"] for x in single["itemScores"]
        ]


def test_columnar_read_matches_row_path(memory_storage, seq_app):
    """The bulk dict-encoded read must produce the same prepared
    sequences as the per-event row path."""
    prep = seq_t.SeqPreparator(None)

    def resolved(columnar):
        ds = seq_t.SeqDataSource(
            seq_t.SeqDataSourceParams(app_name="seqapp", columnar=columnar)
        )
        pd = prep.prepare(ctx, ds.read_training(ctx))
        inv_u, inv_i = pd.user_ids.inverse(), pd.item_ids.inverse()
        return sorted(
            (inv_u[int(u)], inv_i[int(i)], float(t))
            for u, i, t in zip(pd.user_idx, pd.item_idx, pd.times)
        )

    assert resolved(True) == resolved(False)
