"""Ops journal (obs/journal.py): ring semantics, the disk writer's
durability contract (flush barrier, size-capped rotation, torn-tail
read-back), shed-episode aggregation, and the /admin/journal page."""

import json
import threading

import pytest

from predictionio_tpu.obs import journal


class TestRing:
    def test_emit_stamps_and_keeps_fields(self):
        event = journal.emit("reload", instance="i-1", forced=None,
                             prev="i-0")
        assert event["kind"] == "reload"
        assert event["instance"] == "i-1"
        assert event["prev"] == "i-0"
        assert "forced" not in event  # None fields are elided
        assert isinstance(event["ts"], float)
        assert isinstance(event["mono"], float)
        got = journal.JOURNAL.recent()
        assert got and got[-1] == event

    def test_recent_filters_kind_since_and_n(self):
        journal.emit("reload", instance="a")
        journal.emit("breaker", target="t", state="open")
        journal.emit("reload", instance="b")
        reloads = journal.JOURNAL.recent(kind="reload")
        assert [e["instance"] for e in reloads] == ["a", "b"]
        assert journal.JOURNAL.recent(n=1, kind="reload")[0][
            "instance"] == "b"
        assert journal.JOURNAL.recent(n=0) == []
        cutoff = reloads[-1]["ts"]
        assert all(e["ts"] >= cutoff
                   for e in journal.JOURNAL.recent(since=cutoff))

    def test_ring_is_bounded_by_env(self, monkeypatch):
        monkeypatch.setenv("PIO_JOURNAL_RING", "16")
        for i in range(40):
            journal.emit("patch", seq=i)
        got = journal.JOURNAL.recent()
        assert len(got) == 16
        assert got[-1]["seq"] == 39  # newest kept, oldest dropped

    def test_trace_id_joins_when_active(self):
        from predictionio_tpu.obs import trace

        trace_id = trace.new_trace_id()
        token = trace.activate(trace_id)
        try:
            event = journal.emit("breaker", target="x", state="open")
        finally:
            trace.deactivate(token)
        assert event.get("trace") == trace_id
        assert "trace" not in journal.emit("breaker", target="x",
                                           state="closed")

    def test_page_shape(self, monkeypatch, tmp_path):
        sink = str(tmp_path / "j.jsonl")
        monkeypatch.setenv("PIO_JOURNAL_PATH", sink)
        journal.emit("swap", phase="start")
        page = journal.JOURNAL.page(n=10)
        assert set(page) == {"capacity", "path", "dropped_total",
                             "events"}
        assert page["path"] == sink
        assert page["events"][-1]["kind"] == "swap"


class TestWriter:
    def test_flush_is_a_durability_barrier(self, monkeypatch, tmp_path):
        sink = tmp_path / "j.jsonl"
        monkeypatch.setenv("PIO_JOURNAL_PATH", str(sink))
        for i in range(50):
            journal.emit("fold", outcome="ok", events=i)
        assert journal.JOURNAL.flush(timeout=10.0)
        lines = sink.read_text().splitlines()
        assert len(lines) == 50
        assert json.loads(lines[-1])["events"] == 49

    def test_no_sink_means_no_file(self, monkeypatch, tmp_path):
        monkeypatch.delenv("PIO_JOURNAL_PATH", raising=False)
        journal.emit("reload", instance="ring-only")
        assert journal.JOURNAL.flush(timeout=1.0)  # nothing pending
        assert list(tmp_path.iterdir()) == []

    def test_rotation_keeps_current_plus_one_roll(self, monkeypatch,
                                                  tmp_path):
        sink = tmp_path / "j.jsonl"
        monkeypatch.setenv("PIO_JOURNAL_PATH", str(sink))
        monkeypatch.setenv("PIO_JOURNAL_MAX_BYTES", "400")
        for i in range(60):
            journal.emit("patch", seq=i)
            # serialize so tell() sees each append before the next cap
            # check — the cap is a per-line decision on the writer
            assert journal.JOURNAL.flush(timeout=10.0)
        assert sink.exists()
        rolled = tmp_path / "j.jsonl.1"
        assert rolled.exists()
        assert sink.stat().st_size <= 400 + 200  # cap + one line slack
        # exactly one roll file ever: .1 is replaced, .2 never exists
        assert sorted(p.name for p in tmp_path.iterdir()) == [
            "j.jsonl", "j.jsonl.1"]
        # read_back stitches roll + current in order; rotation DROPS
        # history beyond the two files, never corrupts what remains
        events, corrupt = journal.read_back(str(sink))
        assert corrupt == 0
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
        assert seqs[-1] == 59

    def test_restart_durability(self, monkeypatch, tmp_path):
        """A new process (fresh Journal over the same path) appends;
        read_back returns both generations."""
        sink = tmp_path / "j.jsonl"
        monkeypatch.setenv("PIO_JOURNAL_PATH", str(sink))
        journal.emit("reload", instance="gen-1")
        assert journal.JOURNAL.flush(timeout=10.0)
        fresh = journal.Journal()  # the restarted process
        fresh.emit("reload", instance="gen-2")
        assert fresh.flush(timeout=10.0)
        events, corrupt = journal.read_back(str(sink))
        assert corrupt == 0
        assert [e["instance"] for e in events
                if e["kind"] == "reload"] == ["gen-1", "gen-2"]
        fresh.reset()

    def test_read_back_skips_torn_tail(self, monkeypatch, tmp_path):
        sink = tmp_path / "j.jsonl"
        monkeypatch.setenv("PIO_JOURNAL_PATH", str(sink))
        journal.emit("swap", phase="start")
        journal.emit("swap", phase="end", outcome="ok")
        assert journal.JOURNAL.flush(timeout=10.0)
        with open(sink, "a", encoding="utf-8") as f:
            f.write('{"ts": 1.0, "kind": "swa')  # killed mid-append
        events, corrupt = journal.read_back(str(sink))
        assert corrupt == 1
        assert [e["kind"] for e in events] == ["swap", "swap"]

    def test_read_back_counts_non_dict_lines(self, tmp_path):
        sink = tmp_path / "j.jsonl"
        sink.write_text('{"ts": 1.0, "kind": "reload"}\n[1, 2]\n\n')
        events, corrupt = journal.read_back(str(sink))
        assert len(events) == 1 and corrupt == 1

    def test_writer_survives_unwritable_sink(self, monkeypatch,
                                             tmp_path):
        base = journal._DROPPED_TOTAL.value
        monkeypatch.setenv("PIO_JOURNAL_PATH",
                           str(tmp_path / "no-such-dir" / "j.jsonl"))
        journal.emit("reload", instance="doomed")
        assert journal.JOURNAL.flush(timeout=10.0)  # drains via drop
        assert journal._DROPPED_TOTAL.value > base
        # the writer thread is still alive for a good sink
        good = tmp_path / "j.jsonl"
        monkeypatch.setenv("PIO_JOURNAL_PATH", str(good))
        journal.emit("reload", instance="landed")
        assert journal.JOURNAL.flush(timeout=10.0)
        events, _ = journal.read_back(str(good))
        assert events[-1]["instance"] == "landed"

    def test_emit_is_fire_and_forget_under_concurrency(self,
                                                       monkeypatch,
                                                       tmp_path):
        sink = tmp_path / "j.jsonl"
        monkeypatch.setenv("PIO_JOURNAL_PATH", str(sink))

        def hammer(tid):
            for i in range(100):
                journal.emit("breaker", target=f"t{tid}", state="open",
                             seq=i)

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert journal.JOURNAL.flush(timeout=10.0)
        events, corrupt = journal.read_back(str(sink))
        assert corrupt == 0
        assert len(events) == 400


class TestShedEpisodes:
    def test_episode_opens_once_and_closes_after_idle(self):
        eps = journal.SHED_EPISODES
        eps.note_shed("slo_burn", now_mono=100.0, server="eng")
        eps.note_shed("slo_burn", now_mono=101.0, server="eng")
        eps.note_shed("slo_burn", now_mono=102.0, server="eng")
        starts = journal.JOURNAL.recent(kind="shed_episode")
        assert len(starts) == 1  # one start, not one per 429
        assert starts[0]["phase"] == "start"
        assert starts[0]["reason"] == "slo_burn"
        assert starts[0]["server"] == "eng"
        assert not eps.maybe_close(now_mono=103.0)  # still inside idle
        assert eps.maybe_close(now_mono=102.0 + eps.idle_sec() + 0.1)
        events = journal.JOURNAL.recent(kind="shed_episode")
        assert events[-1]["phase"] == "end"
        assert events[-1]["sheds"] == 3
        assert events[-1]["duration_sec"] == pytest.approx(2.0)

    def test_closed_episode_reopens_on_next_shed(self):
        eps = journal.SHED_EPISODES
        eps.note_shed("queue_full", now_mono=10.0)
        assert eps.maybe_close(now_mono=10.0 + eps.idle_sec() + 1.0)
        eps.note_shed("queue_full", now_mono=50.0)
        phases = [e["phase"] for e in
                  journal.JOURNAL.recent(kind="shed_episode")]
        assert phases == ["start", "end", "start"]

    def test_maybe_close_noop_when_inactive(self):
        assert not journal.SHED_EPISODES.maybe_close(now_mono=1.0)
        assert journal.JOURNAL.recent(kind="shed_episode") == []


class TestHTTPSurface:
    """GET /admin/journal + /admin/anomaly on a live server, and the
    fleet variants' 404 contract off-fleet."""

    @pytest.fixture()
    def server(self, memory_storage):
        from predictionio_tpu.serving.event_server import EventServer

        server = EventServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
        try:
            yield f"http://127.0.0.1:{server.port}"
        finally:
            server.stop()

    @staticmethod
    def _get(url):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            body = e.read()
            return e.code, json.loads(body) if body else {}

    def test_admin_journal_page_and_filters(self, server):
        journal.emit("reload", instance="i-1")
        journal.emit("breaker", target="t", state="open")
        status, page = self._get(server + "/admin/journal")
        assert status == 200
        assert [e["kind"] for e in page["events"]] == ["reload",
                                                       "breaker"]
        status, page = self._get(server + "/admin/journal?kind=reload")
        assert status == 200
        assert [e["kind"] for e in page["events"]] == ["reload"]
        status, page = self._get(server + "/admin/journal?n=1")
        assert status == 200 and len(page["events"]) == 1
        status, body = self._get(server + "/admin/journal?n=zap")
        assert status == 400 and "bad n/since" in body["message"]
        status, body = self._get(server + "/admin/journal?since=zap")
        assert status == 400

    def test_admin_anomaly_scans_and_reports(self, server):
        status, report = self._get(server + "/admin/anomaly")
        assert status == 200
        assert set(report) == {"window_sec", "active", "recent_resolved",
                               "scan_ms"}
        assert report["active"] == {}

    def test_fleet_variants_404_off_fleet(self, server, monkeypatch):
        monkeypatch.delenv("PIO_OBS_MEMBERS", raising=False)
        for path in ("/admin/fleet/journal", "/admin/fleet/anomaly"):
            status, body = self._get(server + path)
            assert status == 404
            assert "no fleet supervised" in body["message"]

    def test_fleet_journal_via_obs_members(self, server, monkeypatch):
        # PIO_OBS_MEMBERS pointing at ourselves: the single-member merge
        monkeypatch.setenv("PIO_OBS_MEMBERS", f"self={server}")
        journal.emit("swap", phase="start")
        status, merged = self._get(server + "/admin/fleet/journal")
        assert status == 200
        assert merged["merged_from"] == ["self"]
        assert merged["events"][-1]["kind"] == "swap"
        assert merged["events"][-1]["fleet_member"] == "self"
        status, fa = self._get(server + "/admin/fleet/anomaly")
        assert status == 200
        assert fa["any_active"] is False
        assert fa["members"][0]["ok"] is True
