"""BiMap behavior (ref spec: data/.../storage/BiMapSpec.scala)."""

import numpy as np
import pytest

from predictionio_tpu.data.bimap import BiMap


def test_forward_and_inverse():
    m = BiMap({"a": 1, "b": 2})
    assert m["a"] == 1
    assert m.inverse()[2] == "b"
    assert m.inverse().inverse()["a"] == 1


def test_values_must_be_unique():
    with pytest.raises(ValueError):
        BiMap({"a": 1, "b": 1})


def test_string_int_indexing():
    m = BiMap.string_int(["u3", "u1", "u3", "u2", "u1"])
    assert len(m) == 3
    assert m["u3"] == 0 and m["u1"] == 1 and m["u2"] == 2
    inv = m.inverse()
    assert [inv[i] for i in range(3)] == ["u3", "u1", "u2"]


def test_take_submap():
    m = BiMap.string_int(["a", "b", "c"])
    sub = m.take(["a", "c", "zzz"])
    assert sub.to_dict() == {"a": 0, "c": 2}


def test_vectorized_index_array():
    m = BiMap.string_int(["x", "y"])
    arr = m.to_index_array(["y", "x", "y"])
    assert arr.dtype == np.int64
    np.testing.assert_array_equal(arr, [1, 0, 1])


def test_get_and_contains():
    m = BiMap.string_int(["a"])
    assert "a" in m
    assert m.get("missing") is None
    assert m.contains_value(0)


def test_entity_id_ix_map_bidirectional():
    from predictionio_tpu.data.bimap import EntityIdIxMap

    m = EntityIdIxMap.from_keys(["u3", "u1", "u2"])
    assert m("u3") == 0 and m("u2") == 2          # id -> ix
    assert m(0) == "u3" and m(2) == "u2"          # ix -> id
    assert "u1" in m and 1 in m and "zz" not in m and 9 not in m
    assert m.get("zz") is None and m.get(9) is None
    assert len(m) == 3
    sub = m.take(2)
    assert sub.to_dict() == {"u3": 0, "u1": 1}


def test_entity_map_payload_lookup():
    from predictionio_tpu.data.bimap import EntityMap

    m = EntityMap({"a": 10, "b": 20, "c": 30})
    assert m.data("b") == 20
    assert m.data(m("c")) == 30                    # by dense index
    assert m.get_data("zz", -1) == -1 and m.get_data(99, -1) == -1
    sub = m.take(2)
    assert len(sub) == 2 and sub.data("a") == 10


def test_extract_entity_map_from_events():
    import datetime

    from predictionio_tpu.data import store
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import Storage

    st = Storage.from_env({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    app = st.apps().insert("emapp")
    st.events().init(app.id)
    t = datetime.datetime(2015, 1, 1, tzinfo=datetime.timezone.utc)
    for i, rating in enumerate([3.5, 4.0]):
        st.events().insert(
            Event(event="$set", entity_type="item", entity_id=f"i{i}",
                  properties={"rating": rating}, event_time=t), app.id)
    em = store.extract_entity_map(
        "emapp", "item", lambda pm: pm["rating"], storage=st)
    assert len(em) == 2
    assert em.data("i0") == 3.5 and em.data(em("i1")) == 4.0
