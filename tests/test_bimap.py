"""BiMap behavior (ref spec: data/.../storage/BiMapSpec.scala)."""

import numpy as np
import pytest

from predictionio_tpu.data.bimap import BiMap


def test_forward_and_inverse():
    m = BiMap({"a": 1, "b": 2})
    assert m["a"] == 1
    assert m.inverse()[2] == "b"
    assert m.inverse().inverse()["a"] == 1


def test_values_must_be_unique():
    with pytest.raises(ValueError):
        BiMap({"a": 1, "b": 1})


def test_string_int_indexing():
    m = BiMap.string_int(["u3", "u1", "u3", "u2", "u1"])
    assert len(m) == 3
    assert m["u3"] == 0 and m["u1"] == 1 and m["u2"] == 2
    inv = m.inverse()
    assert [inv[i] for i in range(3)] == ["u3", "u1", "u2"]


def test_take_submap():
    m = BiMap.string_int(["a", "b", "c"])
    sub = m.take(["a", "c", "zzz"])
    assert sub.to_dict() == {"a": 0, "c": 2}


def test_vectorized_index_array():
    m = BiMap.string_int(["x", "y"])
    arr = m.to_index_array(["y", "x", "y"])
    assert arr.dtype == np.int64
    np.testing.assert_array_equal(arr, [1, 0, 1])


def test_get_and_contains():
    m = BiMap.string_int(["a"])
    assert "a" in m
    assert m.get("missing") is None
    assert m.contains_value(0)
