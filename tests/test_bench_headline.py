"""The bench's final stdout line must stay inside the driver's capture
window.

Round 4's lesson (VERDICT r4 weak #1): the single fat JSON line outgrew
the driver's ~2 KB tail capture and BENCH_r04.json recorded
``"parsed": null`` — the round's headline was unverifiable from the
scoreboard. ``bench.emit_headline`` now splits output: a compact line
(metric, gates, key numbers, detail-file pointer) on stdout, everything
else to BENCH_DETAIL.json. These tests feed it a representative detail
blob (the r4 shape: histograms, per-run arrays, roofline trace) and pin
the compact-line budget.
"""

import importlib.util
import json
import os

import pytest

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _representative_detail():
    """A detail blob at least as fat as round 4's real one."""
    return {
        "n_users": 138_493, "n_items": 26_744, "n_ratings": 20_000_000,
        "rank": 64, "iterations": 5,
        "synth_sec": 21.3, "ingest_sec": 14.9,
        "ingest_events_per_sec": 1_341_000.1,
        "post_bulk_append_debt_sec": 2.1,
        "json_build_events_per_sec": 91_000.5,
        "row_lane_events_per_sec": 587_700.0,
        "row_lane_gate_passed": True,
        "row_lane_fsync_events_per_sec": 210_000.0,
        "event_build_events_per_sec": 120_000.0,
        "insert_batch_events_per_sec": 95_000.0,
        "python_row_lane_events_per_sec": 52_000.0,
        "read_sec": 4.2, "prepare_sec": 3.9, "bin_sec": 11.2,
        "bin_cache_hit": False, "transfer_sec": 7.1,
        "transfer_bytes": 219_725_824, "transfer_mb_per_sec": 30.9,
        "compile_sec": 24.2, "bin_compile_sec": 42.5,
        "train_sec": 1.52, "events_to_model_sec": 50.6,
        "events_to_model_events_per_sec": 395_000.0,
        "rmse_heldout": 0.4271, "rmse_global_mean_baseline": 1.2513,
        "rmse_gate_passed": True, "rmse_band": [0.38, 0.48],
        "rmse_band_passed": True,
        "updates_per_sec": 62_400_000.0,
        "roofline": {
            "model": "analytic counts from actual padded device shapes",
            "flops_per_iter": 10**12, "hbm_bytes_per_iter": 10**9,
            "achieved_tflops": 3.1, "achieved_hbm_gb_per_sec": 113.5,
            "peak_bf16_tflops": 197.0, "peak_hbm_gb_per_sec": 819.0,
            "mxu_fraction": 0.016, "hbm_fraction": 0.139,
            "measured": {
                "measured": True, "governing": "gather-issue",
                "profiled_step_sec": 0.31,
                "train_slots_per_sec": 0.43,
                "gather_roof_slots_per_sec": 6.1,
                "governing_fraction": 0.07,
                "trace": {
                    "device_time_sec": 0.29,
                    "flops_total": 5 * 10**12,
                    "bytes_total": 4 * 10**10,
                    "hbm_bytes_total": 3 * 10**10,
                    "by_category": {
                        c: {"time_frac": 0.1, "hbm_bytes": 4_000_000,
                            "flops": 9_000_000}
                        for c in ("while", "gather", "fusion", "convert",
                                  "all-reduce", "dot", "copy", "misc")
                    },
                },
            },
        },
        "serve_p50_ms": 0.96, "serve_p99_ms": 1.52, "serve_qps": 1222.7,
        "serve_gate_passed": True,
        "serve_qps_32conn": 2692.0,
        "serve_p50_ms_32conn": 11.63, "serve_p99_ms_32conn": 19.81,
        "serve_p50_ms_32conn_serverside": 10.64,
        "serve_p99_ms_32conn_serverside": 17.09,
        "serve_32conn_runs": [
            {"errors": 0, "qps": 2692.0, "p50_ms": 11.63, "p99_ms": 19.81,
             "srv_p50_ms": 10.64, "srv_p99_ms": 17.09},
            {"errors": 0, "qps": 2339.3, "p50_ms": 13.25, "p99_ms": 21.89,
             "srv_p50_ms": 11.89, "srv_p99_ms": 18.81},
        ],
        "serve_32conn_note": "x" * 300,
        "serve_batch_histogram": {str(k): 17 for k in range(1, 33)},
        "serve_32_gate_passed": True,
        "serve_sweep": [
            {"conns": c, "qps": 1000.0 + c, "p50_ms": 2.0 * c,
             "p99_ms": 3.0 * c, "srv_p50_ms": 1.5 * c, "srv_p99_ms": 2.5 * c,
             "srv_queue_p50_ms": 0.7 * c, "srv_dispatch_p50_ms": 0.9}
            for c in (1, 8, 32, 128)
        ],
        "twotower": {
            "step_ms": 14.2, "mfu": 0.41, "achieved_tflops": 80.0,
            "peak_basis": "197 TFLOP/s bf16 (public v5e peak)",
            "loss_first": 8.1, "loss_last": 2.2, "loss_gate_passed": True,
            "config": {"users": 1_000_000, "items": 1_000_000, "dim": 128,
                       "batch": 8192},
        },
        "warm": {
            "bin_sec": 4.0, "read_sec": 0.0, "prepare_sec": 0.0,
            "bin_cache_hit": True, "transfer_sec": 26.36,
            "transfer_bytes": 219_725_824, "transfer_mb_per_sec": 8.3,
            "compile_sec": 2.15, "bin_compile_sec": 32.51,
            "train_sec": 1.48, "events_to_model_sec": 33.99,
            "events_to_model_events_per_sec": 588_408.4,
        },
    }


def test_headline_fits_driver_window(tmp_path):
    detail = _representative_detail()
    line = bench.emit_headline(detail, detail_path=str(tmp_path / "d.json"))
    encoded = json.dumps(line).encode()
    assert len(encoded) <= bench.MAX_HEADLINE_BYTES
    # the driver parses json.loads(last stdout line): round-trip it
    parsed = json.loads(encoded)
    assert parsed["metric"] == "als_ml20m_rating_updates_per_sec_per_chip"
    assert parsed["value"] == 62_400_000.0
    assert parsed["vs_baseline"] == 62.4
    assert all(parsed["gates"].values())
    assert parsed["key"]["warm_events_to_model_sec"] == 33.99
    assert parsed["key"]["row_lane_events_per_sec"] == 587_700.0
    assert parsed["detail_file"] == "BENCH_DETAIL.json"
    # full detail file holds everything the line dropped
    full = json.loads((tmp_path / "d.json").read_text())
    assert full["serve_batch_histogram"]["32"] == 17
    assert full["roofline"]["measured"]["trace"]["by_category"]


def test_failed_gate_zeroes_value(tmp_path):
    detail = _representative_detail()
    detail["serve_32_gate_passed"] = False
    line = bench.emit_headline(detail, detail_path=str(tmp_path / "d.json"))
    assert line["value"] == 0.0
    assert line["gates"]["serve_32conn"] is False
    # the other gate flags still tell which gates held
    assert line["gates"]["rmse"] is True


def test_twotower_gate_zeroes_value(tmp_path):
    detail = _representative_detail()
    detail["twotower"]["loss_gate_passed"] = False
    line = bench.emit_headline(detail, detail_path=str(tmp_path / "d.json"))
    assert line["value"] == 0.0
    assert line["gates"]["twotower_loss"] is False


def test_oversize_line_prunes_but_always_prints(tmp_path, monkeypatch):
    """An over-budget line must NOT abort the run (that would reproduce
    the BENCH_r04 parsed:null failure): optional key entries are pruned
    until the line fits, and the pruning is recorded in the detail."""
    monkeypatch.setattr(bench, "MAX_HEADLINE_BYTES", 400)
    detail = _representative_detail()
    line = bench.emit_headline(detail, detail_path=str(tmp_path / "d.json"))
    assert len(json.dumps(line).encode()) <= 400
    # the headline value and gates survive pruning
    assert line["value"] == 62_400_000.0
    assert "gates" in line and line["gates"]["rmse"] is True
    full = json.loads((tmp_path / "d.json").read_text())
    assert full["headline_pruned_keys"]


@pytest.mark.parametrize("wire_hangs,compile_hangs,expect", [
    # a REAL tunnel hang wedges BOTH sides: compile()'s warm-up ends in
    # a blocking scalar pull on the very arrays still crossing the wire
    (True, True, "wire.*compile"),
    (True, False, r"wire \(async puts"),
    (False, True, r"compile\+warmup"),
])
def test_transfer_compile_overlap_times_out_with_side_attribution(
        monkeypatch, wire_hangs, compile_hangs, expect):
    """A hung transfer/compile overlap must surface as a diagnosable
    error naming WHICH side(s) were still pending at the deadline,
    instead of wedging the bench process forever — and the deadline
    must cover the compile thread too, since its warm-up blocks on the
    transferred data (advisor finding, r6)."""
    import threading

    monkeypatch.setattr(bench, "TRANSFER_JOIN_TIMEOUT_SEC", 0.05)
    release = threading.Event()

    class HungTrainer:
        put_start = 0.0
        transfer_bytes = 0

        def wait_device_timed(self):
            if wire_hangs:
                release.wait(5.0)
            return [0.0]

        def compile(self):
            if compile_hangs:
                release.wait(5.0)

    try:
        with pytest.raises(RuntimeError, match=expect):
            bench._transfer_and_compile({"bin_sec": 0.0}, HungTrainer(),
                                        iterations=1, n_read=1)
    finally:
        release.set()            # unblock the daemon threads


def test_transfer_timeout_surfaces_dead_side_error(monkeypatch):
    """When one side FAILED fast and the other hangs (dropped tunnel:
    watcher errors, warm-up waits forever), the timeout message must
    carry the dead side's error — it is the root cause."""
    import threading

    monkeypatch.setattr(bench, "TRANSFER_JOIN_TIMEOUT_SEC", 0.05)
    release = threading.Event()

    class Trainer:
        put_start = 0.0
        transfer_bytes = 0

        def wait_device_timed(self):
            raise OSError("tunnel dropped")

        def compile(self):
            release.wait(5.0)   # waits on data that will never land

    try:
        with pytest.raises(RuntimeError,
                           match=r"compile\+warmup.*wire already failed.*"
                                 r"tunnel dropped"):
            bench._transfer_and_compile({"bin_sec": 0.0}, Trainer(),
                                        iterations=1, n_read=1)
    finally:
        release.set()


def test_lint_stage_key_lands_and_gates_lower_better(tmp_path):
    """The project-mode graftlint wall clock is a first-class gated
    number: stage_lint's measurement lands in key.lint_project_ms and
    bench-compare directions it lower-better (the _ms convention) — a
    super-linear regression in the whole-program analysis fails the
    compare gate instead of silently taxing every commit's tier-1."""
    from predictionio_tpu.tools import benchcmp

    detail = _representative_detail()
    detail["lint_project_ms"] = 5252.6
    line = bench.emit_headline(detail, detail_path=str(tmp_path / "d.json"))
    assert line["key"]["lint_project_ms"] == 5252.6
    assert len(json.dumps(line).encode()) <= bench.MAX_HEADLINE_BYTES
    assert benchcmp.lower_is_better("key.lint_project_ms")
    assert not benchcmp.is_config_key("key.lint_project_ms")


def test_dataobs_stage_keys_land_and_gate(tmp_path):
    """stage_dataobs' two numbers are first-class gated metrics:
    key.dataobs_update_us (the per-event sketch cost) and
    key.dataobs_overhead_pct (the hook's tax on the insert_batch bulk
    lane) land in the headline, bench-compare directions both
    lower-better, and a blown overhead gate (>3%) zeroes the headline
    value like any other hard gate."""
    from predictionio_tpu.tools import benchcmp

    detail = _representative_detail()
    detail["dataobs_update_us"] = 0.55
    detail["dataobs_overhead_pct"] = 0.25
    detail["dataobs_gate_passed"] = True
    line = bench.emit_headline(detail, detail_path=str(tmp_path / "d.json"))
    assert line["key"]["dataobs_update_us"] == 0.55
    assert line["key"]["dataobs_overhead_pct"] == 0.25
    assert line["gates"]["dataobs_overhead"] is True
    assert len(json.dumps(line).encode()) <= bench.MAX_HEADLINE_BYTES
    assert benchcmp.lower_is_better("key.dataobs_update_us")
    assert benchcmp.lower_is_better("key.dataobs_overhead_pct")
    assert not benchcmp.is_config_key("key.dataobs_update_us")

    detail = _representative_detail()
    detail["dataobs_update_us"] = 2.0
    detail["dataobs_overhead_pct"] = 4.8
    detail["dataobs_gate_passed"] = False
    line = bench.emit_headline(detail, detail_path=str(tmp_path / "d.json"))
    assert line["value"] == 0.0
    assert line["gates"]["dataobs_overhead"] is False


def test_benchcmp_dataobs_regression_exits_1(tmp_path, capsys):
    """A sketch-cost regression between rounds fails pio bench-compare
    with exit 1 (the CI contract), exactly like the serving metrics."""
    from predictionio_tpu.tools import benchcmp

    def round_file(name, update_us, overhead_pct):
        p = tmp_path / name
        p.write_text(json.dumps({"parsed": {
            "metric": "m", "value": 1.0,
            "key": {"dataobs_update_us": update_us,
                    "dataobs_overhead_pct": overhead_pct},
        }}))
        return str(p)

    base = round_file("BENCH_r01.json", 0.55, 0.25)
    worse = round_file("BENCH_r02.json", 1.60, 0.25)
    assert benchcmp.run([base, worse]) == 1
    out = capsys.readouterr().out
    assert "key.dataobs_update_us" in out and "REGRESSION" in out
    better = round_file("BENCH_r03.json", 0.50, 0.20)
    assert benchcmp.run([base, better]) == 0
