"""Test harness configuration.

Multi-device testing without TPUs (SURVEY.md §4 lesson — the reference
can only test Spark logic in local[4] mode): force an 8-device CPU mesh
so all pjit/shard_map code paths run in-process.  Must happen before the
first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# the environment may pin a TPU platform plugin over JAX_PLATFORMS; the
# config update wins as long as no backend has been initialized yet
jax.config.update("jax_platforms", "cpu")

import pytest

from predictionio_tpu.data.storage import Storage, set_storage


@pytest.fixture(autouse=True)
def _reset_resilience():
    """Per-test isolation for the resilience subsystem's process-global
    state: circuit breakers are keyed by endpoint (ephemeral test ports
    recycle!), chaos rules are process-wide, and the SLO monitor's burn
    gauges feed admission control — a previous test's open circuit,
    active fault, or deliberately-slow traffic must never shed the next
    test's requests."""
    from predictionio_tpu.obs import anomaly, dataobs, journal, slo
    from predictionio_tpu.resilience import chaos, policy

    def reset():
        policy.reset_breakers()
        chaos.reset()
        slo.MONITOR.clear()
        slo.MONITOR.evaluate()  # no samples -> burn gauges back to 0
        journal.JOURNAL.reset()
        journal.SHED_EPISODES.reset()
        anomaly.SENTINEL.reset()
        dataobs.DATAOBS.reset()

    reset()
    yield
    reset()


@pytest.fixture()
def memory_storage():
    """Fresh in-memory storage installed as the process singleton."""
    storage = Storage.from_env(
        {
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
    set_storage(storage)
    yield storage
    set_storage(None)
