"""Sequential recommender: training learns an obvious transition
pattern; sequence-parallel (ring attention) training step runs on the
mesh and matches the single-device forward."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.sessionrec import (
    SessionEncoder,
    SessionRecConfig,
    SessionRecTrainer,
    SessionScorer,
    build_sequences,
)
from predictionio_tpu.parallel.mesh import create_mesh


def _cyclic_events(n_users=64, n_items=12, hist=24, seed=0):
    """Every user walks the item cycle 0,1,2,...,n-1,0,... from a random
    offset — the next item is fully determined by the last one."""
    rng = np.random.default_rng(seed)
    users, items, times = [], [], []
    for u in range(n_users):
        start = rng.integers(0, n_items)
        for t in range(hist):
            users.append(u)
            items.append((start + t) % n_items)
            times.append(t)
    return np.array(users), np.array(items), np.array(times, np.float64)


def test_build_sequences_right_aligned_chronological():
    u = np.array([1, 0, 1, 0])
    i = np.array([5, 3, 7, 2])
    t = np.array([2.0, 9.0, 4.0, 1.0])
    out = build_sequences(u, i, t, n_users=3, max_len=3)
    # user 0: time order (2@1, 3@9) -> 1-shifted [3, 4], left-aligned
    np.testing.assert_array_equal(out[0], [3, 4, 0, 0])
    np.testing.assert_array_equal(out[1], [6, 8, 0, 0])
    np.testing.assert_array_equal(out[2], [0, 0, 0, 0])


def test_trainer_learns_cycle_and_scorer_predicts_next():
    users, items, times = _cyclic_events()
    cfg = SessionRecConfig(
        dim=32, heads=2, layers=1, max_len=16, dropout=0.0,
        epochs=30, batch_size=64, learning_rate=3e-3,
    )
    tr = SessionRecTrainer((users, items, times), 64, 12, cfg)
    losses = tr.run()
    assert losses[-1] < losses[0] * 0.5, losses
    state = tr.state(losses)
    scorer = SessionScorer(state)
    scores, idx = scorer.top_k(state.sequences[:8], k=1, exclude_seen=False)
    # each user's last item is known; top-1 should be (last + 1) % n
    rows = state.sequences[:8]
    last_pos = (rows > 0).sum(axis=1) - 1
    last = rows[np.arange(8), last_pos] - 1
    expect = (last + 1) % 12
    acc = float(np.mean(idx[:, 0] == expect))
    assert acc >= 0.75, (idx[:, 0], expect)


def test_scorer_excludes_seen_and_pad():
    users, items, times = _cyclic_events(n_users=8, n_items=6, hist=4)
    cfg = SessionRecConfig(dim=16, heads=2, layers=1, max_len=4,
                           dropout=0.0, epochs=1, batch_size=8)
    tr = SessionRecTrainer((users, items, times), 8, 6, cfg)
    tr.run()
    state = tr.state()
    scorer = SessionScorer(state)
    scores, idx = scorer.top_k(state.sequences[:4], k=2, exclude_seen=True)
    for r in range(4):
        seen = set(state.sequences[r][state.sequences[r] > 0] - 1)
        assert not (set(idx[r]) & seen)
        assert (idx[r] >= 0).all()


def test_blockwise_and_ring_forward_match_materialized():
    users, items, times = _cyclic_events(n_users=16, n_items=8, hist=32)
    base = SessionRecConfig(dim=32, heads=2, layers=2, max_len=32, dropout=0.0)
    enc = SessionEncoder(8, base)
    seqs = build_sequences(users, items, times, 16, base.max_len)[:, :-1]
    params = enc.init(jax.random.PRNGKey(0), jnp.asarray(seqs))
    ref = enc.apply(params, jnp.asarray(seqs))

    blk = SessionEncoder(8, dataclasses.replace(base, attn_block=8))
    np.testing.assert_allclose(
        np.asarray(blk.apply(params, jnp.asarray(seqs))),
        np.asarray(ref), atol=1e-5,
    )

    mesh = create_mesh({"seq": 8})
    ring = SessionEncoder(8, dataclasses.replace(base, seq_axis="seq"), mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(ring.apply(params, jnp.asarray(seqs))),
        np.asarray(ref), atol=1e-5,
    )


def test_seq_parallel_training_step_runs():
    users, items, times = _cyclic_events(n_users=16, n_items=8, hist=32)
    mesh = create_mesh({"data": 2, "seq": 4})
    cfg = SessionRecConfig(
        dim=16, heads=2, layers=1, max_len=32, dropout=0.0,
        epochs=1, batch_size=8, seq_axis="seq",
    )
    tr = SessionRecTrainer((users, items, times), 16, 8, cfg, mesh=mesh)
    losses = tr.run()
    assert np.isfinite(losses[0])
