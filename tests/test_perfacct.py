"""Performance accounting (obs/perfacct.py + obs/timeline.py and the
serving/CLI wiring): MFU gauges from cost_analysis with the analytic
fallback, data-path ledger + staleness monotonicity across a train
publish, tail-latency attribution arithmetic, timeline ring eviction
and cadence, the /admin/timeline + /admin/tail auth matrix, and the
`pio top --once --json` output shape."""

import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

import numpy as np
import pytest

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
)
from predictionio_tpu.core.params import EngineParams, Params
from predictionio_tpu.obs import flight, metrics, perfacct, timeline
from predictionio_tpu.obs.flight import FlightRecorder
from predictionio_tpu.obs.perfacct import (
    DataPathLedger,
    StepAccountant,
    tail_report,
    twotower_matmul_flops,
)
from predictionio_tpu.obs.timeline import Timeline, sparkline
from predictionio_tpu.workflow.train import run_train


def http(method, url, body=None, headers=None, timeout=15):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


@pytest.fixture(autouse=True)
def _reset_perfacct():
    """The ledger and timeline are process-global; each test starts
    from a clean clock and empty rings."""
    perfacct.LEDGER.clear()
    timeline.TIMELINE.clear()
    yield
    perfacct.LEDGER.clear()
    timeline.TIMELINE.clear()


# ---------------------------------------------------------------------------
# MFU: cost_analysis path + analytic fallback
# ---------------------------------------------------------------------------

def test_costs_from_compiled_real_cpu_executable():
    """A real CPU-compiled step: cost_analysis either reports flops
    (the primary path) or the helper declines with None — it must
    never raise on any backend."""
    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: x @ x).lower(jnp.ones((16, 16))).compile()
    costs = perfacct.costs_from_compiled(compiled)
    if costs is not None:
        flops, bytes_accessed = costs
        assert flops > 0 and bytes_accessed >= 0


def test_accountant_falls_back_when_cost_analysis_fails():
    class Boom:
        def cost_analysis(self):
            raise RuntimeError("no cost model on this backend")

    acct = StepAccountant.from_compiled("fallback-model", Boom(),
                                        fallback_flops=2.5e9,
                                        fallback_bytes=1e6)
    assert acct.source == "analytic"
    assert acct.flops_per_step == 2.5e9
    mfu = acct.observe(0.01)
    assert mfu > 0
    fam = metrics.REGISTRY.get("pio_train_mfu")
    assert fam.labels("fallback-model").value == pytest.approx(mfu)
    assert metrics.REGISTRY.get("pio_step_flops").labels(
        "fallback-model").value == 2.5e9
    # bytes known -> the roofline-position gauge is set
    assert metrics.REGISTRY.get("pio_roofline_position").labels(
        "fallback-model").value > 0


def test_accountant_empty_cost_analysis_also_falls_back():
    class Empty:
        def cost_analysis(self):
            return [{}]  # jax returning nothing usable

    acct = StepAccountant.from_compiled("empty-model", Empty(),
                                        fallback_flops=1e6)
    assert acct.source == "analytic"


def test_twotower_matmul_flops_matches_trainer_method():
    """The one-formula contract: the trainer's bench hook delegates to
    the shared perfacct formula (bench.py divides the same number)."""
    from predictionio_tpu.ops.twotower import (
        TwoTowerConfig,
        TwoTowerTrainer,
        _tail_widths,
    )

    rng = np.random.default_rng(0)
    u, i = rng.integers(0, 8, 64), rng.integers(0, 8, 64)
    cfg = TwoTowerConfig(dim=4, batch_size=16, epochs=1)
    trainer = TwoTowerTrainer((u, i, None), 8, 8, cfg)
    assert trainer.matmul_flops_per_step() == twotower_matmul_flops(
        trainer.batch, cfg.dim, _tail_widths(cfg))


def test_twotower_run_populates_live_mfu_gauge():
    """Acceptance: a CPU train run sets pio_train_mfu > 0 via either
    the cost-analysis or the analytic fallback path."""
    from predictionio_tpu.ops.twotower import TwoTowerConfig, TwoTowerTrainer

    rng = np.random.default_rng(1)
    u, i = rng.integers(0, 8, 64), rng.integers(0, 8, 64)
    trainer = TwoTowerTrainer((u, i, None), 8, 8,
                              TwoTowerConfig(dim=4, batch_size=16, epochs=2))
    trainer.run()
    assert trainer._acct is not None
    assert trainer._acct.source in ("cost_analysis", "analytic")
    assert metrics.REGISTRY.get("pio_train_mfu").labels(
        "twotower").value > 0


# ---------------------------------------------------------------------------
# data-path ledger + staleness clock
# ---------------------------------------------------------------------------

def test_staleness_monotonic_then_drops_across_publish():
    ledger = DataPathLedger()
    assert ledger.staleness_seconds(now=50.0) == 0.0  # nothing ingested
    ledger.note_ingest(ts=100.0)
    # grows monotonically while the events wait for a model
    assert ledger.staleness_seconds(now=110.0) == pytest.approx(10.0)
    assert ledger.staleness_seconds(now=130.0) == pytest.approx(30.0)
    ledger.note_train_read(ts=140.0)   # the model will cover ts<=100
    ledger.note_publish(ts=150.0)
    # everything ingested is now servable: clock back to zero
    assert ledger.staleness_seconds(now=160.0) == 0.0


def test_staleness_events_arriving_during_train():
    ledger = DataPathLedger()
    ledger.note_ingest(ts=100.0)
    ledger.note_train_read(ts=110.0)   # horizon will be 100
    ledger.note_ingest(ts=115.0)       # lands mid-train
    ledger.note_publish(ts=120.0)
    # the mid-train event is NOT covered: it waits from the horizon
    # boundary (the ledger's documented approximation)
    assert ledger.staleness_seconds(now=130.0) == pytest.approx(30.0)
    ledger.note_train_read(ts=140.0)
    ledger.note_publish(ts=150.0)
    assert ledger.staleness_seconds(now=160.0) == 0.0


def test_ledger_stage_accumulation_and_gauge():
    ledger = DataPathLedger()
    ledger.start_run("run-1")
    ledger.note_stage("read", 1.5)
    ledger.note_stage("bin_cache_load", 0.25)
    ledger.note_stage("bin_cache_load", 0.25)  # additive (two sides)
    snap = ledger.snapshot()
    assert snap["runs"][-1]["run"] == "run-1"
    assert snap["runs"][-1]["stages"] == {
        "read": 1.5, "bin_cache_load": 0.5}


def test_stage_gauge_resets_per_run():
    """The gauge describes the CURRENT run: a warm run that skips
    compile must not keep exporting the cold run's compile seconds."""
    ledger = DataPathLedger()
    ledger.start_run("cold")
    ledger.note_stage("compile", 12.0)
    family = metrics.REGISTRY.get("pio_datapath_stage_seconds")
    assert family.labels("compile").value == 12.0
    ledger.start_run("warm")
    ledger.note_stage("read", 0.5)
    stages = {vals[0]: c.value for vals, c in family.children()}
    assert "compile" not in stages
    assert stages["read"] == 0.5
    # run history keeps the cold run's full story
    assert ledger.snapshot()["runs"][0]["stages"]["compile"] == 12.0


def test_sqlite_insert_batch_notes_ingest(tmp_path):
    """Every bulk storage writer feeds the freshness clock — the
    sqlite transaction lane included."""
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import Storage

    st = Storage.from_env({
        "PIO_STORAGE_SOURCES_S_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_S_PATH": str(tmp_path / "store"),
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "S",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "S",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S",
    })
    st.events().init(1)
    st.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id="u1")], 1)
    assert perfacct.LEDGER.staleness_seconds() >= 0.0
    snap = perfacct.LEDGER.snapshot()
    assert snap["last_ingest_unix"] is not None


def test_run_train_feeds_ledger_and_staleness(memory_storage):
    """Acceptance: across a fake-workflow train publish the staleness
    gauge DECREASES, and the run's ledger carries the pipeline
    stages."""
    from predictionio_tpu.data.event import Event

    @dataclass
    class P(Params):
        pass

    class DS(DataSource):
        def read_training(self, ctx):
            return 1.0

    class Algo(Algorithm):
        def train(self, ctx, pd):
            return pd + 1.0

        def predict(self, model, query):
            return {"result": model}

    # ingest through the storage API: the base insert_batch notes the
    # freshness clock
    memory_storage.events().init(1)
    memory_storage.events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id="u1",
               target_entity_type="item", target_entity_id="i1",
               properties={"rating": 4.0})], 1)
    time.sleep(0.05)
    before = perfacct.LEDGER.staleness_seconds()
    assert before > 0.0

    engine = Engine(DS, IdentityPreparator, {"algo": Algo}, FirstServing)
    ep = EngineParams(
        data_source_params=("", P()),
        preparator_params=("", None),
        algorithm_params_list=[("algo", P())],
        serving_params=("", None),
    )
    instance = run_train(engine, ep, engine_id="perfacct",
                         storage=memory_storage)
    after = perfacct.LEDGER.staleness_seconds()
    assert after < before
    assert after == 0.0  # nothing arrived during the train
    assert metrics.REGISTRY.get(
        "pio_model_staleness_seconds").labels().value == 0.0
    # the run's stage ledger: read/prepare/fit from Engine.train, the
    # whole-train wall from workflow/train.py
    snap = perfacct.LEDGER.snapshot()
    run = next(r for r in snap["runs"] if r["run"] == instance.id)
    for stage in ("read", "prepare", "fit", "train"):
        assert stage in run["stages"], (stage, run["stages"])
    assert snap["model_horizon_unix"] is not None


# ---------------------------------------------------------------------------
# tail-latency attribution
# ---------------------------------------------------------------------------

def _synthetic_records():
    """19 fast requests dominated by dispatch + 1 slow one dominated by
    queue wait: the tail answer must be 'queue'."""
    records = []
    for i in range(19):
        d = 10.0 + i * 0.1
        records.append({"duration_ms": d, "stages": {
            "parse": 0.1, "queue": d * 0.2, "dispatch": d * 0.6,
            "serialize": 0.1,
            "unattributed": d - 0.2 - d * 0.8}})
    d = 100.0
    records.append({"duration_ms": d, "stages": {
        "parse": 0.1, "queue": 90.0, "dispatch": 8.0, "serialize": 0.1,
        "unattributed": 1.8}})
    return records


def test_tail_report_arithmetic():
    report = tail_report(_synthetic_records(), q=0.95)
    assert report["total_count"] == 20
    assert report["tail_count"] >= 1
    assert report["threshold_ms"] == pytest.approx(100.0)
    stages = report["stages"]
    # shares are in [0, 1], never negative, and ~sum to 1 for the tail
    tail_sum = sum(s["tail_share"] for s in stages.values())
    assert tail_sum == pytest.approx(1.0, abs=0.01)
    for s in stages.values():
        assert s["tail_share"] >= 0.0 and s["median_share"] >= 0.0
    # acceptance: >= 95% of above-p95 time attributed to NAMED stages
    assert report["attributed_tail_share"] >= 0.95
    assert report["dominant_tail_stage"] == "queue"
    # the answer differs from the median cohort: queue GROWS in the
    # tail, dispatch shrinks
    assert stages["queue"]["delta_share"] > 0.5
    assert stages["dispatch"]["delta_share"] < 0.0


def test_tail_report_needs_enough_records():
    report = tail_report([{"duration_ms": 1.0, "stages": {}}], q=0.95)
    assert report["tail_count"] == 0 and report["stages"] == {}


def test_tail_report_rejects_bad_quantile():
    with pytest.raises(ValueError):
        tail_report([], q=1.5)


def test_negative_remainder_clamped_and_counted():
    """Satellite: attributed stages exceeding the wall total clamp the
    unattributed remainder to 0 (never negative) and count the clamp in
    pio_flight_negative_remainder_total."""
    counter = metrics.REGISTRY.get("pio_flight_negative_remainder_total")
    before = counter.labels().value
    rec = FlightRecorder(capacity=4)
    key = rec.begin("neg1", "S", "POST", "/q")
    rec.note_stage("dispatch", 10.0, trace_id="neg1")  # 10s >> wall time
    record = rec.finish(key, 200)
    assert record["stages"]["unattributed"] == 0.0
    assert counter.labels().value == before + 1
    # tail attribution over such records stays non-negative
    report = tail_report([record] * 6, q=0.5)
    for s in report["stages"].values():
        assert s["tail_share"] >= 0.0


# ---------------------------------------------------------------------------
# timeline ring
# ---------------------------------------------------------------------------

def test_timeline_ring_eviction_and_capacity():
    t = Timeline(interval=0.0, capacity=3,
                 collectors=[lambda now: {"x": now}])
    for i in range(5):
        assert t.sample(now=float(i), force=True)
    points = t.series()["series"]["x"]
    assert [p[0] for p in points] == [2.0, 3.0, 4.0]  # oldest evicted


def test_timeline_cadence_rate_limits():
    t = Timeline(interval=100.0, capacity=8,
                 collectors=[lambda now: {"x": 1.0}])
    assert t.sample(now=1000.0)
    assert not t.sample(now=1050.0)        # inside the interval: no-op
    assert t.sample(now=1101.0)            # past it: sampled
    assert t.sample(now=1102.0, force=True)  # force bypasses the cadence
    assert len(t.series()["series"]["x"]) == 3


def test_timeline_env_cadence_read_per_sample(monkeypatch):
    t = Timeline(capacity=4, collectors=[lambda now: {"x": 1.0}])
    monkeypatch.setenv("PIO_TIMELINE_INTERVAL_SEC", "0")
    assert t.sample(now=1.0) and t.sample(now=1.1)
    monkeypatch.setenv("PIO_TIMELINE_INTERVAL_SEC", "3600")
    assert not t.sample(now=2.0)


def test_timeline_broken_collector_isolated():
    def boom(now):
        raise RuntimeError("broken probe")

    t = Timeline(interval=0.0, capacity=4,
                 collectors=[boom, lambda now: {"ok": 7.0}])
    assert t.sample(now=1.0, force=True)
    assert t.series()["series"]["ok"] == [[1.0, 7.0]]


def test_default_collectors_pick_up_mfu_and_staleness():
    StepAccountant("twotower", 1e9).observe(0.01)
    perfacct.LEDGER.note_ingest()
    t = Timeline(interval=0.0, capacity=8)
    t.sample(force=True)
    series = t.series()["series"]
    assert "mfu.twotower" in series and series["mfu.twotower"][-1][1] > 0
    assert "staleness_sec" in series


def test_timeline_staleness_grows_between_notes():
    """The staleness collector ASKS the ledger at the sample instant:
    the series (and the gauge) must keep growing while events wait,
    not freeze at the last ingest note's value."""
    perfacct.LEDGER.note_ingest(ts=100.0)
    t = Timeline(interval=0.0, capacity=8)
    t.sample(now=110.0, force=True)
    t.sample(now=150.0, force=True)
    points = t.series()["series"]["staleness_sec"]
    assert points[0][1] == pytest.approx(10.0)
    assert points[1][1] == pytest.approx(50.0)
    # sampling also refreshed the passive gauge for /metrics scrapes
    assert metrics.REGISTRY.get("pio_model_staleness_seconds").labels(
    ).value == pytest.approx(50.0)


def test_sparkline_shapes():
    assert sparkline([]) == ""
    assert sparkline([5.0, 5.0, 5.0]) == "▁▁▁"      # flat != empty
    line = sparkline(list(range(100)), width=10)
    assert len(line) == 10
    assert line[-1] == "█" and line[0] != "█"


# ---------------------------------------------------------------------------
# live server: /admin/timeline + /admin/tail (+ auth matrix)
# ---------------------------------------------------------------------------

@pytest.fixture()
def dash_server(memory_storage):
    from predictionio_tpu.tools.dashboard import DashboardServer

    server = DashboardServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
    yield server
    server.stop()
    flight.RECORDER.clear()


def test_admin_timeline_collects_samples_at_test_cadence(
        dash_server, monkeypatch):
    """Acceptance: GET /admin/timeline returns >= 2 samples for a
    tracked gauge at the test cadence (interval 0 -> every read
    samples)."""
    monkeypatch.setenv("PIO_TIMELINE_INTERVAL_SEC", "0")
    StepAccountant("twotower", 1e9).observe(0.01)
    base = f"http://127.0.0.1:{dash_server.port}"
    for _ in range(2):
        status, _, body = http("GET", f"{base}/admin/timeline")
        assert status == 200
    payload = json.loads(body)
    assert len(payload["series"]["mfu.twotower"]) >= 2
    # the data-path ledger rides along
    assert "staleness_seconds" in payload["datapath"]


def test_admin_tail_serves_attribution(dash_server):
    # the requests driven here are themselves flight-recorded, so the
    # endpoint has real records to attribute
    base = f"http://127.0.0.1:{dash_server.port}"
    for _ in range(6):
        http("GET", f"{base}/healthz")          # not recorded (shared)
        http("GET", f"{base}/metrics")          # not recorded (shared)
        http("GET", f"{base}/")                 # recorded
    status, _, body = http("GET", f"{base}/admin/tail")
    assert status == 200
    report = json.loads(body)
    assert report["total_count"] >= 4
    for s in report["stages"].values():
        assert s["tail_share"] >= 0.0
    status, _, _ = http("GET", f"{base}/admin/tail?q=abc")
    assert status == 400


def test_admin_timeline_and_tail_auth_matrix(dash_server, monkeypatch):
    """PIO_ADMIN_TOKEN gates both new admin routes like every other
    /admin/* diagnostic; healthz/metrics stay open."""
    base = f"http://127.0.0.1:{dash_server.port}"
    monkeypatch.setenv("PIO_ADMIN_TOKEN", "s3cret")
    for route in ("/admin/timeline", "/admin/tail"):
        status, headers, _ = http("GET", base + route)
        assert status == 401
        assert headers.get("WWW-Authenticate") == "Bearer"
        status, _, _ = http("GET", base + route,
                            headers={"Authorization": "Bearer wrong"})
        assert status == 401
        status, _, _ = http("GET", base + route,
                            headers={"Authorization": "Bearer s3cret"})
        assert status == 200
    status, _, _ = http("GET", f"{base}/healthz")
    assert status == 200
    monkeypatch.delenv("PIO_ADMIN_TOKEN")
    status, _, _ = http("GET", f"{base}/admin/timeline")
    assert status == 200


def test_dashboard_timeline_panel_renders(dash_server):
    StepAccountant("twotower", 1e9).observe(0.01)
    base = f"http://127.0.0.1:{dash_server.port}"
    status, _, body = http("GET", f"{base}/timeline")
    assert status == 200
    assert "Metric timelines" in body and "Data-path ledger" in body


# ---------------------------------------------------------------------------
# pio top
# ---------------------------------------------------------------------------

def test_pio_top_once_json_shape(capsys, monkeypatch):
    monkeypatch.setenv("PIO_TIMELINE_INTERVAL_SEC", "0")
    StepAccountant("twotower", 1e9).observe(0.01)
    from predictionio_tpu.tools.cli import main

    assert main(["top", "--once", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert set(payload) >= {"interval_sec", "capacity", "series",
                            "datapath"}
    assert "mfu.twotower" in payload["series"]
    point = payload["series"]["mfu.twotower"][-1]
    assert isinstance(point, list) and len(point) == 2
    assert point[1] > 0


def test_pio_top_once_text_frame(capsys, monkeypatch):
    monkeypatch.setenv("PIO_TIMELINE_INTERVAL_SEC", "0")
    StepAccountant("twotower", 1e9).observe(0.01)
    perfacct.LEDGER.start_run("frame-run")
    perfacct.LEDGER.note_stage("train", 1.0)
    from predictionio_tpu.tools.cli import main

    assert main(["top", "--once"]) == 0
    out = capsys.readouterr().out
    assert "mfu.twotower" in out
    assert "model staleness" in out and "frame-run" in out


def test_pio_top_json_requires_once():
    from predictionio_tpu.tools.cli import main

    assert main(["top", "--json"]) == 1


# ---------------------------------------------------------------------------
# benchcmp: key.* metrics join the direction-aware gate set
# ---------------------------------------------------------------------------

def test_benchcmp_extracts_headline_key_block(tmp_path):
    from predictionio_tpu.tools import benchcmp

    doc = {"parsed": {"metric": "m", "value": 1.0,
                      "key": {"twotower_mfu": 0.042,
                              "serve_32_srv_p99_ms": 23.95,
                              "rmse_heldout": 0.427,
                              "detail_note": "not-a-number"}}}
    path = tmp_path / "BENCH_r09.json"
    path.write_text(json.dumps(doc))
    got = benchcmp.load_metrics(str(path))
    assert got["key.twotower_mfu"] == 0.042
    assert got["key.serve_32_srv_p99_ms"] == 23.95
    assert "key.detail_note" not in got
    # direction awareness: mfu regresses DOWN, p99/rmse regress UP
    assert not benchcmp.lower_is_better("key.twotower_mfu")
    assert benchcmp.lower_is_better("key.serve_32_srv_p99_ms")
    assert benchcmp.lower_is_better("key.rmse_heldout")


def test_benchcmp_flags_mfu_regression(tmp_path):
    import io

    from predictionio_tpu.tools import benchcmp

    for n, mfu_val in ((1, 0.10), (2, 0.04)):
        (tmp_path / f"BENCH_r0{n}.json").write_text(json.dumps(
            {"parsed": {"metric": "m", "value": 1.0,
                        "key": {"twotower_mfu": mfu_val}}}))
    out = io.StringIO()
    rc = benchcmp.run([str(tmp_path / "BENCH_r01.json"),
                       str(tmp_path / "BENCH_r02.json")],
                      tolerance_pct=10.0, out=out)
    assert rc == 1
    assert "key.twotower_mfu" in out.getvalue()
    assert "REGRESSION" in out.getvalue()
