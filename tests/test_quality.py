"""Model-quality observability (ROADMAP item D): drift gauges vs a
shadow retrain, the flight recorder's replay-payload capture, the
replay harness's answer differ, the canary verdict math, and the
drift-band breach auto-triggering the rolling /reload lane exactly
once per episode."""

import datetime as _dt
import json
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.storage import set_storage
from predictionio_tpu.obs import flight, metrics, quality

from tests.test_storage import make_storage
from tests.test_stream import _rate, _seed_world, _train_reco

UTC = _dt.timezone.utc


@pytest.fixture(autouse=True)
def _clean_quality_state():
    quality.STATE.clear()
    yield
    quality.STATE.clear()


class _FakeModel:
    """A bare factor model (the ShadowRef/drift contract surface)."""

    def __init__(self, n_users=24, n_items=40, rank=6, seed=0):
        rng = np.random.default_rng(seed)
        self.user_factors = rng.normal(size=(n_users, rank)).astype(
            np.float32)
        self.item_factors = rng.normal(size=(n_items, rank)).astype(
            np.float32)
        self.user_ids = {f"u{i}": i for i in range(n_users)}
        self.item_ids = {f"i{i}": i for i in range(n_items)}


# ---------------------------------------------------------------------------
# drift math
# ---------------------------------------------------------------------------

class TestDriftReport:
    def test_identical_model_has_no_drift(self):
        m = _FakeModel()
        shadow = quality.ShadowRef(m, "inst")
        report = quality.drift_report(m, shadow)
        assert report["recall_vs_retrain"] == 1.0
        assert report["rmse_drift"] == 0.0
        assert report["factor_drift"] == 0.0
        assert quality.breached_metrics(report) == []

    def test_shadow_is_a_snapshot_not_a_reference(self):
        # the live model mutates AFTER the snapshot: drift must measure
        # against the frozen tables, not follow the mutation
        m = _FakeModel()
        shadow = quality.ShadowRef(m, "inst")
        m.user_factors = m.user_factors * 3.0
        report = quality.drift_report(m, shadow)
        assert report["factor_drift"] > 0.5

    def test_corruption_breaches_every_metric(self):
        m = _FakeModel()
        shadow = quality.ShadowRef(m, "inst")
        m.user_factors = m.user_factors * 7.0 + 3.0
        m.item_factors = m.item_factors[:, ::-1].copy()
        report = quality.publish_drift(quality.drift_report(m, shadow))
        assert report["recall_vs_retrain"] < 0.9
        assert set(report["breached"]) == {
            "recall_vs_retrain", "rmse_drift", "factor_drift"}
        # the gauges carry the SAME numbers (one source of truth)
        assert metrics.REGISTRY.get(
            "pio_model_quality_recall_vs_retrain"
        ).value == report["recall_vs_retrain"]
        assert metrics.REGISTRY.get(
            "pio_model_quality_rmse_drift").value == report["rmse_drift"]
        # ...and the /admin/quality state holds the identical report
        assert quality.STATE.report()["drift"] == report

    def test_band_is_configurable(self, monkeypatch):
        report = {"recall_vs_retrain": 0.85, "rmse_drift": 0.05,
                  "factor_drift": 0.02}
        assert quality.breached_metrics(report, band=0.10) == [
            "recall_vs_retrain"]
        monkeypatch.setenv("PIO_QUALITY_DRIFT_BAND", "0.2")
        assert quality.breached_metrics(report) == []
        monkeypatch.setenv("PIO_QUALITY_DRIFT_BAND", "0.01")
        assert quality.breached_metrics(report) == [
            "recall_vs_retrain", "rmse_drift", "factor_drift"]

    def test_disjoint_vocab_yields_no_verdict(self):
        a, b = _FakeModel(seed=1), _FakeModel(seed=2)
        b.user_ids = {f"x{i}": i for i in range(24)}
        report = quality.drift_report(b, quality.ShadowRef(a, "inst"))
        assert report["recall_vs_retrain"] is None
        assert quality.breached_metrics(report) == []


# ---------------------------------------------------------------------------
# answer differ (replay + canary shared currency)
# ---------------------------------------------------------------------------

class TestCompareAnswers:
    def test_ranked_overlap_and_score_delta(self):
        base = {"itemScores": [{"item": "a", "score": 1.0},
                               {"item": "b", "score": 0.8},
                               {"item": "c", "score": 0.6}]}
        cand = {"itemScores": [{"item": "a", "score": 1.1},
                               {"item": "c", "score": 0.7},
                               {"item": "d", "score": 0.5}]}
        diff = quality.compare_answers(base, cand, k=3)
        assert diff["overlap"] == pytest.approx(2 / 3, abs=1e-4)
        assert diff["score_delta"] == pytest.approx(0.1, abs=1e-6)

    def test_identical_ranked_answers(self):
        a = {"itemScores": [{"item": "x", "score": 2.0}]}
        assert quality.compare_answers(a, a) == {
            "overlap": 1.0, "score_delta": 0.0}

    def test_scalar_answers_compare_by_value(self):
        assert quality.compare_answers(
            {"result": 6.0}, {"result": 6.0})["overlap"] == 1.0
        diff = quality.compare_answers({"result": 6.0}, {"result": 8.0})
        assert diff["overlap"] == 0.0
        assert diff["score_delta"] == pytest.approx(2.0)

    def test_empty_baseline_cannot_be_missed(self):
        assert quality.compare_answers(
            {"itemScores": []},
            {"itemScores": [{"item": "a", "score": 1.0}]})["overlap"] == 1.0


# ---------------------------------------------------------------------------
# canary verdict math
# ---------------------------------------------------------------------------

def _observe_lane(lane, seconds, n):
    child = quality.CANARY_SECONDS.labels(lane)
    for _ in range(n):
        child.observe(seconds)


class TestCanaryVerdict:
    @pytest.fixture(autouse=True)
    def _fresh_lanes(self):
        quality.STATE.canary_begin("r9", "base", "cand")  # resets lanes
        yield
        quality.STATE.canary_end("test_done", None)

    def test_undecided_until_min_pairs(self, monkeypatch):
        monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "5")
        _observe_lane("baseline", 0.01, 10)
        _observe_lane("canary", 0.01, 10)
        for _ in range(3):
            quality.STATE.add_paired({"overlap": 1.0, "score_delta": 0.0})
        assert quality.STATE.canary_verdict()["verdict"] == "undecided"

    def test_clean_candidate_promotes(self, monkeypatch):
        monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "5")
        _observe_lane("baseline", 0.01, 20)
        _observe_lane("canary", 0.012, 20)
        for _ in range(8):
            quality.STATE.add_paired({"overlap": 0.9, "score_delta": 0.01})
        verdict = quality.STATE.canary_verdict()
        assert verdict["verdict"] == "promote", verdict

    def test_low_overlap_rolls_back(self, monkeypatch):
        monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "5")
        _observe_lane("baseline", 0.01, 20)
        _observe_lane("canary", 0.01, 20)
        for _ in range(8):
            quality.STATE.add_paired({"overlap": 0.1, "score_delta": 2.0})
        verdict = quality.STATE.canary_verdict()
        assert verdict["verdict"] == "rollback"
        assert any("quality" in r for r in verdict["reasons"])

    def test_latency_burn_rolls_back_via_slo_math(self, monkeypatch):
        # canary answers blow the serving-latency threshold while the
        # baseline stays clean: the latency gate (the same bucket→burn
        # math obs/slo.py uses) must fail the candidate even though
        # every paired ANSWER matches perfectly
        monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "5")
        monkeypatch.setenv("PIO_SLO_LATENCY_MS", "100")
        _observe_lane("baseline", 0.01, 40)
        _observe_lane("canary", 0.5, 40)
        for _ in range(8):
            quality.STATE.add_paired({"overlap": 1.0, "score_delta": 0.0})
        verdict = quality.STATE.canary_verdict()
        assert verdict["verdict"] == "rollback"
        assert any("latency" in r for r in verdict["reasons"])
        assert verdict["latency"]["canary"]["over_threshold_rate"] == 1.0
        assert verdict["latency"]["baseline"]["over_threshold_rate"] == 0.0

    def test_burning_baseline_does_not_blame_the_canary(self, monkeypatch):
        # shared pain: both lanes equally slow — the RELATIVE gate must
        # not roll back a candidate for the fleet's pre-existing burn
        monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "5")
        monkeypatch.setenv("PIO_SLO_LATENCY_MS", "100")
        _observe_lane("baseline", 0.5, 40)
        _observe_lane("canary", 0.5, 40)
        for _ in range(8):
            quality.STATE.add_paired({"overlap": 1.0, "score_delta": 0.0})
        assert quality.STATE.canary_verdict()["verdict"] == "promote"

    def test_paired_errors_roll_back(self, monkeypatch):
        monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "5")
        _observe_lane("baseline", 0.01, 20)
        _observe_lane("canary", 0.01, 20)
        for _ in range(6):
            quality.STATE.add_paired({"overlap": 1.0, "score_delta": 0.0})
        for _ in range(4):
            quality.STATE.add_paired(None, error="canary answered 500")
        assert quality.STATE.canary_verdict()["verdict"] == "rollback"


# ---------------------------------------------------------------------------
# flight recorder payload capture
# ---------------------------------------------------------------------------

class TestPayloadCapture:
    def test_capture_off_by_default(self):
        rec = flight.FlightRecorder(capacity=8)
        assert not rec.record_payload("/queries.json", {"user": "u"})
        assert rec.payloads() == []
        dump = rec.dump()
        assert "payloads" not in dump
        assert dump["payload_capture"] == {
            "capacity": 0, "captured": 0, "included": False}

    def test_bounded_capture_and_byte_cap(self, monkeypatch):
        monkeypatch.setenv("PIO_FLIGHT_PAYLOADS", "3")
        monkeypatch.setenv("PIO_FLIGHT_PAYLOAD_BYTES", "64")
        rec = flight.FlightRecorder(capacity=8)
        for k in range(5):
            assert rec.record_payload("/queries.json", {"user": f"u{k}"})
        # count cap: only the newest 3 stay
        got = [p["payload"]["user"] for p in rec.payloads()]
        assert got == ["u2", "u3", "u4"]
        # oversized payload skipped + counted
        skipped = metrics.REGISTRY.get(
            "pio_flight_payloads_skipped_total").value
        assert not rec.record_payload("/queries.json",
                                      {"blob": "x" * 500})
        assert metrics.REGISTRY.get(
            "pio_flight_payloads_skipped_total").value == skipped + 1
        # dump carries bodies only when explicitly included
        assert "payloads" not in rec.dump()
        dump = rec.dump(include_payloads=True)
        assert [p["payload"]["user"] for p in dump["payloads"]] == got

    def test_admin_flight_redacts_without_token(self, memory_storage,
                                                monkeypatch):
        from predictionio_tpu.serving.engine_server import EngineServer
        from tests.test_health import get_json, train_const

        monkeypatch.setenv("PIO_FLIGHT_PAYLOADS", "8")
        flight.RECORDER.clear()
        engine, _ = train_const(memory_storage)
        server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                              storage=memory_storage).start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            req = urllib.request.Request(
                base + "/queries.json", data=b'{"mult": 2}',
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
            # no token configured: counts only, bodies redacted
            status, dump = get_json(base + "/admin/flight")
            assert status == 200
            assert dump["payload_capture"]["captured"] == 1
            assert not dump["payload_capture"]["included"]
            assert "payloads" not in dump
            # token configured AND presented: the bodies travel
            monkeypatch.setenv("PIO_ADMIN_TOKEN", "s3cret")
            status, dump = get_json(
                base + "/admin/flight",
                headers={"Authorization": "Bearer s3cret"})
            assert status == 200
            assert dump["payloads"][0]["payload"] == {"mult": 2}
        finally:
            server.stop()
            flight.RECORDER.clear()


# ---------------------------------------------------------------------------
# replay harness
# ---------------------------------------------------------------------------

class TestReplayHarness:
    def test_replay_diffs_and_registers(self):
        from predictionio_tpu.workflow import replay as replay_mod

        def baseline(payload):
            return {"itemScores": [{"item": "a", "score": 1.0},
                                   {"item": "b", "score": 0.5}]}, 0.001

        def candidate(payload):
            if payload.get("user") == "drifted":
                return {"itemScores": [{"item": "z", "score": 9.0},
                                       {"item": "y", "score": 8.0}]}, 0.002
            return {"itemScores": [{"item": "a", "score": 1.0},
                                   {"item": "b", "score": 0.5}]}, 0.002

        payloads = [{"payload": {"user": "ok1"}},
                    {"payload": {"user": "ok2"}},
                    {"payload": {"user": "drifted"}}]
        report = replay_mod.replay(payloads, candidate, baseline, k=2)
        assert report["n"] == 3 and report["diffed"] == 3
        assert report["mean_overlap"] == pytest.approx(2 / 3, abs=1e-4)
        assert report["worst_overlap"] == 0.0
        assert report["latency_ms"]["baseline"]["p50_ms"] > 0
        # registered as THE replay report /admin/quality serves
        assert quality.STATE.report()["replay"]["n"] == 3
        # per-query examples carry the diff
        drifted = [q for q in report["queries"]
                   if q["payload"]["user"] == "drifted"]
        assert drifted[0]["overlap"] == 0.0

    def test_lane_errors_are_counted_not_raised(self):
        from predictionio_tpu.workflow import replay as replay_mod

        def baseline(payload):
            return {"result": 1.0}, 0.001

        def flaky(payload):
            raise ConnectionError("candidate down")

        report = replay_mod.replay([{"payload": {}}] * 3, flaky, baseline,
                                   register=False)
        assert report["errors"] == {"baseline": 0, "candidate": 3}
        assert report["diffed"] == 0 and report["mean_overlap"] is None

    def test_end_to_end_over_live_servers(self, memory_storage,
                                          monkeypatch):
        """Capture real payloads through a live engine server, replay
        them server-vs-server, and read the report back off
        GET /admin/quality — the whole harness in one pass."""
        from predictionio_tpu.serving.engine_server import EngineServer
        from predictionio_tpu.workflow import replay as replay_mod
        from tests.test_health import get_json, train_const

        monkeypatch.setenv("PIO_FLIGHT_PAYLOADS", "16")
        monkeypatch.setenv("PIO_ADMIN_TOKEN", "tok")
        flight.RECORDER.clear()
        engine, _ = train_const(memory_storage)
        server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                              storage=memory_storage).start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            for mult in (2, 3, 4):
                req = urllib.request.Request(
                    base + "/queries.json",
                    data=json.dumps({"mult": mult}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as resp:
                    resp.read()
            report = replay_mod.replay_urls(base, base)
            assert report["n"] == 3
            assert report["mean_overlap"] == 1.0
            assert report["errors"] == {"baseline": 0, "candidate": 0}
            status, served = get_json(
                base + "/admin/quality",
                headers={"Authorization": "Bearer tok"})
            assert status == 200
            assert served["replay"]["mean_overlap"] == 1.0
        finally:
            server.stop()
            flight.RECORDER.clear()

    def test_fetch_payloads_explains_redaction(self, memory_storage,
                                               monkeypatch):
        from predictionio_tpu.serving.engine_server import EngineServer
        from predictionio_tpu.workflow import replay as replay_mod
        from tests.test_health import train_const

        monkeypatch.delenv("PIO_ADMIN_TOKEN", raising=False)
        engine, _ = train_const(memory_storage)
        server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                              storage=memory_storage).start()
        try:
            with pytest.raises(RuntimeError, match="PIO_ADMIN_TOKEN"):
                replay_mod.fetch_payloads(
                    f"http://127.0.0.1:{server.port}")
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# drift → rolling-reload trigger (e2e on the real fold lane)
# ---------------------------------------------------------------------------

class TestDriftReloadTrigger:
    @pytest.fixture()
    def world(self, tmp_path):
        storage = make_storage("eventlog", tmp_path)
        set_storage(storage)
        app = storage.apps().insert("stream")
        storage.events().init(app.id)
        _seed_world(storage, app.id)
        yield storage, app.id
        set_storage(None)

    def test_breach_fires_reload_exactly_once(self, world, monkeypatch):
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        monkeypatch.setenv("PIO_QUALITY_EVERY", "1")
        engine, instance = _train_reco(storage, engine_id="drift_rl",
                                       iterations=4)
        fired = []
        updater = StreamUpdater(engine, "drift_rl", storage=storage,
                                instance=instance,
                                reload_trigger=lambda: fired.append(1))
        reloads_before = metrics.REGISTRY.get(
            "pio_quality_reloads_total").value

        # healthy fold: probe runs (cadence 1) and stays inside band
        storage.events().insert_batch([_rate("q_u0", "i1", 4.0)], app_id)
        stats = updater.poll_once()
        assert stats["published"]
        assert stats["quality"]["breached"] == []
        assert not fired

        # corrupt the streamed model (what a buggy fold lane would do)
        folder = updater._folders[0]
        folder.model.user_factors = folder.model.user_factors * 9.0 + 2.0
        report = updater.probe_quality()
        assert report["breached"], report
        assert len(fired) == 1
        assert metrics.REGISTRY.get(
            "pio_quality_reloads_total").value == reloads_before + 1
        # the breach auto-resynced the updater onto the bound instance:
        # its model matches the shadow again
        assert updater.probe_quality()["breached"] == []

        # SAME instance, drift again: the latch holds — no reload storm
        # while the retrain is in flight
        folder = updater._folders[0]
        folder.model.user_factors = folder.model.user_factors * 9.0 + 2.0
        report = updater.probe_quality()
        assert report["breached"]
        assert len(fired) == 1, "second breach in the episode re-fired"

        # a NEW trained instance re-arms the trigger
        _train_reco(storage, engine_id="drift_rl", iterations=4)
        updater.resync()
        folder = updater._folders[0]
        folder.model.user_factors = folder.model.user_factors * 9.0 + 2.0
        assert updater.probe_quality()["breached"]
        assert len(fired) == 2

    def test_breach_reloads_live_server_over_http(self, world,
                                                  monkeypatch):
        """The default HTTP trigger: a breach GETs /reload on the
        configured URL — the serving side rolls back to the last full
        retrain while the streamer resyncs."""
        from predictionio_tpu.serving.engine_server import EngineServer
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage, app_id = world
        monkeypatch.setenv("PIO_QUALITY_EVERY", "1")
        engine, instance = _train_reco(storage, engine_id="drift_http",
                                       iterations=4)
        server = EngineServer(engine, "drift_http", host="127.0.0.1",
                              port=0, storage=storage).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            updater = StreamUpdater(engine, "drift_http", storage=storage,
                                    instance=instance,
                                    patch_servers=[server],
                                    reload_urls=[url])
            reloads = []
            orig_reload = server.reload
            server.reload = lambda *a, **k: (reloads.append(a),
                                             orig_reload(*a, **k))[1]
            folder = updater._folders[0]
            folder.model.user_factors = folder.model.user_factors * 9.0
            report = updater.probe_quality()
            assert report["breached"]
            # the server's /reload lane ran exactly once, rolling it
            # back onto the last full retrain (same instance id — the
            # rollback IS the point)
            assert len(reloads) == 1
            assert server.deployment.instance.id == instance.id
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# drift probe through the real fold lane (fold stays inside the band)
# ---------------------------------------------------------------------------

class TestFoldQualityProbe:
    def test_honest_folds_stay_inside_band(self, tmp_path, monkeypatch):
        from predictionio_tpu.workflow.stream import StreamUpdater

        storage = make_storage("eventlog", tmp_path)
        set_storage(storage)
        try:
            app = storage.apps().insert("stream")
            storage.events().init(app.id)
            _seed_world(storage, app.id)
            monkeypatch.setenv("PIO_QUALITY_EVERY", "1")
            engine, instance = _train_reco(storage, engine_id="drift_ok")
            updater = StreamUpdater(engine, "drift_ok", storage=storage,
                                    instance=instance)
            rng = np.random.default_rng(4)
            delta = [_rate(f"u{int(rng.integers(0, 40))}",
                           f"i{int(rng.integers(0, 25))}",
                           float(rng.integers(2, 11)) / 2.0)
                     for _ in range(40)]
            storage.events().insert_batch(delta, app.id)
            stats = updater.poll_once()
            assert stats["published"]
            q = stats["quality"]
            # real fold-in moves factors a little, never outside band
            assert q["breached"] == []
            assert q["recall_vs_retrain"] > 0.9
            assert q["rmse_drift"] < 0.1
        finally:
            set_storage(None)


# ---------------------------------------------------------------------------
# bench-compare: quality keys are direction-aware
# ---------------------------------------------------------------------------

class TestQualityBenchKeys:
    @staticmethod
    def _round(tmp_path, name, recall, verdict_ms):
        doc = {"parsed": {
            "metric": "als_ml20m_rating_updates_per_sec_per_chip",
            "value": 6.0e7,
            "key": {"quality_recall_vs_retrain": recall,
                    "canary_verdict_ms": verdict_ms}}}
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_direction_inference(self):
        from predictionio_tpu.tools import benchcmp

        assert not benchcmp.lower_is_better("key.quality_recall_vs_retrain")
        assert benchcmp.lower_is_better("key.canary_verdict_ms")
        assert benchcmp.lower_is_better("key.quality_rmse_drift")
        assert not benchcmp.lower_is_better("key.replay_mean_overlap")

    def test_quality_regression_exits_1(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 0.99, 2.0),
                 self._round(tmp_path, "BENCH_r02.json", 0.70, 2.0)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 1
        out = capsys.readouterr().out
        assert "key.quality_recall_vs_retrain" in out
        assert "REGRESSION" in out

    def test_verdict_cost_regression_exits_1(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 0.99, 2.0),
                 self._round(tmp_path, "BENCH_r02.json", 0.99, 9.0)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 1
        assert "key.canary_verdict_ms" in capsys.readouterr().out

    def test_improvement_passes(self, tmp_path, capsys):
        from predictionio_tpu.tools import benchcmp

        files = [self._round(tmp_path, "BENCH_r01.json", 0.80, 9.0),
                 self._round(tmp_path, "BENCH_r02.json", 0.99, 2.0)]
        assert benchcmp.run(files, tolerance_pct=10.0) == 0
        assert "IMPROVED" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# dashboard /quality panel
# ---------------------------------------------------------------------------

class TestDashboardQualityPanel:
    def test_panel_renders_the_one_state(self, memory_storage):
        from predictionio_tpu.tools.dashboard import DashboardServer
        from tests.test_health import get

        dash = DashboardServer(storage=memory_storage, host="127.0.0.1",
                               port=0).start()
        base = f"http://127.0.0.1:{dash.port}"
        try:
            status, body, _ = get(base + "/quality")
            assert status == 200
            assert "no drift probe yet" in body
            assert "no replay report yet" in body
            quality.publish_drift(
                {"recall_vs_retrain": 0.8, "rmse_drift": 0.5,
                 "factor_drift": 0.01, "shadow_instance": "shadow_y",
                 "sampled_users": 4})
            quality.STATE.set_replay(
                {"n": 7, "diffed": 7, "mean_overlap": 0.93,
                 "worst_overlap": 0.5, "mean_score_delta": 0.01,
                 "errors": {"baseline": 0, "candidate": 0}})
            status, body, _ = get(base + "/quality")
            assert status == 200
            assert "BREACHED" in body and "rmse_drift" in body
            assert "0.93" in body
            status, body, _ = get(base + "/")
            assert 'href="/quality"' in body
        finally:
            dash.stop()


# -- review regressions --------------------------------------------------------

def test_all_error_candidate_reaches_rollback(monkeypatch):
    """A candidate that 500s EVERY request produces only pair errors
    and zero canary-lane answers — it must reach the rollback verdict,
    not hide behind "insufficient data" forever."""
    monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "5")
    quality.STATE.canary_begin("r1", "base", "cand")
    _observe_lane("baseline", 0.01, 10)
    for _ in range(6):
        quality.STATE.add_paired(None, error="canary answered 500")
    verdict = quality.STATE.canary_verdict()
    assert verdict["verdict"] == "rollback"
    assert any("paired canary errors" in r for r in verdict["reasons"])
    quality.STATE.canary_end("test_done", None)


def test_admin_quality_report_strips_replayed_payloads():
    """The replay report's per-query examples carry RAW captured
    payloads (user data): /admin/quality serves aggregates only, same
    contract as /admin/flight's redaction."""
    from predictionio_tpu.workflow import replay as replay_mod

    def target(payload):
        return {"result": 1.0}, 0.001

    report = replay_mod.replay(
        [{"payload": {"user": "secret-u", "ssn-ish": "data"}}],
        target, target)
    assert report["queries"], "the CLI-side report keeps the examples"
    served = quality.STATE.report()["replay"]
    assert "queries" not in served
    assert served["n"] == 1 and served["mean_overlap"] == 1.0
    assert "secret-u" not in json.dumps(quality.STATE.report())
