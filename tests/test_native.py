"""Native-library loader: process-wide cache semantics
(predictionio_tpu/native/__init__.py)."""

import threading

from predictionio_tpu import native


def test_load_library_concurrent_first_callers_share_one_handle(monkeypatch):
    """Regression (graftlint JT20): two threads racing through
    load_library()'s first miss must converge on ONE canonical handle.
    The old second lock region blindly overwrote the cache, so the
    early caller kept a handle the cache no longer knew — per-handle
    state (restype/argtypes set once) split across two live CDLLs."""
    barrier = threading.Barrier(2)
    made = []

    class FakeCDLL:
        def __init__(self, path):
            self.path = path
            made.append(self)

    def fake_build(name, extra_flags=None):
        # both threads are past the cache check before either dlopens:
        # the widest possible race window, deterministically
        barrier.wait(timeout=5)
        return f"/tmp/fake-{name}.so"

    monkeypatch.setattr(native, "build_library", fake_build)
    monkeypatch.setattr(native.ctypes, "CDLL", FakeCDLL)
    name = "t_cache_race"
    native._cache.pop(name, None)
    out = []
    threads = [
        threading.Thread(target=lambda: out.append(native.load_library(name)))
        for _ in range(2)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        assert len(out) == 2
        assert len(made) == 2  # both threads really did dlopen
        assert out[0] is out[1], "callers got different handles"
        assert native._cache[name] is out[0]
    finally:
        native._cache.pop(name, None)


def test_load_library_hits_cache_without_rebuild(monkeypatch):
    calls = []

    class FakeCDLL:
        def __init__(self, path):
            self.path = path

    monkeypatch.setattr(
        native, "build_library",
        lambda name, extra_flags=None: calls.append(name) or "/tmp/x.so")
    monkeypatch.setattr(native.ctypes, "CDLL", FakeCDLL)
    name = "t_cache_hit"
    native._cache.pop(name, None)
    try:
        first = native.load_library(name)
        second = native.load_library(name)
        assert first is second
        assert calls == [name]  # second call never re-built
    finally:
        native._cache.pop(name, None)
