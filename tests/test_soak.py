"""Hours-shaped behavior in minutes form (VERDICT r4 item 8): the
event server, engine server and storage server under CONTINUOUS mixed
load — ingest + queries + reads + periodic hot /reload + scan spools —
asserting what only time surfaces: flat RSS (no leak), the scan-spool
TTL reaper actually firing, and zero 5xx across the whole run.

The burst/stress tests elsewhere cover correctness under contention;
this one covers RESOURCE behavior under sustained duty. Marked slow:
~2-3 minutes of wall clock by design.
"""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.metadata import AccessKey
from predictionio_tpu.serving.event_server import EventServer


def _rss_anon_kb() -> int:
    """Anonymous (heap) RSS: excludes file-backed pages, because the
    ingest legitimately grows the mmap'd event log all soak long —
    log-file pages in the page cache are data, not a leak.

    ``RssAnon:`` only exists on Linux >= 4.5. On older kernels the only
    per-process RSS in /proc is ``VmRSS:``, which COUNTS the growing
    mmap'd log's resident pages — a flat-RSS assertion over it would
    flag legitimate data growth as a leak — so the test skips there
    with the reason instead of failing on a probe the kernel cannot
    answer (it failed at seed on pre-4.5 containers)."""
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("RssAnon:"):
                return int(line.split()[1])
    pytest.skip(
        "kernel /proc/self/status lacks RssAnon: (Linux < 4.5); VmRSS "
        "would count the mmap'd event log's resident pages as a leak, "
        "so the flat-RSS soak assertion cannot run here")


def _post(url, body, ok=(200, 201)):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        assert e.code < 500, (e.code, e.read()[:300])
        return e.code, b""


@pytest.mark.slow
def test_soak_servers_flat_rss_zero_5xx(tmp_path):
    """~2 minutes of continuous mixed duty against real servers over a
    real eventlog store; RSS sampled each cycle must stay flat."""
    _rss_anon_kb()  # probe EARLY: pre-4.5 kernels skip before any
    #                 server spins up, not two minutes into the soak
    import threading

    from predictionio_tpu.core import Engine
    from predictionio_tpu.core.params import EngineParams
    from predictionio_tpu.serving.engine_server import EngineServer
    from predictionio_tpu.serving.storage_server import StorageServer
    from predictionio_tpu.workflow.train import run_train
    from tests.test_servers import (
        ConstAlgo,
        ConstDataSource,
        ConstParams,
        FirstServing,
        IdentityPreparator,
    )
    from tests.test_storage import make_storage

    storage = make_storage("eventlog", tmp_path)
    app = storage.apps().insert("soak")
    key = AccessKey.generate(app.id)
    storage.access_keys().insert(key)
    storage.events().init(app.id)

    ev_srv = EventServer(storage=storage, host="127.0.0.1", port=0).start()
    # short-TTL storage server so the spool reaper provably fires
    # within the soak window
    st_srv = StorageServer(storage=storage, host="127.0.0.1", port=0,
                           scan_ttl=5.0).start()

    engine = Engine(ConstDataSource, IdentityPreparator,
                    {"c": ConstAlgo}, FirstServing)
    ep = EngineParams(
        data_source_params=("", ConstParams(value=1.0)),
        preparator_params=("", None),
        algorithm_params_list=[("c", ConstParams(value=2.0))],
        serving_params=("", None),
    )
    run_train(engine, ep, engine_id="soak", storage=storage)
    en_srv = EngineServer(engine, "soak", host="127.0.0.1", port=0,
                          storage=storage).start()

    ev_base = f"http://127.0.0.1:{ev_srv.port}"
    en_base = f"http://127.0.0.1:{en_srv.port}"
    st_base = f"http://127.0.0.1:{st_srv.port}"
    qs = f"?accessKey={key.key}"

    duration = float(os.environ.get("PIO_SOAK_SECONDS", "120"))
    deadline = time.monotonic() + duration
    errors = []
    counts = {"ingest": 0, "query": 0, "read": 0, "reload": 0, "scan": 0}
    stop = threading.Event()

    def ingest_loop():
        k = 0
        while not stop.is_set():
            batch = json.dumps([
                {"event": "rate", "entityType": "user",
                 "entityId": f"u{(k + j) % 500}",
                 "targetEntityType": "item",
                 "targetEntityId": f"i{(k * 7 + j) % 200}",
                 "properties": {"rating": float(1 + (k + j) % 5)}}
                for j in range(50)
            ]).encode()
            s, _ = _post(f"{ev_base}/batch/events.json{qs}", batch)
            assert s in (200, 201), s
            counts["ingest"] += 50
            k += 50
            time.sleep(0.01)

    def query_loop():
        while not stop.is_set():
            s, body = _post(f"{en_base}/queries.json",
                            json.dumps({"mult": 2}).encode())
            assert s == 200 and b"result" in body, (s, body[:200])
            counts["query"] += 1
            time.sleep(0.005)

    def read_loop():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                        f"{ev_base}/events.json{qs}&limit=20") as r:
                    assert r.status == 200
                    r.read()
            except urllib.error.HTTPError as e:
                # empty result set is a 404 by reference parity
                # (EventAPI.scala:209); anything 5xx fails the soak
                assert e.code == 404, (e.code, e.read()[:200])
            counts["read"] += 1
            time.sleep(0.02)

    def guarded(fn):
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                stop.set()
        return run

    threads = [threading.Thread(target=guarded(f), daemon=True)
               for f in (ingest_loop, query_loop, read_loop)]
    for t in threads:
        t.start()

    rss_samples = []
    spool_reaped = False
    try:
        cycle = 0
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(5.0)
            cycle += 1
            # periodic hot reload (warm-before-swap path; GET route,
            # CreateServer.scala:592 parity)
            with urllib.request.urlopen(f"{en_base}/reload") as r:
                assert r.status == 200
                r.read()
            counts["reload"] += 1
            # open a columnar scan spool and DON'T fetch or release it:
            # the TTL reaper (5 s) must clean it up, not an explicit
            # close
            payload = json.dumps({"app_id": app.id, "channel_id": None,
                                  "event_names": ["rate"]}).encode()
            s, body = _post(f"{st_base}/storage/events/find_columnar",
                            payload)
            if s in (200, 201):
                counts["scan"] += 1
            with urllib.request.urlopen(f"{st_base}/storage/stats") as r:
                stats = json.loads(r.read())
            live = stats.get("live_scan_spools")
            if counts["scan"] >= 3 and live is not None and live < counts["scan"]:
                spool_reaped = True   # older spools were TTL-collected
            rss_samples.append(_rss_anon_kb())
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        en_srv.stop()
        st_srv.stop()
        ev_srv.stop()
        storage.events().close()

    assert not errors, errors[0]
    # real duty happened
    assert counts["ingest"] > 1000 and counts["query"] > 500, counts
    assert counts["reload"] >= 3
    # the TTL reaper fired (spools opened every cycle, TTL 5 s)
    assert spool_reaped, (counts, stats)
    # bounded heap: anonymous RSS may grow with the DATA the soak
    # itself ingests (in-process eventlog indexes are data-proportional
    # by design) but never faster — growth beyond ~3x the ingested
    # bytes (+25 MB allocator slack) means a leak (spooled scans,
    # request objects, reload leaving the old deployment alive)
    assert len(rss_samples) >= 6, rss_samples
    early = min(rss_samples[:3])
    tail = rss_samples[-1]
    ingested_kb = counts["ingest"] * 150 // 1024   # ~150 B/event
    allowed = early + 3 * ingested_kb + 25_000
    assert tail < allowed, (
        f"anon RSS grew {early} kB -> {tail} kB with only "
        f"~{ingested_kb} kB ingested (samples: {rss_samples})")
