"""Train workflow + deploy reload (ref: EngineWorkflowTest.scala +
EngineTest train-persistence matrix)."""

import pytest

from predictionio_tpu.core import Engine, EngineParams
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.workflow.config import WorkflowParams
from predictionio_tpu.workflow.deploy import engine_params_from_instance, prepare_deploy
from predictionio_tpu.workflow.train import run_train
from predictionio_tpu.workflow.variant import EngineVariant

from tests.sample_engine import (
    Algo0,
    AlgoPersistent,
    DataSource0,
    IdParams,
    Preparator0,
    Query,
    Serving0,
)


def make_engine():
    return Engine(
        data_source_classes={"ds": DataSource0},
        preparator_classes={"prep": Preparator0},
        algorithm_classes={"algo": Algo0, "persistent": AlgoPersistent},
        serving_classes={"serve": Serving0},
    )


def make_params(algos=("algo",)):
    return EngineParams(
        data_source_params=("ds", IdParams(id=1)),
        preparator_params=("prep", IdParams(id=2)),
        algorithm_params_list=[(a, IdParams(id=3 + i)) for i, a in enumerate(algos)],
        serving_params=("serve", IdParams(id=9)),
    )


ctx = MeshContext()


def test_run_train_persists_instance_and_model(memory_storage):
    engine = make_engine()
    instance = run_train(
        engine, make_params(), engine_id="myengine", storage=memory_storage
    )
    assert instance.status == "COMPLETED"
    stored = memory_storage.engine_instances().get(instance.id)
    assert stored.status == "COMPLETED"
    assert memory_storage.models().get(instance.id) is not None
    # params snapshot recorded (ref: CreateWorkflow.scala:232-252)
    assert '"id": 1' in stored.data_source_params
    latest = memory_storage.engine_instances().get_latest_completed("myengine", "0", "default")
    assert latest.id == instance.id


def test_deploy_round_trip(memory_storage):
    engine = make_engine()
    instance = run_train(
        engine, make_params(algos=("algo", "algo")), engine_id="e", storage=memory_storage
    )
    deployment = prepare_deploy(engine, instance, ctx, memory_storage)
    # deployed pipeline reproduces training wiring end-to-end
    p = deployment.query(Query(q=42))
    assert p.q == 42
    assert p.algo_id == 3 + 4  # serving sums both algo ids
    # engine params were reconstructed from the instance snapshot
    ep = engine_params_from_instance(engine, instance)
    assert ep.data_source_params == ("ds", IdParams(id=1))
    assert [p.id for _, p in ep.algorithm_params_list] == [3, 4]


def test_persistent_model_path(memory_storage, tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
    engine = make_engine()
    instance = run_train(
        engine, make_params(algos=("persistent",)), engine_id="e", storage=memory_storage
    )
    # the Models repo holds a manifest, not the model itself
    import pickle

    blob = pickle.loads(memory_storage.models().get(instance.id).models)
    from predictionio_tpu.core.persistent_model import PersistentModelManifest

    assert isinstance(blob[0], PersistentModelManifest)
    # deploy reloads through the loader class
    deployment = prepare_deploy(engine, instance, ctx, memory_storage)
    assert deployment.query(Query(q=1)).algo_id == 3


def test_failed_training_marks_instance(memory_storage):
    engine = make_engine()
    ep = make_params()
    ep.data_source_params = ("ds", IdParams(id=1, fail_sanity=True))
    with pytest.raises(ValueError):
        run_train(engine, ep, engine_id="e", storage=memory_storage)
    instances = memory_storage.engine_instances().get_all()
    assert len(instances) == 1
    assert instances[0].status == "FAILED"
    assert memory_storage.engine_instances().get_latest_completed("e", "0", "default") is None


def test_stop_after_read_skips_model(memory_storage):
    engine = make_engine()
    instance = run_train(
        engine,
        make_params(),
        engine_id="e",
        storage=memory_storage,
        workflow_params=WorkflowParams(stop_after_read=True),
    )
    assert "stopped after read" in instance.batch
    assert memory_storage.models().get(instance.id) is None


def test_no_save_model(memory_storage):
    engine = make_engine()
    instance = run_train(
        engine,
        make_params(),
        engine_id="e",
        storage=memory_storage,
        workflow_params=WorkflowParams(save_model=False),
    )
    assert instance.status == "COMPLETED"
    assert memory_storage.models().get(instance.id) is None


def test_engine_variant_loading(tmp_path):
    import json

    variant_path = tmp_path / "engine.json"
    variant_path.write_text(
        json.dumps(
            {
                "id": "v1",
                "engineFactory": "tests.test_workflow.sample_factory",
                "datasource": {"name": "ds", "params": {"id": 5}},
                "algorithms": [{"name": "algo", "params": {"id": 6}}],
                "preparator": {"name": "prep", "params": {}},
                "serving": {"name": "serve", "params": {}},
                "runtimeConf": {"mesh.data": "8"},
            }
        )
    )
    variant = EngineVariant.load(str(variant_path))
    assert variant.id == "v1"
    engine = variant.create_engine()
    ep = variant.engine_params(engine)
    assert ep.data_source_params[1].id == 5
    assert variant.runtime_conf() == {"mesh.data": "8"}
    result = engine.train(ctx, ep)
    assert result.models[0].algo_id == 6


def sample_factory():
    """Engine factory resolved by dotted path (ref: WorkflowUtils.getEngine:60)."""
    return make_engine()


def test_profile_dir_captures_trace(memory_storage, tmp_path, monkeypatch):
    """PIO_PROFILE_DIR captures a JAX device trace per training instance
    (first-party training observability — the reference only has the
    Spark UI, SURVEY.md §5.1)."""
    monkeypatch.setenv("PIO_PROFILE_DIR", str(tmp_path / "prof"))
    engine = make_engine()
    instance = run_train(
        engine, make_params(), engine_id="prof", storage=memory_storage
    )
    assert instance.status == "COMPLETED"
    trace_root = tmp_path / "prof" / instance.id
    assert trace_root.is_dir()
    # the profiler wrote something (plugins/profile/<ts>/*)
    files = [p for p in trace_root.rglob("*") if p.is_file()]
    assert files, "no trace files captured"
