"""Regression engine template tests (ref: examples/experimental/
scala-parallel-regression/Run.scala behavior: file data source, SGD
linear regression, AverageServing fan-out, MeanSquareError eval)."""

import numpy as np
import pytest

from predictionio_tpu.core.evaluation import MeanSquareError
from predictionio_tpu.core.params import EngineParams
from predictionio_tpu.models.regression import (
    RegressionData,
    RidgeRegressionParams,
    SGDRegressionParams,
    train_ridge_regression,
    train_sgd_regression,
)
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.templates import regression as reg_t

ctx = MeshContext()

TRUE_W = np.array([2.0, -1.0, 0.5], dtype=np.float32)


def _make_points(n=120, seed=0, noise=0.01):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3)).astype(np.float32)
    y = x @ TRUE_W + noise * rng.normal(size=n).astype(np.float32)
    return x, y


@pytest.fixture()
def data_file(tmp_path):
    x, y = _make_points()
    path = tmp_path / "lr_data.txt"
    with open(path, "w") as f:
        for yi, xi in zip(y, x):
            f.write(f"{yi} " + " ".join(str(v) for v in xi) + "\n")
    return str(path)


def test_sgd_recovers_weights():
    x, y = _make_points()
    model = train_sgd_regression(
        RegressionData(x, y), SGDRegressionParams(iterations=400, step_size=0.2))
    np.testing.assert_allclose(model.weights, TRUE_W, atol=0.05)
    assert model.intercept == 0.0


def test_ridge_recovers_weights_one_shot():
    x, y = _make_points()
    model = train_ridge_regression(
        RegressionData(x, y), RidgeRegressionParams(reg=1e-6))
    np.testing.assert_allclose(model.weights, TRUE_W, atol=0.02)


def test_file_datasource_parses(data_file):
    ds = reg_t.FileRegressionDataSource(reg_t.RegressionDSParams(filepath=data_file))
    td = ds.read_training(ctx)
    assert td.features.shape == (120, 3)
    assert td.targets.shape == (120,)


def test_train_and_average_serving(data_file):
    engine = reg_t.regression_engine()
    ep = reg_t.default_engine_params(data_file, step_sizes=[0.1, 0.2, 0.4])
    result = engine.train(ctx, ep)
    assert len(result.models) == 3
    algos = engine.make_algorithms(ep)
    serving = engine.make_serving(ep)
    q = {"features": [1.0, 1.0, 1.0]}
    preds = [a.predict(m, q) for a, m in zip(algos, result.models)]
    combined = serving.serve(q, preds)
    # true value 1.5; the average of the three variants should be close
    assert combined == pytest.approx(sum(preds) / 3)
    assert combined == pytest.approx(1.5, abs=0.1)


def test_eval_mse(data_file):
    engine = reg_t.regression_engine()
    ep = reg_t.default_engine_params(data_file, eval_k=3, step_sizes=[0.2])
    results = engine.eval(ctx, ep)
    assert len(results) == 3
    mse = MeanSquareError().calculate(ctx, results)
    assert mse < 0.05
    assert MeanSquareError.higher_is_better is False


def test_ridge_collinear_features_no_nan():
    x, y = _make_points()
    x_dup = np.concatenate([x, x[:, :1]], axis=1)  # duplicated column
    model = train_ridge_regression(
        RegressionData(x_dup, y), RidgeRegressionParams(reg=1e-6))
    assert np.isfinite(model.weights).all()
    pred = model.predict_batch(x_dup)
    np.testing.assert_allclose(pred, y, atol=0.05)


def test_empty_data_file_reports_sanity_error(tmp_path):
    path = tmp_path / "empty.txt"
    path.write_text("\n")
    engine = reg_t.regression_engine()
    ep = reg_t.default_engine_params(str(path), step_sizes=[0.1])
    with pytest.raises(ValueError, match="no labeled points"):
        engine.train(ctx, ep)


def test_entity_ix_map_rejects_float_keys():
    from predictionio_tpu.data.bimap import EntityIdIxMap

    m = EntityIdIxMap.from_keys(["a", "b", "c"])
    with pytest.raises(TypeError):
        m(1.7)
    assert 1.7 not in m and None not in m
    assert m.get(1.7, "d") == "d" and m.get(None, "d") == "d"


def test_eval_with_empty_fold(data_file, tmp_path):
    """A fold whose test split is empty must not crash batch_predict."""
    path = tmp_path / "tiny.txt"
    x, y = _make_points(n=2)
    with open(path, "w") as f:
        for yi, xi in zip(y, x):
            f.write(f"{yi} " + " ".join(str(v) for v in xi) + "\n")
    engine = reg_t.regression_engine()
    ep = reg_t.default_engine_params(str(path), eval_k=3, step_sizes=[0.2])
    results = engine.eval(ctx, ep)
    assert len(results) == 3
    assert sum(len(qpa) for _ei, qpa in results) == 2


def test_ridge_does_not_shrink_intercept():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(200, 3)).astype(np.float32)
    y = (x @ TRUE_W + 100.0).astype(np.float32)  # large constant offset
    model = train_ridge_regression(
        RegressionData(x, y), RidgeRegressionParams(reg=10.0, intercept=True))
    assert model.intercept == pytest.approx(100.0, abs=1.0)
