"""Continuous profiling plane (obs/contprof.py): the bounded trie,
role/wait classification, refcounted sampler lifecycle across every
server kind and the stream daemon, overhead self-governance (synthetic
slow clock pins the auto-downshift; a real run pins the tier-1 cost
ceiling), the ``/admin/prof`` + fleet + CLI + dashboard surfaces, and
the acceptance e2e — a hedging 3-replica fleet under load whose
``?slow=1`` tail flame names trace ids the flight recorder's slow ring
also holds.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass

import pytest

from predictionio_tpu.obs import collect, contprof, flight, metrics, trace

from tests.test_health import get, get_json, train_const
from tests.test_fleet import post, running_fleet


@pytest.fixture(autouse=True)
def fresh_profiler():
    """Per-test isolation for the process-global profiler: drop leaked
    owners (a crashed test's server never released) and all samples."""
    p = contprof.PROFILER

    def scrub():
        for owner in p.owners():
            p.release(owner)
        p.reset()

    scrub()
    yield
    scrub()


def sampler_threads():
    return [t for t in threading.enumerate()
            if t.name == "pio-contprof" and t.is_alive()]


# ---------------------------------------------------------------------------
# bounded trie
# ---------------------------------------------------------------------------

def test_trie_folds_stacks_with_cpu_wait_split():
    t = contprof._Trie(budget=64)
    t.add(["[handler]", "a.py:f", "b.py:g"], waiting=False)
    t.add(["[handler]", "a.py:f", "b.py:g"], waiting=False)
    t.add(["[handler]", "a.py:f"], waiting=True)
    folded = t.folded()
    assert folded["[handler];a.py:f;b.py:g"] == {"cpu": 2, "wait": 0}
    assert folded["[handler];a.py:f"] == {"cpu": 0, "wait": 1}
    assert t.cpu == 2 and t.wait == 1
    assert t.stats()["evictions"] == 0


def test_trie_bounds_nodes_and_counts_evictions():
    budget = 32
    t = contprof._Trie(budget=budget)
    # synthetic deep stacks: 40 distinct 20-frame chains would need 800
    # nodes — the budget must hold and every sample still land
    for i in range(40):
        t.add([f"s{i}.py:f{d}" for d in range(20)], waiting=False)
    assert t.nodes <= budget + 1  # +1: the reserved overflow terminal
    assert t.evictions > 0
    # no sample is lost: overflow truncates at the deepest existing
    # node, and a stack matching nothing lands on "(evicted)"
    assert t.cpu == 40
    folded = t.folded()
    total = sum(c["cpu"] + c["wait"] for c in folded.values())
    assert total == 40
    assert "(evicted)" in folded


def test_endpoint_tries_fold_overflow_into_other(monkeypatch):
    monkeypatch.setenv("PIO_PROF_MAX_ENDPOINTS", "2")
    p = contprof.ContProfiler()
    with p._lock:
        for i in range(5):
            p._endpoint_trie(f"/route{i}").add(["x.py:f"], waiting=False)
    snap = p.snapshot()
    assert "(other)" in snap["endpoints"]
    assert len(snap["endpoints"]) <= 3  # 2 routes + the fold bucket


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_role_inference_name_then_frames():
    assert contprof._role_of("pio-batcher-r0", []) == "batcher"
    assert contprof._role_of("pio-watchdog:x", []) == "watchdog"
    assert contprof._role_of("pio-contprof", []) == "sampler"
    assert contprof._role_of("MainThread", []) == "main"
    assert contprof._role_of(
        "Thread-7", [("socketserver.py", "process_request_thread"),
                     ("http.py", "do_POST")]) == "handler"
    assert contprof._role_of(
        "Thread-3", [("engine_server.py", "_loop")]) == "batcher"
    assert contprof._role_of("Thread-9", [("x.py", "run")]) == "other"


def test_wait_classification_leaf_only():
    assert contprof._is_waiting([("a.py", "f"), ("threading.py", "wait")])
    assert contprof._is_waiting([("socket.py", "recv_into")])
    assert contprof._is_waiting([("selectors.py", "select")])
    # a threading.py leaf that is NOT a named wait is real CPU time
    assert not contprof._is_waiting([("threading.py", "is_set")])
    assert not contprof._is_waiting([("als.py", "solve")])
    # only the leaf decides: waiting deeper in the stack is history
    assert not contprof._is_waiting([("threading.py", "wait"),
                                     ("als.py", "solve")])


# ---------------------------------------------------------------------------
# sampler lifecycle: refcounted owners
# ---------------------------------------------------------------------------

def test_retain_release_refcount_controls_the_thread():
    p = contprof.ContProfiler()
    assert not p.running()
    p.retain("a")
    p.retain("b")
    assert p.running() and p.owners() == ["a", "b"]
    p.release("a")
    assert p.running()  # one owner still holds it
    p.release("b")
    assert not p.running() and p.owners() == []
    # restart after full drain works
    p.retain("c")
    assert p.running()
    p.release("c")
    assert not p.running()


def test_double_retain_never_starts_a_second_sampler():
    before = len(sampler_threads())
    p = contprof.ContProfiler()
    p.retain("server")
    first = p._thread
    p.retain("server")  # a /reload re-entering start()
    p.retain("another")
    assert p._thread is first  # same thread, not a second sampler
    assert len(sampler_threads()) == before + 1
    p.release("server")
    p.release("another")
    assert not p.running()


@pytest.mark.parametrize("kind", ["event", "storage", "dashboard",
                                  "engine"])
def test_server_start_stop_drives_profiler_lifecycle(
        kind, memory_storage):
    """Every HTTPServerBase main (event/storage/dashboard/engine — the
    router rides the same base class and is exercised in the e2e below)
    retains the sampler on start and releases it on stop; a double stop
    (drain_stop then stop) releases exactly once."""
    from predictionio_tpu.serving.event_server import EventServer
    from predictionio_tpu.serving.storage_server import StorageServer
    from predictionio_tpu.tools.dashboard import DashboardServer

    if kind == "event":
        server = EventServer(storage=memory_storage, host="127.0.0.1",
                             port=0)
    elif kind == "storage":
        server = StorageServer(storage=memory_storage, host="127.0.0.1",
                               port=0)
    elif kind == "dashboard":
        server = DashboardServer(storage=memory_storage,
                                 host="127.0.0.1", port=0)
    else:
        from predictionio_tpu.serving.engine_server import EngineServer

        engine, _ = train_const(memory_storage)
        server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                              storage=memory_storage)
    assert not contprof.PROFILER.running()
    server.start()
    try:
        assert contprof.PROFILER.running()
        assert len(sampler_threads()) == 1
        assert contprof.PROFILER.owners()  # this server holds it
    finally:
        server.stop()
    assert not contprof.PROFILER.running()
    assert contprof.PROFILER.owners() == []
    server.stop()  # drain_stop -> stop double-release is a no-op
    assert contprof.PROFILER.owners() == []


def test_two_servers_share_one_sampler(memory_storage):
    from predictionio_tpu.serving.event_server import EventServer
    from predictionio_tpu.serving.storage_server import StorageServer

    a = EventServer(storage=memory_storage, host="127.0.0.1",
                    port=0).start()
    b = StorageServer(storage=memory_storage, host="127.0.0.1",
                      port=0).start()
    try:
        assert len(sampler_threads()) == 1  # shared, not duplicated
        a.stop()
        assert contprof.PROFILER.running()  # b still holds it
    finally:
        b.stop()
    assert not contprof.PROFILER.running()


def test_stream_daemon_retains_and_releases_sampler():
    """``pio stream``'s run_forever holds the profiler for the daemon's
    lifetime — a PIO process like any server."""
    from predictionio_tpu.workflow.stream import StreamUpdater

    updater = object.__new__(StreamUpdater)  # the daemon loop only
    updater.poll_once = lambda: None         # touches poll_once
    stop = threading.Event()
    t = threading.Thread(
        target=updater.run_forever,
        kwargs={"interval": 0.01, "stop": stop}, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while (not contprof.PROFILER.running()
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert contprof.PROFILER.running()
        assert any(o.startswith("StreamUpdater:")
                   for o in contprof.PROFILER.owners())
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert not contprof.PROFILER.running()
    assert contprof.PROFILER.owners() == []


# ---------------------------------------------------------------------------
# overhead governance
# ---------------------------------------------------------------------------

class ScriptedClock:
    """perf_counter stand-in: every call advances a fixed step, so one
    _tick() measures a deterministic 'sampling cost'."""

    def __init__(self, step: float):
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        self.t += self.step
        return self.t


def test_overhead_downshift_converges_under_budget(monkeypatch):
    """ISSUE acceptance pin: with a synthetic slow clock making every
    sampling pass 'cost' ~4ms against a 40ms interval (10x the 1%
    budget), the governor halves the rate until the EMA fits under
    PIO_PROF_MAX_OVERHEAD — and never below the 1 Hz floor."""
    monkeypatch.setenv("PIO_PROF_HZ", "25")
    monkeypatch.setenv("PIO_PROF_MAX_OVERHEAD", "0.01")
    monkeypatch.setenv("PIO_PROF_WARMUP_TICKS", "0")
    before = metrics.REGISTRY.get("pio_prof_downshifts_total").value
    p = contprof.ContProfiler(clock=ScriptedClock(0.001))
    for _ in range(60):
        p._tick()
    assert p.effective_hz() < 25.0  # it DID downshift
    assert p.effective_hz() >= contprof.MIN_HZ
    assert p.overhead_ratio() <= contprof.max_overhead()
    after = metrics.REGISTRY.get("pio_prof_downshifts_total").value
    assert after > before
    # downshift-only by design: a later cheap pass does not raise it
    cheap = p.effective_hz()
    p._clock = p._cpu_clock = ScriptedClock(1e-9)
    p._tick()
    assert p.effective_hz() == cheap


def test_warmup_ticks_exempt_from_governance(monkeypatch):
    """The governor's grace period: over-budget passes during the first
    PIO_PROF_WARMUP_TICKS never downshift (import-heavy process start
    looks 10-100x steady-state cost), the warm-up EMA is DISCARDED at
    the boundary, and the re-seeded EMA averages EMA_SEED_TICKS passes
    before the first decision — one startup spike never parks the
    rate."""
    monkeypatch.setenv("PIO_PROF_HZ", "25")
    monkeypatch.setenv("PIO_PROF_MAX_OVERHEAD", "0.01")
    monkeypatch.setenv("PIO_PROF_WARMUP_TICKS", "10")
    p = contprof.ContProfiler(clock=ScriptedClock(0.01))  # 100x budget
    for _ in range(10):
        p._tick()
    assert p.effective_hz() == 25.0  # warm-up: no downshift despite cost
    # steady state turns cheap: the startup EMA must not linger and
    # force a downshift the current cost does not justify, even across
    # the whole seed window
    p._clock = p._cpu_clock = ScriptedClock(1e-6)
    for _ in range(contprof.EMA_SEED_TICKS + 2):
        p._tick()
    assert p.effective_hz() == 25.0
    assert p.overhead_ratio() <= contprof.max_overhead()
    # but a genuinely expensive steady state still governs post-warm-up
    p._clock = p._cpu_clock = ScriptedClock(0.01)
    for _ in range(contprof.EMA_SEED_TICKS + 2):
        p._tick()
    assert p.effective_hz() < 25.0


def test_hz_zero_disables_sampling_but_not_surfaces(monkeypatch):
    monkeypatch.setenv("PIO_PROF_HZ", "0")
    p = contprof.ContProfiler()
    assert p._tick() == 0.5  # idle poll, no sample
    snap = p.snapshot()
    assert snap["total_samples"] == 0
    assert snap["hz"] == 0.0


def test_real_sampler_overhead_under_5pct_at_default_rate():
    """Tier-1 cost ceiling: the real sampler at the default 25 Hz on a
    process with live threads must cost well under 5% of wall time.
    The worker mix mirrors a serving process — short compute bursts
    between waits (pure GIL-saturated spinners would starve the
    sampler's own pass and measure GIL queueing, not sampling cost)."""
    p = contprof.ContProfiler()
    stop = threading.Event()

    def work():
        while not stop.is_set():
            sum(i * i for i in range(200))
            stop.wait(0.002)

    workers = [threading.Thread(target=work, daemon=True)
               for _ in range(3)]
    for w in workers:
        w.start()
    p.retain("tier1")
    try:
        time.sleep(1.0)
        assert p.snapshot()["total_samples"] > 0
        assert p.overhead_ratio() < 0.05
    finally:
        stop.set()
        p.release("tier1")
        for w in workers:
            w.join(timeout=2.0)


def test_single_spike_costs_at_most_one_halving(monkeypatch):
    """Cascade guard: ONE expensive pass (a GC pause billed to the
    sampler thread) spikes the EMA for several ticks as it decays — the
    governor must not convert that one event into halving-per-tick down
    to the floor. A downshift discards the EMA and holds the next
    decision for EMA_SEED_TICKS, so the spike costs exactly one step."""
    monkeypatch.setenv("PIO_PROF_HZ", "25")
    monkeypatch.setenv("PIO_PROF_MAX_OVERHEAD", "0.01")
    monkeypatch.setenv("PIO_PROF_WARMUP_TICKS", "0")
    before = metrics.REGISTRY.get("pio_prof_downshifts_total").value
    cheap, spike = ScriptedClock(1e-7), ScriptedClock(0.01)
    p = contprof.ContProfiler(clock=cheap)
    for _ in range(contprof.EMA_SEED_TICKS + 1):
        p._tick()
    assert p.effective_hz() == 25.0
    p._clock = p._cpu_clock = spike
    p._tick()  # the one expensive pass
    p._clock = p._cpu_clock = cheap
    for _ in range(3 * contprof.EMA_SEED_TICKS):
        p._tick()
    assert p.effective_hz() == 12.5  # one halving, not a cascade
    after = metrics.REGISTRY.get("pio_prof_downshifts_total").value
    assert after - before == 1


def test_gil_contention_does_not_downshift(monkeypatch):
    """The governor meters CPU time, not wall time: pure-Python spinner
    threads hold the GIL so a sampling pass takes large WALL time
    waiting its turn, but the sampler's own CPU cost stays tiny — a
    loaded server must keep its full sampling rate (wall-based metering
    downshifted to the floor exactly under load)."""
    monkeypatch.setenv("PIO_PROF_HZ", "25")
    # a few warm-up ticks absorb the genuine first-pass cold cost; the
    # sustained spin period after them is what must stay ungoverned
    monkeypatch.setenv("PIO_PROF_WARMUP_TICKS", "5")
    p = contprof.ContProfiler()
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(i * i for i in range(5000))

    workers = [threading.Thread(target=spin, daemon=True)
               for _ in range(3)]
    for w in workers:
        w.start()
    before = metrics.REGISTRY.get("pio_prof_downshifts_total").value
    p.retain("gil")
    try:
        time.sleep(0.8)
        assert p.snapshot()["total_samples"] > 0
        assert p.effective_hz() == 25.0
        assert metrics.REGISTRY.get(
            "pio_prof_downshifts_total").value == before
    finally:
        stop.set()
        p.release("gil")
        for w in workers:
            w.join(timeout=2.0)


# ---------------------------------------------------------------------------
# per-request attribution
# ---------------------------------------------------------------------------

def test_request_attribution_endpoint_slow_and_dominant(monkeypatch):
    monkeypatch.setenv("PIO_SLOW_MS", "0")  # everything is tail
    p = contprof.ContProfiler()
    p.request_begin("trace-1", "/queries.json")
    for _ in range(5):
        p._sample_once()
    dominant = p.request_end()
    assert dominant is not None and ":" in dominant
    # this thread was sampled into the route's trie and the slow cohort
    by_route = p.snapshot(endpoint="/queries.json")
    assert by_route["samples"]["cpu"] + by_route["samples"]["wait"] >= 5
    slow = p.snapshot(slow=True)
    assert slow["slice"] == "slow"
    assert "trace-1" in slow["slow_trace_ids"]
    # after request_end the thread no longer attributes
    p._sample_once()
    assert p.snapshot(slow=True)["slow_trace_ids"] == ["trace-1"]


def test_fast_requests_stay_out_of_slow_cohort(monkeypatch):
    monkeypatch.setenv("PIO_SLOW_MS", "60000")
    p = contprof.ContProfiler()
    p.request_begin("trace-fast", "/x")
    p._sample_once()
    p.request_end()
    snap = p.snapshot(slow=True)
    assert snap["slow_trace_ids"] == []
    assert snap["samples"] == {"cpu": 0, "wait": 0}


# ---------------------------------------------------------------------------
# renderers
# ---------------------------------------------------------------------------

def _payload():
    return {
        "slice": "all", "hz": 25.0, "effective_hz": 25.0,
        "overhead_ratio": 0.004, "max_overhead": 0.01,
        "samples": {"cpu": 6, "wait": 4},
        "folded": {
            "[handler];server.py:read": {"cpu": 1, "wait": 0},
            "[handler];socket.py:recv_into": {"cpu": 0, "wait": 4},
            "[handler];decoder.py:decode": {"cpu": 2, "wait": 0},
            "[batcher];als.py:solve": {"cpu": 3, "wait": 0},
        },
    }


def test_collapsed_text_is_folded_flamegraph_form():
    text = contprof.collapsed_text(_payload())
    assert "[handler];socket.py:recv_into 4\n" in text
    assert "[batcher];als.py:solve 3\n" in text


def test_hot_frames_rank_by_self_time():
    hot = contprof.hot_frames(_payload(), n=2)
    assert hot[0]["frame"] == "socket.py:recv_into"
    assert hot[0]["total"] == 4 and hot[0]["wait"] == 4
    assert len(hot) == 2


def test_format_flame_tree_marks_waits_and_hot_frames():
    text = contprof.format_flame(_payload())
    assert "continuous profile [all]" in text
    assert "6 cpu / 4 wait" in text
    assert "~wait" in text  # the parked leaf is marked
    assert "hot frames" in text
    empty = contprof.format_flame({"folded": {}, "samples": {}})
    assert "(no samples yet)" in empty


def test_merge_folded_sums_members():
    a = {"folded": {"x;y": {"cpu": 1, "wait": 0}},
         "samples": {"cpu": 1, "wait": 0}}
    b = {"folded": {"x;y": {"cpu": 2, "wait": 1},
                    "z": {"cpu": 0, "wait": 1}},
         "samples": {"cpu": 2, "wait": 2}}
    merged = contprof.merge_folded([a, b])
    assert merged["slice"] == "fleet"
    assert merged["folded"]["x;y"] == {"cpu": 3, "wait": 1}
    assert merged["folded"]["z"] == {"cpu": 0, "wait": 1}
    assert merged["samples"] == {"cpu": 3, "wait": 2}


def test_serve_path_breakdown_buckets_handler_self_time():
    shares = contprof.serve_path_breakdown(_payload())
    # batcher stacks are excluded; handler total = 7
    assert shares["socket"] == round(4 / 7, 4)
    assert shares["json"] == round(2 / 7, 4)
    assert shares["parse"] == round(1 / 7, 4)
    assert contprof.serve_path_breakdown({"folded": {}}) == {}


# ---------------------------------------------------------------------------
# federation plane
# ---------------------------------------------------------------------------

def test_federate_prof_merges_and_degrades_on_dead_member():
    contprof.PROFILER._trie.add(["[main]", "a.py:f"], waiting=False)
    report = collect.federate_prof([
        collect.Member("local", None),
        collect.Member("dead", "http://127.0.0.1:1"),
    ])
    by_name = {m["name"]: m for m in report["members"]}
    assert by_name["local"]["ok"] and by_name["local"]["samples"] >= 1
    assert not by_name["dead"]["ok"] and by_name["dead"]["error"]
    assert report["merged_from"] == ["local"]
    assert report["merged"]["folded"]["[main];a.py:f"]["cpu"] == 1


# ---------------------------------------------------------------------------
# HTTP surface + CLI + dashboard on a single server
# ---------------------------------------------------------------------------

def test_admin_prof_endpoint_and_cli(memory_storage, capsys):
    from predictionio_tpu.serving.event_server import EventServer
    from predictionio_tpu.tools import cli

    server = EventServer(storage=memory_storage, host="127.0.0.1",
                         port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # let the sampler fold a few passes of the live server
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            status, payload = get_json(base + "/admin/prof")
            assert status == 200
            if payload["total_samples"] > 0:
                break
            time.sleep(0.05)
        assert payload["running"] is True
        assert payload["slice"] == "all"
        assert payload["total_samples"] > 0
        assert payload["folded"]  # stacks landed
        # the sampler names itself in the flame
        assert any(s.startswith("[sampler]") for s in payload["folded"])
        # collapsed form for external tooling
        status, text, headers = get(base + "/admin/prof?format=collapsed")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert ";" in text and text.strip().rsplit(" ", 1)[1].isdigit()
        # slow slice answers (empty cohort on an idle server)
        status, slow = get_json(base + "/admin/prof?slow=1")
        assert status == 200 and slow["slice"] == "slow"
        assert slow["slow_trace_ids"] == []
        # the 501 device-profile answer now points here
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            base + "/admin/profile?seconds=0.01", data=b"", method="POST")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 501
        body = json.loads(err.value.read())
        assert body["host_profiler"] == "/admin/prof"
        assert "GET /admin/prof" in body["hint"]
        # pio prof renders the same payload through the shared renderer
        assert cli.main(["prof", "--url", base]) == 0
        out = capsys.readouterr().out
        assert "continuous profile [all]" in out
        assert "hot frames" in out
        assert cli.main(["prof", "--url", base, "--collapsed"]) == 0
        out = capsys.readouterr().out
        assert "[sampler]" in out
        assert cli.main(["prof", "--url", base, "--json"]) == 0
        assert json.loads(capsys.readouterr().out)["slice"] == "all"
    finally:
        server.stop()


def test_dashboard_prof_view(memory_storage):
    from predictionio_tpu.tools.dashboard import DashboardServer

    server = DashboardServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status, text, _ = get(base + "/prof")
        assert status == 200 and "continuous profile" in text
        status, text, _ = get(base + "/prof?slow=1")
        assert status == 200 and "[slow]" in text
        # the index links the flame view
        status, text, _ = get(base + "/")
        assert status == 200 and "/prof" in text
    finally:
        server.stop()


def test_timeline_carries_prof_overhead_series():
    from predictionio_tpu.obs import timeline

    sample = timeline.contprof_collector()(0.0)
    assert set(sample) == {"prof.overhead"}
    assert isinstance(sample["prof.overhead"], float)
    # the default collector set carries the series
    merged = {}
    for collector in timeline.default_collectors():
        merged.update(collector(0.0))
    assert "prof.overhead" in merged


# ---------------------------------------------------------------------------
# acceptance e2e: hedging fleet under load -> tail flame joins flight
# ---------------------------------------------------------------------------

def _train_slow_engine(storage, sleep_ms=60.0):
    """A const-style engine whose predict sleeps: every query is a tail
    request once PIO_SLOW_MS sits below the sleep."""
    from predictionio_tpu.core import (Algorithm, DataSource, Engine,
                                       FirstServing, IdentityPreparator)
    from predictionio_tpu.core.params import EngineParams, Params
    from predictionio_tpu.workflow.train import run_train

    @dataclass
    class NoParams(Params):
        pass

    class OneDataSource(DataSource):
        def read_training(self, ctx):
            return 1.0

    class SlowAlgo(Algorithm):
        def train(self, ctx, pd):
            return pd

        def predict(self, model, query):
            time.sleep(sleep_ms / 1e3)
            return {"model": model}

    engine = Engine(OneDataSource, IdentityPreparator,
                    {"slowalgo": SlowAlgo}, FirstServing)
    ep = EngineParams(
        data_source_params=("", NoParams()),
        preparator_params=("", None),
        algorithm_params_list=[("slowalgo", NoParams())],
        serving_params=("", None),
    )
    # trained under "const": running_fleet's factory binds that id
    run_train(engine, ep, engine_id="const", storage=storage)
    return engine


def test_acceptance_tail_flame_joins_flight_slow_ring(memory_storage,
                                                      monkeypatch,
                                                      capsys):
    """ISSUE acceptance: under router load with hedging armed,
    ``GET /admin/prof?slow=1`` yields a non-empty tail flame whose
    trace ids appear in the flight recorder's slow ring, ``pio prof
    --fleet`` renders the member-merged view, and the run sees zero
    non-429 client errors."""
    from predictionio_tpu.tools import cli

    # fast sampling with a permissive budget (tiny test intervals would
    # otherwise downshift mid-run), tail threshold under the sleep
    monkeypatch.setenv("PIO_PROF_HZ", "200")
    monkeypatch.setenv("PIO_PROF_MAX_OVERHEAD", "0.5")
    monkeypatch.setenv("PIO_SLOW_MS", "20")
    engine = _train_slow_engine(memory_storage, sleep_ms=60.0)
    with running_fleet(memory_storage, engine) as (fleet, router, base):
        assert contprof.PROFILER.running()  # router+replicas retain it
        trace_ids = []
        for _ in range(30):  # past HedgeClock.min_samples
            status, body, headers = post(base + "/queries.json",
                                         body=b'{"q": 1}')
            assert status == 200, body  # zero non-429 (indeed, none)
            trace_ids.append(headers[trace.TRACE_HEADER])
        assert router.hedge.deadline() is not None  # hedging armed

        # -- the tail flame off the router ------------------------------
        status, slow = get_json(base + "/admin/prof?slow=1")
        assert status == 200
        assert slow["samples"]["cpu"] + slow["samples"]["wait"] > 0
        assert slow["folded"]  # non-empty tail flame
        assert slow["slow_trace_ids"]
        assert set(slow["slow_trace_ids"]) & set(trace_ids)

        # its trace ids join the flight recorder's slow ring
        slow_records = flight.RECORDER.records(slow_only=True)
        ring = {r.get("trace") for r in slow_records}
        joined = set(slow["slow_trace_ids"]) & ring
        assert joined, (slow["slow_trace_ids"], ring)
        # slow flight records name the dominant host frame (satellite:
        # `pio flight --slow` names code, not just stages)
        stamped = [r for r in slow_records
                   if r.get("dominant_frame")]
        assert stamped
        assert all(":" in r["dominant_frame"] for r in stamped)

        # -- member-merged fleet view -----------------------------------
        status, report = get_json(base + "/admin/fleet/prof")
        assert status == 200
        assert {m["name"] for m in report["members"]} == {"r0", "r1",
                                                          "r2"}
        assert all(m["ok"] for m in report["members"])
        assert report["merged"]["folded"]
        assert report["merged_from"] == ["r0", "r1", "r2"]
        status, text, _ = get(
            base + "/admin/fleet/prof?format=collapsed")
        assert status == 200 and ";" in text

        # -- pio prof drives the same surfaces --------------------------
        assert cli.main(["prof", "--fleet", "--url", base]) == 0
        out = capsys.readouterr().out
        assert "member r0" in out and "continuous profile" in out
        assert cli.main(["prof", "--url", base, "--slow"]) == 0
        out = capsys.readouterr().out
        assert "slow-cohort trace ids" in out
    assert not contprof.PROFILER.running()  # fleet teardown released


# ---------------------------------------------------------------------------
# bench + CI gate: prof overhead is a first-class lower-better key
# ---------------------------------------------------------------------------

def _bench_round(tmp_path, name, overhead_pct):
    path = tmp_path / name
    path.write_text(json.dumps({"parsed": {
        "metric": "m", "value": 1.0,
        "key": {"prof_overhead_pct": overhead_pct},
    }}))
    return str(path)


def test_benchcmp_gates_prof_overhead_lower_better(tmp_path, capsys):
    from predictionio_tpu.tools import benchcmp

    assert benchcmp.lower_is_better("key.prof_overhead_pct")
    assert not benchcmp.is_config_key("key.prof_overhead_pct")
    base = _bench_round(tmp_path, "BENCH_r01.json", 0.5)
    worse = _bench_round(tmp_path, "BENCH_r02.json", 3.0)
    assert benchcmp.run([base, worse]) == 1  # regression -> exit 1
    out = capsys.readouterr().out
    assert "key.prof_overhead_pct" in out and "REGRESSION" in out
    better = _bench_round(tmp_path, "BENCH_r03.json", 0.3)
    assert benchcmp.run([base, better]) == 0
