"""Mid-training checkpoint/resume (core.checkpoint) — beyond the
reference's train-to-completion-or-nothing (SURVEY.md §5.4): an
interrupted-and-resumed run must produce the SAME parameters as an
uninterrupted one (optimizer state, epoch counter and RNG streams all
persist)."""

import pickle

import numpy as np
import pytest

from predictionio_tpu.core.checkpoint import TrainCheckpointer


def test_checkpointer_atomicity_and_retention(tmp_path):
    ck = TrainCheckpointer(str(tmp_path), every=2, keep=2)
    assert ck.restore() is None
    assert ck.maybe_save(1, {"a": 1}) is False      # not due
    assert ck.maybe_save(2, {"a": 2}) is True
    assert ck.maybe_save(4, {"a": 4}) is True
    assert ck.maybe_save(6, {"a": 6}) is True       # evicts epoch 2
    names = sorted(p.name for p in tmp_path.iterdir())
    assert names == ["ckpt_4.pkl", "ckpt_6.pkl"]
    assert ck.restore() == (6, {"a": 6})
    # a torn newest checkpoint falls back to the previous good one
    (tmp_path / "ckpt_6.pkl").write_bytes(b"torn")
    assert ck.restore() == (4, {"a": 4})


def _toy_data(n=400, n_users=30, n_items=12, seed=2):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, n_users, n), rng.integers(0, n_items, n),
            np.arange(n, dtype=np.float64))


def test_twotower_resume_matches_uninterrupted(tmp_path):
    from predictionio_tpu.ops.twotower import TwoTowerConfig, TwoTowerTrainer

    u, i, _ = _toy_data()
    kw = dict(dim=8, epochs=4, batch_size=64, seed=5)

    straight = TwoTowerTrainer((u, i, None), 30, 12, TwoTowerConfig(**kw))
    losses_straight = straight.run()

    ckdir = str(tmp_path / "tt")
    cfg = TwoTowerConfig(**kw, checkpoint_dir=ckdir, checkpoint_every=1)
    first = TwoTowerTrainer((u, i, None), 30, 12, cfg)
    first.run(epochs=2)                      # "crash" after 2 epochs

    resumed = TwoTowerTrainer((u, i, None), 30, 12, cfg)  # fresh process stand-in
    assert resumed._epochs_done == 2
    losses_resumed = resumed.run()           # finishes epochs 3..4

    assert np.allclose(losses_resumed, losses_straight, atol=1e-5)
    for a, b in zip(
        np.asarray(resumed.embeddings().item_vecs),
        np.asarray(straight.embeddings().item_vecs),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_sessionrec_resume_matches_uninterrupted(tmp_path):
    from predictionio_tpu.ops.sessionrec import (
        SessionRecConfig,
        SessionRecTrainer,
    )

    u, i, t = _toy_data()
    kw = dict(dim=8, heads=2, layers=1, max_len=8, dropout=0.1,
              epochs=3, batch_size=32, seed=7)

    straight = SessionRecTrainer((u, i, t), 30, 12, SessionRecConfig(**kw))
    losses_straight = straight.run()

    ckdir = str(tmp_path / "sr")
    cfg = SessionRecConfig(**kw, checkpoint_dir=ckdir, checkpoint_every=1)
    first = SessionRecTrainer((u, i, t), 30, 12, cfg)
    first.run(epochs=1)

    resumed = SessionRecTrainer((u, i, t), 30, 12, cfg)
    assert resumed._epochs_done == 1
    losses_resumed = resumed.run()

    assert np.allclose(losses_resumed, losses_straight, atol=1e-5)
    import jax

    sa = straight.state(losses_straight)
    sb = resumed.state(losses_resumed)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        sa.params, sb.params,
    )


def test_fingerprint_guards_stale_and_wrong_shape(tmp_path):
    """A checkpoint from different data/config is IGNORED — no silent
    stale-model no-op, no wrong-shape embedding adoption."""
    from predictionio_tpu.ops.twotower import TwoTowerConfig, TwoTowerTrainer

    u, i, _ = _toy_data()
    ckdir = str(tmp_path / "fp")
    cfg = TwoTowerConfig(dim=8, epochs=2, batch_size=64, seed=5,
                         checkpoint_dir=ckdir)
    t1 = TwoTowerTrainer((u, i, None), 30, 12, cfg)
    t1.run()
    assert t1._epochs_done == 2

    # same data + config: resume-to-completion is the correct result
    t_same = TwoTowerTrainer((u, i, None), 30, 12, cfg)
    assert t_same._epochs_done == 2

    # new data (the week-later retrain): fingerprint mismatch -> fresh
    u2, i2, _ = _toy_data(seed=99)
    t_new = TwoTowerTrainer((u2, i2, None), 30, 12, cfg)
    assert t_new._epochs_done == 0
    # grown catalog: never adopts the 12-item embedding table
    t_grown = TwoTowerTrainer((u, i, None), 30, 20, cfg)
    assert t_grown._epochs_done == 0
    assert t_grown.run()  # trains cleanly from scratch
