"""Data & ingest observability plane (obs/dataobs.py): sketch accuracy
vs exact numpy, schema-drift detection, ingest-seam exactly-once
counting, fleet merge degradation, the serving-side unknown-entity
coverage seam, and the acceptance e2e pin — a Zipf hot-key storm with a
mid-stream schema change against a live event server, detected,
journaled, attributed by the anomaly sentinel and rendered by
``pio data --fleet`` with one dead member degraded."""

import collections
import json
import socket
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.core import (Algorithm, DataSource, Engine,
                                   FirstServing, IdentityPreparator)
from predictionio_tpu.core.params import EmptyParams, EngineParams
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import AccessKey
from predictionio_tpu.obs import collect, dataobs, journal
from predictionio_tpu.obs.dataobs import (DATAOBS, CountMinSketch,
                                          HyperLogLog, QuantileSketch,
                                          SpaceSaving, _hash_u64)
from predictionio_tpu.serving.engine_server import EngineServer
from predictionio_tpu.serving.event_server import EventServer
from predictionio_tpu.workflow.train import run_train


def http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"} if data else {}
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read() or b"null")


def zipf_keys(n=60_000, a=1.5, seed=42):
    rng = np.random.default_rng(seed)
    return [f"u{d}" for d in rng.zipf(a, n)]


# ---------------------------------------------------------------------------
# sketch accuracy vs exact numpy
# ---------------------------------------------------------------------------

class TestCountMin:
    def test_zipf_error_bounds(self):
        keys = zipf_keys()
        exact = collections.Counter(keys)
        cms = CountMinSketch(width=1024, depth=4)
        uniq = list(exact.keys())
        cms.update(_hash_u64(uniq),
                   np.fromiter(exact.values(), np.int64, len(exact)))
        assert cms.total == len(keys)
        # one-sided error: never an undercount, overcount bounded by
        # the standard 2N/width envelope on every probed key
        bound = 2 * len(keys) / 1024
        for key, true in exact.most_common(20):
            est = cms.estimate(key)
            assert est >= true
            assert est - true <= bound
        # a never-seen key collides to at most the same envelope
        assert cms.estimate("never-seen") <= bound

    def test_width_must_be_power_of_two(self):
        with pytest.raises(ValueError):
            CountMinSketch(width=1000)


class TestSpaceSaving:
    def test_zipf_top_k_and_error_certificates(self):
        keys = zipf_keys()
        exact = collections.Counter(keys)
        ss = SpaceSaving(capacity=128)
        # feed in update rounds, the way the worker drains batches
        for lo in range(0, len(keys), 4096):
            ss.offer_counts(collections.Counter(keys[lo:lo + 4096]))
        assert len(ss) <= 128  # bounded by construction
        top = {key: (count, err) for key, count, err in ss.top(32)}
        for key, true in exact.most_common(10):
            assert key in top  # every true heavy hitter is tracked
            count, err = top[key]
            # space-saving invariant: recorded count overestimates the
            # truth by at most the admission-floor error certificate
            assert count >= true
            assert count - err <= true

    def test_capacity_floor(self):
        assert SpaceSaving(capacity=2).capacity == 8


class TestHyperLogLog:
    def test_within_five_percent_on_zipf_stream(self):
        keys = zipf_keys(n=120_000, a=1.3)
        exact = len(set(keys))
        hll = HyperLogLog(p=11)
        for lo in range(0, len(keys), 8192):
            hll.add_hashes(_hash_u64(keys[lo:lo + 8192]))
        est = hll.estimate()
        assert abs(est - exact) / exact <= 0.05

    def test_small_sets_linear_counting(self):
        hll = HyperLogLog(p=11)
        hll.add_hashes(_hash_u64([f"k{i}" for i in range(100)]))
        assert abs(hll.estimate() - 100) <= 5


class TestQuantileSketch:
    def test_tracks_np_quantile_within_rank_tolerance(self):
        rng = np.random.default_rng(7)
        sample = rng.lognormal(3.0, 1.0, 50_000)
        qs = QuantileSketch(budget=256)
        for lo in range(0, sample.size, 4096):
            qs.update(sample[lo:lo + 4096])
        assert qs.n == sample.size
        for q in (0.5, 0.9, 0.99):
            est = qs.quantile(q)
            # rank-tolerance: the estimate must land between the exact
            # quantiles one rank-percent either side
            lo_v = np.quantile(sample, max(0.0, q - 0.01))
            hi_v = np.quantile(sample, min(1.0, q + 0.01))
            assert lo_v <= est <= hi_v
        assert qs.quantile(0.0) == sample.min()
        assert qs.quantile(1.0) == sample.max()

    def test_summary_shape(self):
        qs = QuantileSketch()
        assert qs.summary() == {"n": 0}
        qs.add(3.0)
        summ = qs.summary()
        assert summ["n"] == 1 and summ["min"] == summ["max"] == 3.0

    def test_non_finite_values_dropped(self):
        qs = QuantileSketch()
        qs.update(np.array([1.0, np.inf, np.nan, 2.0]))
        assert qs.n == 2


# ---------------------------------------------------------------------------
# schema drift matrix: added / vanished / retyped
# ---------------------------------------------------------------------------

def _rate_event(props, name="rate", entity="u1"):
    return Event(event=name, entity_type="user", entity_id=entity,
                 properties=props)


class TestSchemaDrift:
    def test_add_remove_retype_matrix(self, monkeypatch):
        monkeypatch.setenv("PIO_DATAOBS_VANISH_AFTER", "3")
        for _ in range(4):
            DATAOBS.observe_event(
                1, _rate_event({"rating": 4.0, "note": "x"}))
        DATAOBS.freeze_schemas("inst-1")

        # added: a field the frozen profile never saw
        DATAOBS.observe_event(
            1, _rate_event({"rating": 4.0, "note": "x", "source": "web"}))
        # retyped: rating flips float -> str
        DATAOBS.observe_event(
            1, _rate_event({"rating": "5", "note": "x"}))
        # vanished: 'note' absent for VANISH_AFTER samples
        for _ in range(4):
            DATAOBS.observe_event(1, _rate_event({"rating": 4.0}))

        changes = {(c["change"], c["field"])
                   for c in DATAOBS.report()["schema"]["changes"]}
        assert ("added", "source") in changes
        assert ("retyped", "rating") in changes
        assert ("vanished", "note") in changes
        # every drift is an ops-journal event the sentinel can attribute
        kinds = {(e["change"], e["field"])
                 for e in journal.JOURNAL.recent(kind="schema_change")}
        assert {("added", "source"), ("retyped", "rating"),
                ("vanished", "note")} <= kinds

    def test_changes_dedupe(self):
        DATAOBS.observe_event(1, _rate_event({"rating": 4.0}))
        DATAOBS.freeze_schemas("inst-1")
        for _ in range(5):
            DATAOBS.observe_event(1, _rate_event({"rating": 4.0,
                                                  "extra": 1}))
        report = DATAOBS.report()
        assert report["schema"]["changes_total"] == 1
        assert report["schema"]["frozen_instance"] == "inst-1"

    def test_no_frozen_profile_no_changes(self):
        DATAOBS.observe_event(1, _rate_event({"rating": 4.0}))
        DATAOBS.observe_event(1, _rate_event({"rating": "oops"}))
        assert DATAOBS.report()["schema"]["changes"] == []


# ---------------------------------------------------------------------------
# bounded state + exactly-once counting through the storage seams
# ---------------------------------------------------------------------------

class TestBoundedState:
    def test_rate_rows_overflow_to_other(self, monkeypatch):
        monkeypatch.setenv("PIO_DATAOBS_MAX_RATE_ROWS", "8")
        for i in range(40):
            DATAOBS.observe_event(1, _rate_event({}, name=f"ev{i}"))
        report = DATAOBS.report()
        assert len(report["rates"]) <= 9  # 8 rows + the (other) row
        other = [r for r in report["rates"] if r["event"] == "(other)"]
        assert other and other[0]["count"] == 32
        assert report["events_total"] == 40

    def test_queue_overflow_drops_never_blocks(self, monkeypatch):
        from predictionio_tpu.obs.dataobs import _QUEUE_DROPPED
        monkeypatch.setenv("PIO_DATAOBS_QUEUE", "8")
        before = _QUEUE_DROPPED.value
        with DATAOBS._q_cond:  # stall the worker's view: fill directly
            for _ in range(64):
                DATAOBS._q.append(("tail", 1, 0, {}, {}))
            DATAOBS._pending += 64
        for _ in range(16):
            DATAOBS.observe_batch(1, [b"rate"], entity_ids=[b"u1"])
        assert _QUEUE_DROPPED.value > before
        DATAOBS.reset()

    def test_disable_knob_gates_every_seam(self, monkeypatch):
        monkeypatch.setenv("PIO_DATAOBS_DISABLE", "1")
        DATAOBS.observe_event(1, _rate_event({"rating": 1.0}))
        DATAOBS.observe_batch(1, [b"rate"], entity_ids=[b"u1"])
        DATAOBS.note_query(4, 2)
        monkeypatch.delenv("PIO_DATAOBS_DISABLE")
        report = DATAOBS.report()
        assert report["events_total"] == 0
        assert report["queries_seen"] == 0


class TestIngestSeams:
    def test_memory_batch_lane_counts_once(self, memory_storage):
        app = memory_storage.apps().insert("obs-app")
        memory_storage.events().init(app.id)
        events = [Event(event="rate", entity_type="user",
                        entity_id=f"u{i % 7}", properties={"rating": 1.0})
                  for i in range(25)]
        memory_storage.events().insert_batch(events, app.id)
        assert DATAOBS.flush(timeout=5.0)
        report = DATAOBS.report()
        assert report["events_total"] == 25
        assert report["entities"]["cardinality"]["entityId"] >= 6

    def test_event_server_201_lane_counts_payload_bytes(self, memory_storage):
        app = memory_storage.apps().insert("obs-app")
        memory_storage.events().init(app.id)
        key = AccessKey.generate(app.id)
        memory_storage.access_keys().insert(key)
        server = EventServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, _ = http(
                "POST", f"{base}/events.json?accessKey={key.key}",
                {"event": "rate", "entityType": "user", "entityId": "u1",
                 "properties": {"rating": 4.5}})
            assert status == 201
        finally:
            server.stop()
        report = DATAOBS.report()
        assert report["events_total"] == 1
        assert report["bytes_total"] > 0  # stamped from len(body)
        assert report["quantiles"]["value"]["n"] == 1


# ---------------------------------------------------------------------------
# fleet merge: dead member degrades, never fails
# ---------------------------------------------------------------------------

def _dead_member(name="gone"):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    return collect.Member(name, f"http://127.0.0.1:{port}")


class TestFederateData:
    def test_merge_degrades_on_dead_member(self):
        for _ in range(3):
            DATAOBS.observe_event(1, _rate_event({"rating": 2.0}))
        DATAOBS.freeze_schemas("inst-1")
        DATAOBS.observe_event(1, _rate_event({"rating": 2.0, "new": 1}))
        report = collect.federate_data(
            [collect.Member("local", None), _dead_member()])
        by_name = {m["name"]: m for m in report["members"]}
        assert by_name["local"]["ok"] is True
        assert by_name["gone"]["ok"] is False and by_name["gone"]["error"]
        assert report["merged_from"] == ["local"]
        assert report["totals"]["events_total"] == 4
        assert report["schema_changes"]
        assert all(c["fleet_member"] == "local"
                   for c in report["schema_changes"])

    def test_all_dead_still_returns_shape(self):
        report = collect.federate_data([_dead_member("a"), _dead_member("b")])
        assert report["merged_from"] == []
        assert report["totals"]["events_total"] == 0
        assert report["skew"] == 0.0


# ---------------------------------------------------------------------------
# serving-side unknown-entity coverage, e2e through a live engine server
# ---------------------------------------------------------------------------

class MapModel:
    def __init__(self):
        self.user_ids = {"u1": 0, "u2": 1}
        self.item_ids = {"i1": 0, "i2": 1}


class MapDataSource(DataSource):
    def read_training(self, ctx):
        return 0.0


class MapAlgo(Algorithm):
    def train(self, ctx, pd):
        return MapModel()

    def predict(self, model, query):
        return {"ok": True}


def _map_engine_server(storage):
    engine = Engine(MapDataSource, IdentityPreparator, {"m": MapAlgo},
                    FirstServing)
    ep = EngineParams(
        data_source_params=("", EmptyParams()),
        preparator_params=("", None),
        algorithm_params_list=[("m", EmptyParams())],
        serving_params=("", None),
    )
    run_train(engine, ep, engine_id="mapper", storage=storage)
    return EngineServer(engine, "mapper", host="127.0.0.1", port=0,
                        storage=storage).start()


class TestUnknownEntityCoverage:
    def test_query_decode_seam_e2e(self, memory_storage):
        server = _map_engine_server(memory_storage)
        try:
            base = f"http://127.0.0.1:{server.port}"
            # known user + known item: 2 refs, 0 unknown
            assert http("POST", f"{base}/queries.json",
                        {"user": "u1", "items": ["i1"]})[0] == 200
            # unknown user + one unknown of two items: 3 refs, 2 unknown
            assert http("POST", f"{base}/queries.json",
                        {"user": "ghost", "items": ["i2", "nope"]})[0] == 200
            status, report = http("GET", f"{base}/admin/data")
            assert status == 200
        finally:
            server.stop()
        assert report["queries_seen"] == 5
        assert report["unknown_ratio"] == pytest.approx(2 / 5)
        from predictionio_tpu.obs.dataobs import _UNKNOWN_RATIO
        assert _UNKNOWN_RATIO.value == pytest.approx(2 / 5)

    def test_queries_without_entity_refs_are_ignored(self, memory_storage):
        server = _map_engine_server(memory_storage)
        try:
            base = f"http://127.0.0.1:{server.port}"
            assert http("POST", f"{base}/queries.json",
                        {"mult": 3})[0] == 200
        finally:
            server.stop()
        assert DATAOBS.report()["queries_seen"] == 0


# ---------------------------------------------------------------------------
# pio top ingest row
# ---------------------------------------------------------------------------

def test_top_frame_ingest_row():
    from predictionio_tpu.tools.cli import _render_top_frame

    frame = _render_top_frame({"series": {
        "data.eps": [(0.0, 100.0), (15.0, 120.0)],
        "data.unknown_ratio": [(0.0, 0.0), (15.0, 0.25)],
        "data.skew": [(0.0, 0.0), (15.0, 1.4)],
    }})
    assert "ingest:" in frame
    assert "120 ev/s" in frame and "25.00%" in frame and "skew 1.4" in frame


def test_top_frame_without_data_series_has_no_ingest_row():
    from predictionio_tpu.tools.cli import _render_top_frame

    frame = _render_top_frame({"series": {
        "serve_p99_ms.eng": [(0.0, 10.0)]}})
    assert "ingest:" not in frame


def test_fleet_frame_ingest_row_sums_and_maxes():
    from predictionio_tpu.tools.cli import _render_fleet_frame

    frame = _render_fleet_frame({"samples": {
        'pio_data_events_total{app="1",event="rate",member="a"}': 700.0,
        'pio_data_events_total{app="1",event="rate",member="b"}': 300.0,
        'pio_data_entity_skew{member="a"}': 0.4,
        'pio_data_entity_skew{member="b"}': 1.7,
        'pio_query_unknown_entity_ratio{member="a"}': 0.25,
    }, "members": []})
    # counters sum across the merge; skew/unknown take the fleet max
    assert "fleet ingest: events 1000" in frame
    assert "skew 1.7" in frame
    assert "unknown-entity 25.00%" in frame


# ---------------------------------------------------------------------------
# acceptance e2e pin: Zipf hot-key storm + mid-stream schema change
# against a LIVE event server — detected, journaled, attributed,
# rendered fleet-wide with one dead member degraded, zero ingest errors
# ---------------------------------------------------------------------------

class TestAcceptanceStorm:
    def test_hot_key_storm_schema_change_end_to_end(
            self, memory_storage, monkeypatch, capsys):
        import predictionio_tpu.obs.timeline as timeline_mod
        from predictionio_tpu.obs import anomaly
        from predictionio_tpu.tools import cli

        monkeypatch.setenv("PIO_DATAOBS_BREACH_INTERVAL_SEC", "0")
        monkeypatch.setenv("PIO_DATAOBS_SKEW_BREACH", "1.0")
        app = memory_storage.apps().insert("storm-app")
        memory_storage.events().init(app.id)
        key = AccessKey.generate(app.id)
        memory_storage.access_keys().insert(key)
        server = EventServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            batch_url = f"{base}/batch/events.json?accessKey={key.key}"

            def post_batch(events):
                status, body = http("POST", batch_url, events)
                assert status == 200
                bad = [r for r in body if r.get("status") != 201]
                assert bad == []  # zero ingest errors

            def make(entity, props):
                return {"event": "rate", "entityType": "user",
                        "entityId": entity, "targetEntityType": "item",
                        "targetEntityId": "i1", "properties": props}

            # phase 1 — calm baseline traffic, schema frozen at a
            # "completed train": rating is a float
            post_batch([make(f"u{i}", {"rating": float(i % 5)})
                        for i in range(40)])
            DATAOBS.freeze_schemas("inst-storm-base")

            # phase 2 — the Zipf hot-key storm: counts ~ rank^-2 over
            # 24 entities, the top key dominating
            storm = []
            for rank in range(1, 25):
                count = max(1, int(1200 / rank ** 2))
                storm.extend(make(f"hot{rank}",
                                  {"rating": float(rank % 5)})
                             for _ in range(count))
            for lo in range(0, len(storm), 400):
                post_batch(storm[lo:lo + 400])

            # phase 3 — mid-stream schema change: rating flips to str
            # and a new field appears
            post_batch([make(f"hot{i % 4 + 1}",
                             {"rating": "5", "source": "web"})
                        for i in range(20)])

            skew = DATAOBS.skew()
            assert skew >= 1.0  # the storm registered in the gauge
            from predictionio_tpu.obs.dataobs import _SKEW
            assert _SKEW.value == pytest.approx(skew, rel=0.2)

            breaches = journal.JOURNAL.recent(kind="data_breach")
            assert any(b["breach"] == "entity_skew" and b["top_entity"]
                       == "hot1" for b in breaches)
            drifts = journal.JOURNAL.recent(kind="schema_change")
            changes = {(d["change"], d["field"]) for d in drifts}
            assert ("retyped", "rating") in changes
            assert ("added", "source") in changes

            # the anomaly sentinel sees the skew step on the data.skew
            # timeline and attributes it to the data_breach event
            tl = timeline_mod.Timeline()
            monkeypatch.setattr(timeline_mod, "TIMELINE", tl)
            ring = tl._series.setdefault(
                "data.skew", collections.deque(maxlen=360))
            baseline = [0.2 + (0.02 if i % 2 else -0.02)
                        for i in range(24)]
            for i, v in enumerate(baseline + [skew] * 12):
                ring.append((1000.0 + i * 15.0, float(v)))
            monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "60")
            # pin the breach event just before the onset (index 24 ->
            # ts 1360), the way the sentinel fixtures do
            for entry in journal.JOURNAL._ring:
                if entry["kind"] == "data_breach":
                    entry["ts"] = 1355.0
            report = anomaly.SENTINEL.scan(now=1540.0)
            verdict = report["active"].get("data.skew")
            assert verdict is not None
            assert verdict["direction"] == "up"
            assert verdict["cause"]["kind"] == "data_breach"
            onsets = journal.JOURNAL.recent(kind="anomaly")
            assert onsets and onsets[-1]["series"] == "data.skew"
            assert onsets[-1]["cause_kind"] == "data_breach"

            # the storm is visible in `pio anomalies` with attribution
            assert cli.main(["anomalies"]) == 1
            out = capsys.readouterr().out
            assert "data.skew" in out and "<- data_breach" in out

            # ... and in `pio data --fleet` through the live server's
            # /admin/fleet/data, with one dead member degraded
            dead = _dead_member()
            monkeypatch.setenv(
                "PIO_OBS_MEMBERS", f"self={base},gone={dead.url}")
            assert cli.main(["data", "--fleet", "--url", base]) == 0
            out = capsys.readouterr().out
            assert "member self" in out and "ok" in out
            assert "member gone" in out and "ERROR" in out
            assert "ACTIVE BREACH: entity_skew" in out
            assert "rate.rating retyped" in out

            # the single-server page shows the hot-entity table itself
            assert cli.main(["data", "--url", base]) == 0
            out = capsys.readouterr().out
            assert "hot entities:" in out and "hot1" in out

            # every accepted event was counted exactly once
            assert DATAOBS.report()["events_total"] == 40 + len(storm) + 20
        finally:
            server.stop()
