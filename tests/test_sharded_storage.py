"""EVENTDATA sharded across N storage servers (VERDICT r2 item 4).

The reference's event store scales horizontally because HBase splits
tables into regions by the MD5 rowkey prefix and spreads them across
region servers (hbase/HBEventsUtil.scala:47,96-108). Here the same
partition function (storage.stable_hash on entity id) routes the rest
client's writes across N storage servers; reads fan out and merge; a
down shard fails loudly naming its endpoint; `pio status` reports
per-shard health.
"""

import dataclasses
import datetime as _dt

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import (
    Storage,
    StorageUnavailableError,
    stable_hash,
)
from predictionio_tpu.serving.storage_server import StorageServer

from tests.test_sharded_reads import _decode

UTC = _dt.timezone.utc


def _memory_storage() -> Storage:
    return Storage.from_env({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })


def _client(ports, replicas=None) -> Storage:
    env = {
        "PIO_STORAGE_SOURCES_SH_TYPE": "rest",
        "PIO_STORAGE_SOURCES_SH_HOSTS": "127.0.0.1",
        "PIO_STORAGE_SOURCES_SH_PORTS": ",".join(str(p) for p in ports),
        "PIO_STORAGE_SOURCES_SH_RETRIES": "0",
        "PIO_STORAGE_SOURCES_SH_TIMEOUT": "5",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SH",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SH",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SH",
    }
    if replicas is not None:
        env["PIO_STORAGE_SOURCES_SH_REPLICAS"] = str(replicas)
    return Storage.from_env(env)


@pytest.fixture()
def two_servers():
    """Two storage servers over independent backends + sharded client."""
    backends = [_memory_storage(), _memory_storage()]
    servers = [
        StorageServer(storage=b, host="127.0.0.1", port=0).start()
        for b in backends
    ]
    try:
        yield backends, servers, _client([s.port for s in servers])
    finally:
        for s in servers:
            s.stop()


def _events(n=80, users=13, items=6):
    out = []
    for i in range(n):
        out.append(Event(
            event="rate",
            entity_type="user",
            entity_id=f"user_{i % users}",
            target_entity_type="item",
            target_entity_id=f"item_{i % items}",
            properties={"rating": float(1 + i % 5)},
            event_time=_dt.datetime(2026, 2, 1, tzinfo=UTC)
            + _dt.timedelta(minutes=i),
        ))
    return out


def test_writes_route_by_entity_hash_and_reads_merge(two_servers):
    backends, _, client = two_servers
    store = client.events()
    store.init(1)
    events = _events()
    ids = store.insert_batch(events, 1)
    assert len(ids) == len(set(ids)) == len(events)

    # each backend holds exactly the entity-hash share; both non-empty
    per_server = [b.events().find(1) for b in backends]
    assert all(len(p) > 0 for p in per_server)
    assert sum(len(p) for p in per_server) == len(events)
    for s, part in enumerate(per_server):
        for e in part:
            assert stable_hash(e.entity_id) % 2 == s

    # merged find equals the oracle: same events, globally time-ordered
    merged = store.find(1)
    assert [e.event_time for e in merged] == sorted(e.event_time for e in events)
    assert {(e.entity_id, e.target_entity_id, e.event_time) for e in merged} \
        == {(e.entity_id, e.target_entity_id, e.event_time) for e in events}

    # limit + reversed apply AFTER the merge
    newest = store.find(1, limit=5, reversed=True)
    assert [e.event_time for e in newest] == sorted(
        (e.event_time for e in events), reverse=True)[:5]


def test_columnar_fanout_matches_single_store_oracle(two_servers):
    _, _, client = two_servers
    store = client.events()
    store.init(1)
    events = _events()
    store.insert_batch(events, 1)

    oracle = _memory_storage()
    oracle.events().init(1)
    oracle.events().insert_batch(events, 1)
    expected = oracle.events().find_columnar(
        1, value_property="rating", time_ordered=False)

    merged = store.find_columnar(1, value_property="rating",
                                 time_ordered=False)
    assert sorted(_decode(merged)) == sorted(_decode(expected))

    # host read shards compose with server shards: union of the host
    # shards == everything, each filtered consistently
    host_shards = [
        store.find_columnar(1, value_property="rating", time_ordered=False,
                            shard_index=h, shard_count=2)
        for h in range(2)
    ]
    assert sum(len(s) for s in host_shards) == len(expected)
    for h, s in enumerate(host_shards):
        for ent in s.entity_vocab:
            assert stable_hash(ent) % 2 == h


def test_columnar_limit_respects_reversed_across_shards(two_servers):
    """limit + reversed must keep the global NEWEST rows (find's
    order-then-truncate contract), not the head of the ascending merge
    (code-review regression)."""
    _, _, client = two_servers
    store = client.events()
    store.init(1)
    events = _events(n=40)
    store.insert_batch(events, 1)

    got = store.find_columnar(1, time_ordered=True, limit=7, reversed=True)
    newest = sorted((e.event_time for e in events), reverse=True)[:7]
    assert [int(t.timestamp() * 1e6) for t in newest] == list(got.times_us)

    got2 = store.find_columnar(1, time_ordered=True, limit=7)
    oldest = sorted(int(e.event_time.timestamp() * 1e6) for e in events)[:7]
    assert oldest == list(got2.times_us)


def test_columnar_bulk_ingest_shards(two_servers):
    backends, _, client = two_servers
    store = client.events()
    store.init(1)
    oracle = _memory_storage()
    oracle.events().init(1)
    oracle.events().insert_batch(_events(), 1)
    cols = oracle.events().find_columnar(1, value_property="rating",
                                         time_ordered=False)

    n = store.insert_columnar(cols, 1, entity_type="user",
                              target_entity_type="item",
                              value_property="rating")
    assert n == len(cols)
    per_server = [len(b.events().find(1)) for b in backends]
    assert all(c > 0 for c in per_server) and sum(per_server) == n
    back = store.find_columnar(1, value_property="rating",
                               time_ordered=False)
    assert sorted(_decode(back)) == sorted(_decode(cols))


def test_point_ops_across_shards(two_servers):
    _, _, client = two_servers
    store = client.events()
    store.init(1)
    events = _events(n=10)
    ids = store.insert_batch(events, 1)
    for eid, ev in zip(ids, events):
        got = store.get(eid, 1)
        assert got is not None and got.entity_id == ev.entity_id
    assert store.get("nonexistent", 1) is None
    assert store.delete(ids[0], 1) is True
    assert store.get(ids[0], 1) is None
    assert store.delete(ids[0], 1) is False


def test_down_shard_fails_loudly_naming_it(two_servers):
    backends, servers, client = two_servers
    store = client.events()
    store.init(1)
    store.insert_batch(_events(n=20), 1)

    dead_url = f"http://127.0.0.1:{servers[1].port}"
    servers[1].stop()

    with pytest.raises(StorageUnavailableError) as ei:
        store.find(1)
    assert dead_url in str(ei.value)
    with pytest.raises(StorageUnavailableError) as ei:
        store.find_columnar(1, time_ordered=False)
    assert dead_url in str(ei.value)

    # per-shard health names the down endpoint; repo health fails
    details = client.health_details()
    ev = details["EVENTDATA"]
    assert ev[f"http://127.0.0.1:{servers[0].port}"] is True
    assert ev[dead_url] is False
    assert client.verify_all_data_objects()["EVENTDATA"] is False


@pytest.fixture()
def three_servers_r2():
    """Three storage servers, REPLICAS=2: shard k lives on servers k and
    k+1 (mod 3) — any ONE server can die and reads stay complete."""
    backends = [_memory_storage() for _ in range(3)]
    servers = [
        StorageServer(storage=b, host="127.0.0.1", port=0).start()
        for b in backends
    ]
    try:
        yield backends, servers, _client([s.port for s in servers],
                                         replicas=2)
    finally:
        for s in servers:
            s.stop()


def test_replicated_writes_land_on_every_replica(three_servers_r2):
    backends, _, client = three_servers_r2
    store = client.events()
    store.init(1)
    events = _events(n=60)
    ids = store.insert_batch(events, 1)
    assert len(set(ids)) == len(events)

    # every row exists on exactly 2 of the 3 servers, same id on both
    per_server = [
        {e.event_id for e in b.events().find(1)} for b in backends
    ]
    assert sum(len(p) for p in per_server) == 2 * len(events)
    for eid, ev in zip(ids, events):
        holders = [s for s, p in enumerate(per_server) if eid in p]
        shard = stable_hash(ev.entity_id) % 3
        assert holders == sorted({shard, (shard + 1) % 3})

    # reads with all servers up: no duplicates
    assert len(store.find(1)) == len(events)
    cols = store.find_columnar(1, time_ordered=False)
    assert len(cols) == len(events)

    # delete removes every copy
    assert store.delete(ids[0], 1) is True
    assert all(ids[0] not in {e.event_id for e in b.events().find(1)}
               for b in backends)


def test_replicated_reads_survive_one_server_down(three_servers_r2):
    backends, servers, client = three_servers_r2
    store = client.events()
    store.init(1)
    events = _events(n=60)
    store.insert_batch(events, 1)
    oracle_rows = sorted(
        (e.entity_id, e.target_entity_id, e.event_time) for e in events)

    servers[1].stop()  # kill one replica; every shard still has a copy

    merged = store.find(1)
    assert sorted((e.entity_id, e.target_entity_id, e.event_time)
                  for e in merged) == oracle_rows
    cols = store.find_columnar(1, value_property="rating",
                               time_ordered=False)
    assert len(cols) == len(events)

    # limit + reversed still the global newest
    newest = store.find_columnar(1, time_ordered=True, limit=5,
                                 reversed=True)
    exp = sorted((e.event_time for e in events), reverse=True)[:5]
    assert [int(t.timestamp() * 1e6) for t in exp] == list(newest.times_us)

    # host read shards compose (client-side under replication)
    host_shards = [
        store.find_columnar(1, time_ordered=False, shard_index=h,
                            shard_count=2)
        for h in range(2)
    ]
    assert sum(len(s) for s in host_shards) == len(events)

    # point reads still answer from the surviving copy
    eid = merged[0].event_id
    assert store.get(eid, 1) is not None


def test_find_placement_filter_on_wire(two_servers):
    """The row find wire's placement filter: a server holding several
    shards' copies sends only the requested shards' rows, limit applied
    after the filter (code-review regression)."""
    backends, servers, _ = two_servers
    backends[0].events().init(1)
    backends[0].events().insert_batch(_events(n=40), 1)

    from predictionio_tpu.data.backends.rest import RestEventStore, _Transport

    st = RestEventStore(
        _Transport(f"http://127.0.0.1:{servers[0].port}", None, 10))
    full = st.find(1)
    only0 = st.find(1, placement_shards=[0], placement_count=2)
    assert 0 < len(only0) < len(full)
    assert all(stable_hash(e.entity_id) % 2 == 0 for e in only0)
    # limit applies AFTER the placement filter
    lim = st.find(1, placement_shards=[0], placement_count=2, limit=3)
    assert [e.event_id for e in lim] == [e.event_id for e in only0[:3]]


def test_multi_shard_batch_rolls_back_all_groups():
    """A failed multi-shard replicated batch must roll back EVERY shard
    group it committed, not just the failing one — a retry with fresh
    ids would otherwise duplicate the committed group's rows
    (code-review regression)."""
    backends = [_memory_storage(), _memory_storage()]
    servers = [
        StorageServer(storage=b, host="127.0.0.1", port=0).start()
        for b in backends
    ]
    try:
        client = _client([s.port for s in servers], replicas=2)
        store = client.events()
        store.init(1)
        # events spanning BOTH shards
        batch = _events(n=20)
        assert len({stable_hash(e.entity_id) % 2 for e in batch}) == 2
        servers[0].stop()
        with pytest.raises(StorageUnavailableError):
            store.insert_batch(batch, 1)
        # whichever shard group committed to the live server first was
        # rolled back when the dead server failed the other group
        assert backends[1].events().find(1) == []
    finally:
        for s in servers:
            s.stop()


def test_partial_replica_write_rolls_back():
    """A replica write that fails midway must not leave a copy that
    reads would serve: the already-written copies are deleted by their
    client-stamped ids (code-review regression)."""
    backends = [_memory_storage(), _memory_storage()]
    servers = [
        StorageServer(storage=b, host="127.0.0.1", port=0).start()
        for b in backends
    ]
    try:
        client = _client([s.port for s in servers], replicas=2)
        store = client.events()
        store.init(1)

        def uid_for_shard(s):
            i = 0
            while stable_hash(f"user_{i}") % 2 != s:
                i += 1
            return f"user_{i}"

        servers[0].stop()
        ev = _events(n=1)[0]

        # owner = dead server 0: the successor (server 1) is written
        # first, the owner write fails, and the rollback removes the
        # successor's copy — the live server serves nothing
        ev_owner_dead = dataclasses.replace(ev, entity_id=uid_for_shard(0))
        with pytest.raises(StorageUnavailableError):
            store.insert(ev_owner_dead, 1)
        assert backends[1].events().find(1) == []

        # owner = live server 1: its successor (server 0) is written
        # FIRST and is dead, so nothing lands anywhere
        ev_successor_dead = dataclasses.replace(
            ev, entity_id=uid_for_shard(1))
        with pytest.raises(StorageUnavailableError):
            store.insert(ev_successor_dead, 1)
        assert backends[1].events().find(1) == []

        # batch path rolls back too
        batch = [dataclasses.replace(e, entity_id=uid_for_shard(0))
                 for e in _events(n=5)]
        with pytest.raises(StorageUnavailableError):
            store.insert_batch(batch, 1)
        assert backends[1].events().find(1) == []
    finally:
        for s in servers:
            s.stop()


def test_repair_reconciles_diverged_replicas(three_servers_r2):
    """Owner-authoritative anti-entropy: after repair, every replica
    holds exactly its shards' owner rows — rollback leftovers and
    divergent copies are reconciled (the HDFS block-repair role)."""
    backends, _, client = three_servers_r2
    store = client.events()
    store.init(1)
    events = _events(n=45)
    store.insert_batch(events, 1)

    # diverge by hand: drop one REPLICA copy (server 1 replicates shard
    # 0 — deleting an owner copy would be authoritative, not
    # divergence), plant an orphan on another replica (the states
    # partial failures leave behind)
    victim = next(e for e in backends[1].events().find(1)
                  if stable_hash(e.entity_id) % 3 == 0)
    backends[1].events().delete(victim.event_id, 1)
    orphan_shard = next(s for s in range(3)
                        if stable_hash("orphan_u") % 3 == s)
    replica_of_orphan = (orphan_shard + 1) % 3
    backends[replica_of_orphan].events().insert(
        dataclasses.replace(events[0], entity_id="orphan_u"), 1)

    stats = store.repair(1)
    assert stats["copied"] >= 1 and stats["deleted"] >= 1

    # post-repair invariant: each server holds exactly the owner rows
    # of the shards it replicates
    for srv, b in enumerate(backends):
        rows = b.events().find(1)
        my_shards = {srv, (srv - 1) % 3}
        expected = {
            e.event_id for e in store.find(1)
            if stable_hash(e.entity_id) % 3 in my_shards
        }
        assert {e.event_id for e in rows} == expected
    # merged reads are clean and complete (no orphan, nothing missing)
    merged = store.find(1)
    assert len(merged) == len(events)
    assert all(e.entity_id != "orphan_u" for e in merged)


def test_repair_recognizes_columnar_ingested_copies(three_servers_r2):
    """Columnar-ingested replicas carry per-server ids; repair must
    match them by CONTENT and leave them alone, not rewrite every
    replica (code-review regression)."""
    _, _, client = three_servers_r2
    store = client.events()
    store.init(1)
    oracle = _memory_storage()
    oracle.events().init(1)
    oracle.events().insert_batch(_events(n=45), 1)
    cols = oracle.events().find_columnar(1, value_property="rating",
                                         time_ordered=False)
    store.insert_columnar(cols, 1, entity_type="user",
                          target_entity_type="item",
                          value_property="rating")
    stats = store.repair(1)
    assert stats == {"copied": 0, "deleted": 0}, stats
    assert len(store.find(1)) == 45


def test_repair_cli_refuses_unreplicated_backend(two_servers, memory_storage):
    """`pio storagerepair` must fail loudly when there is nothing to
    check — a zeros result would read as "consistent"."""
    from predictionio_tpu.data.storage import StorageError
    from predictionio_tpu.tools.commands import CommandError, repair_events

    # sharded but unreplicated: repair() itself owns the guard
    _, _, client = two_servers
    client.apps().insert("shapp2")
    with pytest.raises(StorageError):
        repair_events("shapp2", storage=client)
    # plain unsharded backend: no repair surface at all
    memory_storage.apps().insert("plain")
    with pytest.raises(CommandError):
        repair_events("plain", storage=memory_storage)


def test_replicas_exceeding_servers_rejected():
    from predictionio_tpu.data.storage import StorageError

    with pytest.raises(StorageError):
        _client([7001, 7002], replicas=3)
    with pytest.raises(StorageError):
        _client([7001], replicas=2)


def test_event_server_ingests_to_sharded_tier(two_servers):
    """Live traffic through the whole stack: HTTP POST /events.json on
    the Event Server, whose storage is the sharded rest client — rows
    hash-route across both storage servers, GET round-trips through
    the fan-out read path. (SDK -> event server -> sharded store, the
    reference's SDK -> EventAPI -> HBase regions pipeline, §3.3.)"""
    import json as _json
    import urllib.request

    from predictionio_tpu.data.metadata import AccessKey
    from predictionio_tpu.serving.event_server import EventServer

    backends, _, client = two_servers
    app = client.apps().insert("live-app")
    client.events().init(app.id)
    key = AccessKey.generate(app.id)
    client.access_keys().insert(key)
    es = EventServer(storage=client, host="127.0.0.1", port=0).start()
    try:
        base = f"http://127.0.0.1:{es.port}"
        ids = []
        for i in range(12):
            req = urllib.request.Request(
                f"{base}/events.json?accessKey={key.key}",
                data=_json.dumps({
                    "event": "rate", "entityType": "user",
                    "entityId": f"user_{i}", "targetEntityType": "item",
                    "targetEntityId": f"item_{i % 3}",
                    "properties": {"rating": float(1 + i % 5)},
                    "eventTime": "2026-03-01T00:00:00.000Z",
                }).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 201
                ids.append(_json.loads(resp.read())["eventId"])
        # rows hash-routed across BOTH storage servers
        per_server = [b.events().find(app.id) for b in backends]
        assert all(len(p) > 0 for p in per_server)
        assert sum(len(p) for p in per_server) == 12
        for s, part in enumerate(per_server):
            for e in part:
                assert stable_hash(e.entity_id) % 2 == s
        # GET round-trips through the fan-out read path
        with urllib.request.urlopen(
            f"{base}/events/{ids[0]}.json?accessKey={key.key}"
        ) as resp:
            got = _json.loads(resp.read())
        assert got["entityId"] == "user_0"
    finally:
        es.stop()


def test_cli_compact_handles_per_shard_stats(two_servers, capsys):
    """`pio app compact` on a sharded source gets a LIST of per-shard
    stats and must print them instead of crashing (code-review
    regression)."""
    from predictionio_tpu.data.storage import set_storage
    from predictionio_tpu.tools.cli import main as cli_main

    _, _, client = two_servers
    try:
        set_storage(client)
        assert cli_main(["app", "new", "compactapp"]) == 0
        capsys.readouterr()
        # the regression was a TypeError on the list-of-stats return;
        # memory shards compact in place -> the collapsed no-op line
        assert cli_main(["app", "compact", "compactapp"]) == 0
        out = capsys.readouterr().out
        assert "nothing to compact" in out

        # a stats-returning sharded store prints one line per shard
        from predictionio_tpu.tools import cli as cli_mod

        class FakeShardedStore:
            def compact(self, app_id, channel_id=None):
                return [{"dropped": 1, "before_bytes": 10, "after_bytes": 5},
                        None]

        class FakeStorage:
            def events(self):
                return FakeShardedStore()

            def __getattr__(self, name):
                return getattr(client, name)

        set_storage(FakeStorage())  # type: ignore[arg-type]
        assert cli_main(["app", "compact", "compactapp"]) == 0
        out = capsys.readouterr().out
        assert "shard 0: Compacted: dropped 1" in out
        assert "shard 1: stores events in place" in out
    finally:
        set_storage(None)


def test_scan_ttl_slides_with_fetch_progress(memory_storage):
    """A resumed transfer must never die to the absolute scan TTL while
    it is making progress (code-review regression)."""
    import time as _time

    from predictionio_tpu.serving.storage_server import _ScanRegistry

    # generous margins: the sleeps stay well under the ttl so ordinary
    # CI scheduling delay cannot reap between a sleep and the assert
    reg = _ScanRegistry(ttl=2.0)
    scan = reg.create(lambda f: f.write(b"x" * 64))
    _time.sleep(1.2)
    assert reg.path_for(scan["scan_id"]) is not None  # refreshes the TTL
    _time.sleep(1.2)
    # absolute age (2.4s) > ttl, but the access above slid the window
    assert reg.path_for(scan["scan_id"]) is not None
    _time.sleep(2.5)  # idle past the ttl: reaped
    assert reg.path_for(scan["scan_id"]) is None
    reg.close()


def test_keepalive_connection_survives_streaming_then_bad_route(two_servers):
    """After a streamed NDJSON find on a keep-alive connection, the
    NEXT request's body must still be drained before answering — a
    stale body would desynchronize the connection (code-review
    regression)."""
    import http.client as _hc
    import json as _json

    _, servers, client = two_servers
    store = client.events()
    store.init(1)
    store.insert_batch(_events(n=6), 1)

    conn = _hc.HTTPConnection("127.0.0.1", servers[0].port, timeout=10)
    try:
        # 1. streamed NDJSON response (bypasses _send)
        conn.request("POST", "/storage/events/find",
                     _json.dumps({"app_id": 1}).encode(),
                     {"Content-Type": "application/json"})
        r1 = conn.getresponse()
        lines = [l for l in r1.read().split(b"\n") if l]
        assert len(lines) > 0
        # 2. unknown events method WITH a body -> short-circuit 404
        conn.request("POST", "/storage/events/bogus",
                     _json.dumps({"app_id": 1, "junk": "x" * 200}).encode(),
                     {"Content-Type": "application/json"})
        r2 = conn.getresponse()
        assert r2.status == 404
        r2.read()
        # 3. the SAME connection must still parse a clean request
        conn.request("GET", "/storage/stats")
        r3 = conn.getresponse()
        assert r3.status == 200
        assert "columnar_scan_count" in _json.loads(r3.read())
    finally:
        conn.close()


def test_metadata_and_models_pin_to_first_shard(two_servers):
    backends, _, client = two_servers
    app = client.apps().insert("shapp")
    assert backends[0].apps().get_by_name("shapp") is not None
    assert backends[1].apps().get_by_name("shapp") is None
    from predictionio_tpu.data.metadata import Model

    client.models().insert(Model(id="m1", models=b"\x00\x01"))
    assert backends[0].models().get("m1") is not None
    assert backends[1].models().get("m1") is None
    assert client.apps().get(app.id).name == "shapp"
